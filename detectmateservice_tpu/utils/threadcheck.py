"""Runtime twin of the DM-A static thread-affinity analyzer.

The static analyzer proves what it can from the AST; this module audits the
same contract dynamically: a seam declared ``# dmlint: thread(engine)``
also calls :func:`assert_affinity` (``"engine"``), which — **only** when
``DM_THREADCHECK=1`` (tests arm it in ``tests/conftest.py``) — verifies the
calling thread actually belongs to that domain. Disarmed, the whole cost is
one module-global bool check, cheap enough for the engine hot path.

A thread's domain comes from, in order:

* an explicit :func:`bind_thread` call (the loop entry points bind
  themselves — the authoritative source), or
* its ``threading.Thread`` name via :data:`NAME_DOMAINS` (``EngineLoop`` →
  ``engine``, ``ReplicaSupervisor`` → ``supervisor``, …), so the
  production thread topology is covered with zero per-loop code.

A thread with **no** domain (pytest's MainThread, an ad-hoc helper) passes
every assert: the contract constrains the known production threads, not
test harnesses driving seams directly — that is what keeps the whole suite
green under ``DM_THREADCHECK=1`` while a supervisor thread calling an
engine-owned spool method still trips the assert immediately.

Dependency-free on purpose (the WAL spool imports this inside non-jax
parser stages).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

__all__ = ["ThreadAffinityError", "assert_affinity", "bind_thread",
           "unbind_thread", "current_domain", "arm", "armed"]

# thread-name prefix → domain: the production topology's spawned threads
NAME_DOMAINS = {
    "EngineLoop": "engine",
    "ReplicaSupervisor": "supervisor",
    "HealthWatchdog": "watchdog",
    "ModelRollout": "rollout",
    "loadgen-sender": "loadgen",
    "loadgen-collector": "loadgen",
    "WebServerThread": "admin",
}

_ARMED = os.environ.get("DM_THREADCHECK", "") == "1"
_LOCK = threading.Lock()
_BINDINGS: Dict[int, str] = {}      # thread ident → bound domain


class ThreadAffinityError(AssertionError):
    """A thread crossed a declared affinity seam (only ever raised while
    armed — production runs never pay or see this)."""


def arm(enabled: bool = True) -> None:
    """Programmatic arm/disarm (tests use this; production uses the env)."""
    global _ARMED
    _ARMED = enabled


def armed() -> bool:
    return _ARMED


def bind_thread(domain: str, ident: Optional[int] = None) -> None:
    """Declare the current (or given) thread a member of ``domain`` —
    authoritative over the name map. No-op overhead concerns: binding
    happens once per thread lifetime, not per iteration."""
    key = ident if ident is not None else threading.get_ident()
    with _LOCK:
        _BINDINGS[key] = domain


def unbind_thread(ident: Optional[int] = None) -> None:
    key = ident if ident is not None else threading.get_ident()
    with _LOCK:
        _BINDINGS.pop(key, None)


def current_domain() -> Optional[str]:
    """The calling thread's domain: explicit binding first, then the
    thread-name map, else None (unclassified — passes every assert)."""
    ident = threading.get_ident()
    with _LOCK:
        bound = _BINDINGS.get(ident)
    if bound is not None:
        return bound
    name = threading.current_thread().name
    for prefix, domain in NAME_DOMAINS.items():
        if name.startswith(prefix):
            return domain
    return None


def assert_affinity(domain: str) -> None:
    """Assert the calling thread belongs to ``domain``. A no-op unless
    armed (``DM_THREADCHECK=1`` or :func:`arm`); unclassified threads and
    ``"any"`` seams always pass."""
    if not _ARMED or domain == "any":
        return
    actual = current_domain()
    if actual is None or actual == domain:
        return
    raise ThreadAffinityError(
        f"thread {threading.current_thread().name!r} (domain {actual}) "
        f"crossed a seam owned by the {domain} thread — the static "
        "declaration (# dmlint: thread(...)) and the runtime are out of "
        "agreement")
