"""Orbax-backed checkpoint/restore for scorer params + detector state.

Closes the reference's checkpoint gap (SURVEY.md §5.4: detector state is
in-memory only there; "add real model-state checkpoint (orbax-style)").

Crash atomicity (PR 10): a save used to overwrite ``params/`` and
``opt_state/`` in place and then rewrite ``meta.json`` — a crash between
those steps left a *valid-looking* meta pointing at half-written param
trees, which ``load_scorer_state`` would trust. Saves now write the array
trees into fresh nonce-named directories and COMMIT by atomically replacing
``meta.json`` (temp file + fsync + ``os.replace`` + directory fsync); the
meta names the nonce it belongs to (``data_nonce``), so the loader can only
ever see a fully-written generation. A crash mid-save leaves the previous
generation untouched and at most some orphaned nonce directories, which the
next successful save prunes. Legacy checkpoints (no ``data_nonce``) keep
loading from the bare ``params``/``opt_state`` names.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Dict, Tuple

import orbax.checkpoint as ocp

_META = "meta.json"

# Param-tree layout versions, stamped into every checkpoint's meta.json and
# checked on restore — PER MODEL FAMILY, because a layout bump in one family
# must not reject still-compatible checkpoints of another. v2 = the
# compact→setup() restructure (renamed block_i→blocks_i, LayerNorm_0→
# final_ln, rnn_i/gru_i→rnns_i/cell); mlp was untouched, so BOTH v1 and v2
# stamps restore for mlp (one intermediate build stamped a global v2 on
# every family). A mismatch fails with a clear message instead of orbax's
# opaque missing-key error.
MODEL_TREE_VERSIONS = {"mlp": 1, "gru": 2, "logbert": 2}
COMPATIBLE_TREE_VERSIONS = {"mlp": {1, 2}, "gru": {2}, "logbert": {2}}


class CheckpointFormatError(RuntimeError):
    """Checkpoint param-tree layout does not match this build."""

# orbax's in-process save machinery (async manager, tensorstore context,
# per-process metadata) is not safe under concurrent saves from multiple
# threads EVEN to distinct directories (observed: "No ArrayMetadata found
# for process_index=0 in ... .orbax-checkpoint-tmp" under a checkpoint
# stress test). Saves are rare control-plane ops; serializing them costs
# nothing and makes concurrent external checkpoint callers safe.
_SAVE_LOCK = threading.Lock()

# ONE process-lifetime checkpointer, never closed: a `with
# ocp.StandardCheckpointer()` per save closes orbax's shared
# checkpoint-metadata executor on exit, and under rapid save sequences the
# NEXT save then dies with "cannot schedule new futures after shutdown" /
# "Must provide item to save" (observed once under a loaded parallel test
# run). orbax installs its own atexit hooks for process teardown.
_CKPTR = None


def _checkpointer() -> "ocp.StandardCheckpointer":
    global _CKPTR
    if _CKPTR is None:
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


# the commit primitive moved to utils/atomicio.py (dependency-free) so the
# WAL spool can use it inside non-jax stages; re-exported here for the
# existing checkpoint/rollout callers and the tests that monkeypatch it
from .atomicio import write_json_atomic  # noqa: F401  (re-export)


def _prune_stale_data(path: Path, keep_nonce: str) -> None:
    """Remove data generations other than ``keep_nonce``: older nonce dirs,
    orphans from crashed saves, and the legacy bare ``params``/``opt_state``
    layout (safe only AFTER the meta commit landed)."""
    for entry in path.iterdir():
        name = entry.name
        if name in ("params", "opt_state") or (
                (name.startswith("params.") or name.startswith("opt_state."))
                and not name.endswith(keep_nonce)):
            shutil.rmtree(entry, ignore_errors=True)


def save_scorer_state(directory: str, params: Any, opt_state: Any,
                      meta: Dict[str, Any], tree_version: int = 1) -> None:
    path = Path(directory).absolute()
    path.mkdir(parents=True, exist_ok=True)
    # fresh generation per save: the previous one stays intact and trusted
    # until the meta commit below atomically retargets the loader
    nonce = f"{os.getpid()}-{time.time_ns():x}"
    with _SAVE_LOCK:
        ckptr = _checkpointer()
        ckptr.save(path / f"params.{nonce}", params, force=True)
        ckptr.save(path / f"opt_state.{nonce}", opt_state, force=True)
        ckptr.wait_until_finished()
    write_json_atomic(path / _META, {**meta, "tree_version": tree_version,
                                     "data_nonce": nonce})
    _prune_stale_data(path, keep_nonce=nonce)


def load_scorer_state(directory: str, params_template: Any,
                      opt_state_template: Any,
                      accepted_tree_versions=frozenset({1}),
                      ) -> Tuple[Any, Any, Dict[str, Any]]:
    path = Path(directory).absolute()
    # meta first: a tree-version mismatch must produce an actionable error,
    # not orbax's missing-key traceback halfway through the restore
    meta = json.loads((path / _META).read_text())
    found = meta.get("tree_version", 1)
    if found not in accepted_tree_versions:
        raise CheckpointFormatError(
            f"checkpoint at {path} has param-tree version {found}, this "
            f"build accepts {sorted(accepted_tree_versions)} for this model "
            "family; the flax module layout changed (param paths were "
            "renamed), so this checkpoint cannot be restored directly — "
            "refit the scorer, or migrate the checkpoint by renaming its "
            "param keys to the new layout")
    # the meta names the data generation it was committed with; absent =
    # a pre-PR-10 checkpoint in the bare-name layout
    nonce = meta.get("data_nonce")
    params_dir = path / (f"params.{nonce}" if nonce else "params")
    opt_dir = path / (f"opt_state.{nonce}" if nonce else "opt_state")
    with _SAVE_LOCK:  # share the serialized singleton with the save path
        ckptr = _checkpointer()
        params = ckptr.restore(params_dir, params_template)
        opt_state = ckptr.restore(opt_dir, opt_state_template)
    return params, opt_state, meta
