"""Orbax-backed checkpoint/restore for scorer params + detector state.

Closes the reference's checkpoint gap (SURVEY.md §5.4: detector state is
in-memory only there; "add real model-state checkpoint (orbax-style)").
"""
from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Any, Dict, Tuple

import orbax.checkpoint as ocp

_META = "meta.json"

# Param-tree layout versions, stamped into every checkpoint's meta.json and
# checked on restore — PER MODEL FAMILY, because a layout bump in one family
# must not reject still-compatible checkpoints of another. v2 = the
# compact→setup() restructure (renamed block_i→blocks_i, LayerNorm_0→
# final_ln, rnn_i/gru_i→rnns_i/cell); mlp was untouched, so BOTH v1 and v2
# stamps restore for mlp (one intermediate build stamped a global v2 on
# every family). A mismatch fails with a clear message instead of orbax's
# opaque missing-key error.
MODEL_TREE_VERSIONS = {"mlp": 1, "gru": 2, "logbert": 2}
COMPATIBLE_TREE_VERSIONS = {"mlp": {1, 2}, "gru": {2}, "logbert": {2}}


class CheckpointFormatError(RuntimeError):
    """Checkpoint param-tree layout does not match this build."""

# orbax's in-process save machinery (async manager, tensorstore context,
# per-process metadata) is not safe under concurrent saves from multiple
# threads EVEN to distinct directories (observed: "No ArrayMetadata found
# for process_index=0 in ... .orbax-checkpoint-tmp" under a checkpoint
# stress test). Saves are rare control-plane ops; serializing them costs
# nothing and makes concurrent external checkpoint callers safe.
_SAVE_LOCK = threading.Lock()

# ONE process-lifetime checkpointer, never closed: a `with
# ocp.StandardCheckpointer()` per save closes orbax's shared
# checkpoint-metadata executor on exit, and under rapid save sequences the
# NEXT save then dies with "cannot schedule new futures after shutdown" /
# "Must provide item to save" (observed once under a loaded parallel test
# run). orbax installs its own atexit hooks for process teardown.
_CKPTR = None


def _checkpointer() -> "ocp.StandardCheckpointer":
    global _CKPTR
    if _CKPTR is None:
        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def save_scorer_state(directory: str, params: Any, opt_state: Any,
                      meta: Dict[str, Any], tree_version: int = 1) -> None:
    path = Path(directory).absolute()
    path.mkdir(parents=True, exist_ok=True)
    with _SAVE_LOCK:
        ckptr = _checkpointer()
        ckptr.save(path / "params", params, force=True)
        ckptr.save(path / "opt_state", opt_state, force=True)
        ckptr.wait_until_finished()
    (path / _META).write_text(json.dumps({**meta, "tree_version": tree_version}))


def load_scorer_state(directory: str, params_template: Any,
                      opt_state_template: Any,
                      accepted_tree_versions=frozenset({1}),
                      ) -> Tuple[Any, Any, Dict[str, Any]]:
    path = Path(directory).absolute()
    # meta first: a tree-version mismatch must produce an actionable error,
    # not orbax's missing-key traceback halfway through the restore
    meta = json.loads((path / _META).read_text())
    found = meta.get("tree_version", 1)
    if found not in accepted_tree_versions:
        raise CheckpointFormatError(
            f"checkpoint at {path} has param-tree version {found}, this "
            f"build accepts {sorted(accepted_tree_versions)} for this model "
            "family; the flax module layout changed (param paths were "
            "renamed), so this checkpoint cannot be restored directly — "
            "refit the scorer, or migrate the checkpoint by renaming its "
            "param keys to the new layout")
    with _SAVE_LOCK:  # share the serialized singleton with the save path
        ckptr = _checkpointer()
        params = ckptr.restore(path / "params", params_template)
        opt_state = ckptr.restore(path / "opt_state", opt_state_template)
    return params, opt_state, meta
