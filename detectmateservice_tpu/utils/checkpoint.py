"""Orbax-backed checkpoint/restore for scorer params + detector state.

Closes the reference's checkpoint gap (SURVEY.md §5.4: detector state is
in-memory only there; "add real model-state checkpoint (orbax-style)").
"""
from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Any, Dict, Tuple

import orbax.checkpoint as ocp

_META = "meta.json"

# orbax's in-process save machinery (async manager, tensorstore context,
# per-process metadata) is not safe under concurrent saves from multiple
# threads EVEN to distinct directories (observed: "No ArrayMetadata found
# for process_index=0 in ... .orbax-checkpoint-tmp" under a checkpoint
# stress test). Saves are rare control-plane ops; serializing them costs
# nothing and makes concurrent external checkpoint callers safe.
_SAVE_LOCK = threading.Lock()


def save_scorer_state(directory: str, params: Any, opt_state: Any,
                      meta: Dict[str, Any]) -> None:
    path = Path(directory).absolute()
    path.mkdir(parents=True, exist_ok=True)
    with _SAVE_LOCK:
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path / "params", params, force=True)
            ckptr.save(path / "opt_state", opt_state, force=True)
    (path / _META).write_text(json.dumps(meta))


def load_scorer_state(directory: str, params_template: Any,
                      opt_state_template: Any) -> Tuple[Any, Any, Dict[str, Any]]:
    path = Path(directory).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(path / "params", params_template)
        opt_state = ckptr.restore(path / "opt_state", opt_state_template)
    meta = json.loads((path / _META).read_text())
    return params, opt_state, meta
