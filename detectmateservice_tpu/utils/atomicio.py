"""Crash-atomic filesystem primitives (dependency-free).

Extracted from ``utils/checkpoint.py`` so subsystems that must stay
importable on non-jax stages (the WAL ingress spool runs inside parser
processes) can share the proven temp+fsync+rename commit pattern without
pulling the orbax/jax import chain. ``utils.checkpoint`` re-exports
``write_json_atomic`` for its existing callers.
"""
from __future__ import annotations

import errno
import json
import os
from pathlib import Path
from typing import Any, Dict

from .. import faults


def fsync_dir(directory: Path) -> None:
    """fsync a directory so a just-created/renamed/removed entry survives a
    power loss (the rename itself is atomic; its *durability* needs this)."""
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_json_atomic(path: Path, doc: Dict[str, Any]) -> None:
    """Durably replace ``path`` with ``doc``: write a temp sibling, fsync
    it, ``os.replace`` onto the final name, fsync the directory. The
    replace is the commit point — a reader (or a post-crash restart) sees
    either the old document or the new one, never a torn write. Shared by
    the checkpoint meta commit, the rollout store's manifest, and the WAL
    spool's manifest."""
    path = Path(path)
    tmp = path.with_name(f".{path.name}.tmp.{os.getpid()}")
    data = json.dumps(doc, indent=0, sort_keys=True)
    # fs_commit fault site: eio raises before any byte is written; torn
    # writes the temp sibling then aborts before os.replace — exactly the
    # crash window the commit pattern must survive (the old document stays)
    torn = False
    inj = faults._ACTIVE
    if inj is not None:
        torn = inj.fs("fs_commit")
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    if torn:
        raise OSError(errno.EIO,
                      f"injected torn commit: {tmp.name} written, "
                      f"rename to {path.name} aborted")
    os.replace(tmp, path)
    fsync_dir(path.parent)
