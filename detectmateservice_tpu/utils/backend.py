"""Lazy jax platform pinning.

``ServiceSettings.backend`` ("auto" | "cpu" | "tpu") selects the accelerator
platform, but importing jax costs seconds of cold-start and hundreds of MB of
RSS — a parser or reader service must never pay that. So the Service records
the request here without importing jax, and jax-using components (the scorer's
``_ensure_scorer``) apply it right before their first jax op.

The env var route (``JAX_PLATFORMS``) is not enough on images whose
sitecustomize force-registers an accelerator platform for every interpreter;
``jax.config.update("jax_platforms", ...)`` before backend initialization is
the reliable override.
"""
from __future__ import annotations

from typing import Optional

_requested: Optional[str] = None


def request_platform(name: Optional[str]) -> None:
    """Record the platform choice (no jax import). "auto"/None = leave as-is."""
    global _requested
    if name in ("cpu", "tpu"):
        _requested = name


def apply_platform_pin(logger=None) -> None:
    """Pin jax to the requested platform; call before the first jax op."""
    global _requested
    if _requested is None:
        return
    name, _requested = _requested, None
    try:
        import jax

        jax.config.update("jax_platforms", name)
    except Exception as exc:  # backend already initialized elsewhere
        if logger is not None:
            logger.warning("cannot pin jax platform %r: %s", name, exc)
