"""Package metadata (role of the reference's src/service/metadata.py:10)."""

NAME = "detectmateservice-tpu"
VERSION = "0.5.0"
