"""``detectmate-client`` CLI: drive a service's admin HTTP API.

Parity with the reference client (reference: src/service/client.py:27-120):
subcommands ``start`` / ``stop`` / ``status`` / ``metrics`` /
``reconfigure [--persist]`` against ``--url``, plus the TPU-build additions
``checkpoint`` (save component state to the service's checkpoint_dir) and
``trace [--chrome] [-o FILE]`` (read the pipeline flight recorder; --chrome
fetches a Perfetto-loadable trace-event document).
Uses stdlib urllib — no extra dependencies.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request
from typing import Any, List, Optional

import yaml


class DetectMateClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> Any:
        body = None if payload is None else json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            self.url + path,
            data=body,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            if "json" in ctype:
                return json.loads(raw)
            return raw.decode("utf-8", errors="replace")

    def start(self) -> Any:
        return self._request("POST", "/admin/start")

    def stop(self) -> Any:
        return self._request("POST", "/admin/stop")

    def shutdown(self) -> Any:
        return self._request("POST", "/admin/shutdown")

    def status(self) -> Any:
        return self._request("GET", "/admin/status")

    def metrics(self) -> str:
        return self._request("GET", "/metrics")

    def reconfigure(self, config: dict, persist: bool = False) -> Any:
        return self._request(
            "POST", "/admin/reconfigure", {"config": config, "persist": persist}
        )

    def checkpoint(self) -> Any:
        """Save component state to the service's checkpoint_dir now."""
        return self._request("POST", "/admin/checkpoint")

    def trace(self, chrome: bool = False) -> Any:
        """Read the pipeline flight recorder (slowest + sampled traces);
        ``chrome=True`` returns a Perfetto-loadable trace-event document."""
        suffix = "?format=chrome" if chrome else ""
        return self._request("GET", "/admin/trace" + suffix)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="detectmate-client", description="Admin client for DetectMate TPU services"
    )
    parser.add_argument("--url", default="http://127.0.0.1:8000", help="service admin URL")
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("start")
    sub.add_parser("stop")
    sub.add_parser("shutdown")
    sub.add_parser("status")
    sub.add_parser("metrics")
    sub.add_parser("checkpoint")
    trace = sub.add_parser(
        "trace", help="read the pipeline flight recorder (/admin/trace)")
    trace.add_argument("--chrome", action="store_true",
                       help="fetch Chrome trace-event JSON (Perfetto-loadable)")
    trace.add_argument("-o", "--out",
                       help="write the result to a file instead of stdout")
    reconf = sub.add_parser("reconfigure")
    reconf.add_argument("config_file", help="YAML file with the new component config")
    reconf.add_argument("--persist", action="store_true")
    args = parser.parse_args(argv)

    client = DetectMateClient(args.url)
    try:
        if args.command == "reconfigure":
            with open(args.config_file, "r", encoding="utf-8") as fh:
                config = yaml.safe_load(fh) or {}
            result = client.reconfigure(config, persist=args.persist)
        elif args.command == "trace":
            result = client.trace(chrome=args.chrome)
            if args.out:
                with open(args.out, "w", encoding="utf-8") as fh:
                    json.dump(result, fh, indent=2)
                print(f"wrote {args.out}")
                return 0
        else:
            result = getattr(client, args.command)()
    except (urllib.error.URLError, OSError) as exc:
        print(f"request failed: {exc}", file=sys.stderr)
        return 1
    if isinstance(result, str):
        print(result)
    else:
        print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
