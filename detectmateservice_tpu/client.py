"""``detectmate-client`` CLI: drive a service's admin HTTP API.

Parity with the reference client (reference: src/service/client.py:27-120):
subcommands ``start`` / ``stop`` / ``status`` / ``metrics`` /
``reconfigure [--persist]`` against ``--url``, plus the TPU-build additions
``checkpoint`` (save component state to the service's checkpoint_dir),
``trace [--chrome] [-o FILE]`` (read the pipeline flight recorder; --chrome
fetches a Perfetto-loadable trace-event document), ``events`` (the
structured-event ring), ``xla [--limit N]`` (the device-side XLA compile
ledger + batch spans), ``profile [--seconds N] [--wait] [-o FILE]`` (start an
on-demand jax.profiler capture and, with --wait, download the artifact zip),
``load start|status|stop`` (drive the open-loop load generator behind
``/admin/load`` and read its live SLO scorecard; ``start --wait`` exits
non-zero on client-visible loss),
``replicas [targets...] [--drain ADDR | --undrain ADDR]`` (replica-router
roll-up across a pipeline — one row per replica with state/backlog/
inflight/frames, non-zero exit on any non-active replica; the drain verbs
post operator drain/undrain to a single router stage),
``model status|history|promote|rollback|pin|unpin|cycle|deploy`` (the
dmroll model lifecycle behind ``/admin/model``; ``deploy --version N``
rolls one checkpoint across a replica tier — drain → promote → verify →
undrain per replica via the router admin plane, rolling back on any
rejection)
``replay [status] [--shadow --version N] [--wal-dir D] [--limit N]``
(re-drive a recorded WAL ingress spool through the stage behind
``/admin/replay`` — deterministic pipeline replay/backfill, or ``--shadow``
offline scoring of a dmroll candidate against recorded traffic),
``tenants [--limit N]`` (the dmshed admission-control snapshot behind
``/admin/tenants`` — per-tier/per-tenant admitted+shed counters and the
current degradation-ladder state),
``dlq [status] [--limit N] | requeue [--id N] | purge [--id N]`` (the
dmfault dead-letter queue behind ``/admin/dlq`` — inspect quarantined
poison frames, hand them back to the engine, or drop them),
``faults [status] | arm PLAN.json | disarm`` (the dmfault injection plane
behind ``/admin/faults`` — arm a seeded fault plan, read the armed plan's
op counters + fired log, disarm and collect the fired schedule),
and ``health`` — which fans out across every stage of
a pipeline (stage URLs, service settings YAMLs, or a pipeline YAML with a
``stages:`` mapping), prints a roll-up table, and exits non-zero when any
stage is degraded, unhealthy, or unreachable.
Uses stdlib urllib — no extra dependencies.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request
from pathlib import Path
from typing import Any, List, Optional, Tuple

import yaml


class DetectMateClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> Any:
        body = None if payload is None else json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            self.url + path,
            data=body,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            if "json" in ctype:
                return json.loads(raw)
            if "zip" in ctype or "octet-stream" in ctype:
                return raw  # binary artifact (profile download)
            return raw.decode("utf-8", errors="replace")

    def start(self) -> Any:
        return self._request("POST", "/admin/start")

    def stop(self) -> Any:
        return self._request("POST", "/admin/stop")

    def shutdown(self) -> Any:
        return self._request("POST", "/admin/shutdown")

    def status(self) -> Any:
        return self._request("GET", "/admin/status")

    def metrics(self) -> str:
        return self._request("GET", "/metrics")

    def reconfigure(self, config: dict, persist: bool = False) -> Any:
        return self._request(
            "POST", "/admin/reconfigure", {"config": config, "persist": persist}
        )

    def checkpoint(self) -> Any:
        """Save component state to the service's checkpoint_dir now."""
        return self._request("POST", "/admin/checkpoint")

    def trace(self, chrome: bool = False) -> Any:
        """Read the pipeline flight recorder (slowest + sampled traces);
        ``chrome=True`` returns a Perfetto-loadable trace-event document —
        cross-stage when the target is the telemetry collector, local hops
        only elsewhere."""
        suffix = "?format=chrome" if chrome else ""
        return self._request("GET", "/admin/trace" + suffix)

    def traces(self, trace_id: Optional[str] = None,
               fmt: Optional[str] = None,
               limit: Optional[int] = None) -> Any:
        """Read the telemetry collector's assembled cross-stage traces
        (``GET /admin/traces``): the retained ring, one trace by id, or a
        perfetto/otlp export. Only the collector stage answers 200."""
        params = []
        if trace_id:
            params.append(f"id={trace_id}")
        if fmt:
            params.append(f"format={fmt}")
        if limit is not None:
            params.append(f"limit={int(limit)}")
        suffix = ("?" + "&".join(params)) if params else ""
        return self._request("GET", "/admin/traces" + suffix)

    def health(self, deep: bool = False) -> Any:
        """Read the self-diagnosis state (``GET /admin/health``). A non-200
        answer IS an answer here — the body still carries the report — so
        the HTTP error is unwrapped instead of raised."""
        path = "/admin/health" + ("?deep=1" if deep else "")
        try:
            return self._request("GET", path)
        except urllib.error.HTTPError as exc:
            body = exc.read()
            try:
                return json.loads(body)
            except json.JSONDecodeError:
                return {"state": "unknown",
                        "detail": body.decode("utf-8", errors="replace")}

    def events(self, limit: Optional[int] = None) -> Any:
        """Read the structured event ring (``GET /admin/events``)."""
        suffix = f"?limit={int(limit)}" if limit is not None else ""
        return self._request("GET", "/admin/events" + suffix)

    def xla(self, limit: Optional[int] = None) -> Any:
        """Read the XLA compile ledger + device-batch spans
        (``GET /admin/xla``)."""
        suffix = f"?limit={int(limit)}" if limit is not None else ""
        return self._request("GET", "/admin/xla" + suffix)

    def replicas(self) -> Any:
        """Replica-router roll-up (``GET /admin/replicas``). HTTP 404 means
        the stage is not a router — surfaced to the caller as None so the
        fan-out can skip non-router stages instead of erroring."""
        try:
            return self._request("GET", "/admin/replicas")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise

    def replica_drain(self, replica: str) -> Any:
        """Operator drain of one replica (``POST /admin/replicas``)."""
        return self._request("POST", "/admin/replicas",
                             {"action": "drain", "replica": replica})

    def replica_undrain(self, replica: str) -> Any:
        return self._request("POST", "/admin/replicas",
                             {"action": "undrain", "replica": replica})

    def model_status(self) -> Any:
        """Model lifecycle status (``GET /admin/model``). HTTP 404 (stage
        without ``rollout_enabled``) surfaces as None so fan-outs can skip
        non-lifecycle stages, mirroring ``replicas``."""
        try:
            return self._request("GET", "/admin/model")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise

    def model_history(self, limit: Optional[int] = None) -> Any:
        suffix = f"&limit={int(limit)}" if limit is not None else ""
        return self._request("GET", "/admin/model?history=1" + suffix)

    def model_action(self, action: str, version: Optional[int] = None,
                     block: bool = False) -> Any:
        """Model lifecycle verb (``POST /admin/model``): promote / rollback
        / pin / unpin / cycle."""
        payload: dict = {"action": action}
        if version is not None:
            payload["version"] = int(version)
        if block:
            payload["block"] = True
        return self._request("POST", "/admin/model", payload)

    def load_start(self, profile: dict) -> Any:
        """Start an open-loop load run (``POST /admin/load``). HTTP 409
        (another run active) is raised as urllib.error.HTTPError."""
        return self._request("POST", "/admin/load",
                             dict(profile, action="start"))

    def load_stop(self) -> Any:
        """Stop the active load run and return its final scorecard."""
        return self._request("POST", "/admin/load", {"action": "stop"})

    def load_status(self) -> Any:
        """Live SLO scorecard of the load run (``GET /admin/load``)."""
        return self._request("GET", "/admin/load")

    def tenants(self, limit: Optional[int] = None) -> Any:
        """Admission-control snapshot (``GET /admin/tenants``): per-tier and
        per-tenant admitted/shed counters + the current degradation-ladder
        state. HTTP 404 (stage without ``shed_enabled``) surfaces as None,
        mirroring ``replicas``/``model_status``."""
        suffix = f"?limit={int(limit)}" if limit is not None else ""
        try:
            return self._request("GET", "/admin/tenants" + suffix)
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise

    def drift(self) -> Any:
        """Drift-monitor snapshot (``GET /admin/drift``): live-vs-baseline
        KS/PSI, hysteresis state, top drifting feature columns. HTTP 404
        (stage without ``drift_enabled``) surfaces as None, mirroring
        ``model_status``."""
        try:
            return self._request("GET", "/admin/drift")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise

    def slo(self) -> Any:
        """SLO burn-rate snapshot (``GET /admin/slo``): multi-window error
        ratios/burn rates, per-stage dwell attribution, and the capacity
        model when ``capacity_enabled``."""
        return self._request("GET", "/admin/slo")

    def dlq_status(self, limit: Optional[int] = None) -> Any:
        """Dead-letter-queue snapshot (``GET /admin/dlq``): depth, totals,
        and the newest quarantined entries (frame bytes omitted)."""
        suffix = f"?limit={int(limit)}" if limit is not None else ""
        return self._request("GET", "/admin/dlq" + suffix)

    def dlq_action(self, action: str, entry_id: Optional[int] = None) -> Any:
        """DLQ verb (``POST /admin/dlq``): ``requeue`` hands frames back to
        the engine (at-most-once), ``purge`` drops them; one ``id`` or all."""
        payload: dict = {"action": action}
        if entry_id is not None:
            payload["id"] = int(entry_id)
        return self._request("POST", "/admin/dlq", payload)

    def faults_status(self, tail: Optional[int] = None) -> Any:
        """Fault-injection status (``GET /admin/faults``): the armed plan,
        per-site op counters, and the fired-fault log tail."""
        suffix = f"?tail={int(tail)}" if tail is not None else ""
        return self._request("GET", "/admin/faults" + suffix)

    def faults_arm(self, plan: dict) -> Any:
        """Arm a seeded fault plan (``POST /admin/faults``)."""
        return self._request("POST", "/admin/faults",
                             {"action": "arm", "plan": plan})

    def faults_disarm(self) -> Any:
        """Disarm the active plan and return its final fired schedule."""
        return self._request("POST", "/admin/faults", {"action": "disarm"})

    def replay_status(self) -> Any:
        """WAL replay status + the live ingress spool's stats
        (``GET /admin/replay``)."""
        return self._request("GET", "/admin/replay")

    def replay_start(self, payload: dict) -> Any:
        """Start (or, with ``wait: true``, run to completion) a WAL replay
        (``POST /admin/replay``). HTTP 409 (another replay, or pipeline
        mode against a running engine) raises urllib.error.HTTPError."""
        return self._request("POST", "/admin/replay", payload)

    def profile_start(self, seconds: float = 1.0) -> Any:
        """Start an on-demand jax.profiler capture
        (``POST /admin/profile?seconds=N``)."""
        return self._request("POST", f"/admin/profile?seconds={float(seconds)}")

    def profile_status(self) -> Any:
        """Capture status (``GET /admin/profile``)."""
        return self._request("GET", "/admin/profile")

    def profile_latest(self) -> bytes:
        """Download the newest completed capture as zip bytes
        (``GET /admin/profile/latest``)."""
        return self._request("GET", "/admin/profile/latest")


def resolve_stages(default_url: str, targets: List[str]) -> List[Tuple[str, str]]:
    """Targets → ordered ``(stage_name, admin_url)`` pairs. Accepted forms:

    * a stage admin URL (``http://host:port``),
    * a service settings YAML (the per-stage files a pipeline already has —
      the URL is derived from its ``http_host``/``http_port``),
    * a pipeline YAML with a ``stages:`` mapping of name → URL.

    No targets = just ``--url`` (single-stage roll-up)."""
    if not targets:
        return [("service", default_url)]
    stages: List[Tuple[str, str]] = []
    for target in targets:
        if target.startswith(("http://", "https://")):
            stages.append((target, target))
            continue
        with open(target, "r", encoding="utf-8") as fh:
            doc = yaml.safe_load(fh) or {}
        if not isinstance(doc, dict):
            raise ValueError(f"{target}: expected a YAML mapping")
        if isinstance(doc.get("stages"), dict):
            if not doc["stages"]:
                raise ValueError(f"{target}: 'stages:' mapping is empty — "
                                 "expected name: url entries")
            for name, url in doc["stages"].items():
                stages.append((str(name), str(url)))
            continue
        host = doc.get("http_host", "127.0.0.1")
        port = doc.get("http_port", 8000)
        name = (doc.get("component_name") or doc.get("component_type")
                or Path(target).stem)
        stages.append((str(name), f"http://{host}:{port}"))
    return stages


def health_rollup(default_url: str, targets: List[str],
                  deep: bool = False) -> int:
    """Fan ``/admin/health?deep=1`` out over every stage, print the roll-up
    table, and return the exit code: 0 only when every stage is healthy."""
    stages = resolve_stages(default_url, targets)
    rows = []
    exit_code = 0
    for name, url in stages:
        try:
            report = DetectMateClient(url).health(deep=True)
            state = report.get("state", "unknown")
            failing = [c for c in report.get("checks", [])
                       if c.get("status") != "pass"]
        except (urllib.error.URLError, OSError) as exc:
            state, failing = "unreachable", [{"name": "admin_endpoint",
                                             "detail": str(exc)}]
        if state != "healthy":
            exit_code = 1
        rows.append((name, state, url, failing))
    name_w = max([5, *(len(r[0]) for r in rows)])
    state_w = max([5, *(len(r[1]) for r in rows)])
    print(f"{'STAGE':<{name_w}}  {'STATE':<{state_w}}  URL / failing checks")
    for name, state, url, failing in rows:
        summary = ", ".join(c.get("name", "?") for c in failing)
        print(f"{name:<{name_w}}  {state:<{state_w}}  {url}"
              + (f"  [{summary}]" if summary else ""))
        if deep:
            for check in failing:
                print(f"{'':<{name_w}}  {'':<{state_w}}    "
                      f"{check.get('name', '?')}: {check.get('detail', '')}")
    return exit_code


def replicas_rollup(default_url: str, targets: List[str],
                    drain: Optional[str] = None,
                    undrain: Optional[str] = None) -> int:
    """Fan ``GET /admin/replicas`` out over every stage (same target forms
    as the ``health`` roll-up), print one row per replica, and return the
    exit code: 0 only when every replica of every router stage is active.
    ``--drain`` / ``--undrain`` post the operator verb to the single
    targeted router stage first."""
    stages = resolve_stages(default_url, targets)
    if drain or undrain:
        if len(stages) != 1:
            print("error: --drain/--undrain need exactly one router stage "
                  "target", file=sys.stderr)
            return 2
        client = DetectMateClient(stages[0][1])
        result = (client.replica_drain(drain) if drain
                  else client.replica_undrain(undrain))
        print(json.dumps(result, indent=2))
    rows = []        # (stage, replica, state, backlog, inflight, frames)
    exit_code = 0
    saw_router = False
    for name, url in stages:
        try:
            snap = DetectMateClient(url).replicas()
        except (urllib.error.URLError, OSError) as exc:
            rows.append((name, "-", "unreachable", "-", "-", "-", str(exc)))
            exit_code = 1
            continue
        if snap is None:
            continue                      # not a router stage: skip quietly
        saw_router = True
        policy = snap.get("policy", "?")
        for rep in snap.get("replicas", []):
            state = rep.get("state", "?")
            if state != "active":
                exit_code = 1
            rows.append((name, rep.get("addr", "?"), state,
                         rep.get("backlog", 0), rep.get("inflight", 0),
                         rep.get("frames_total", 0),
                         f"policy={policy}" if rep is snap["replicas"][0]
                         else ""))
    if not saw_router and not rows:
        print("no replica-router stage found among the targets",
              file=sys.stderr)
        return 1
    widths = [max([len(h), *(len(str(r[i])) for r in rows)])
              for i, h in enumerate(
                  ("STAGE", "REPLICA", "STATE", "BACKLOG", "INFLIGHT",
                   "FRAMES"))]
    header = ("STAGE", "REPLICA", "STATE", "BACKLOG", "INFLIGHT", "FRAMES")
    print("  ".join(h.ljust(widths[i]) for i, h in enumerate(header)))
    for row in rows:
        print("  ".join(str(v).ljust(widths[i])
                        for i, v in enumerate(row[:6]))
              + (f"  {row[6]}" if row[6] else ""))
    return exit_code


def rolling_deploy(router_url: str, version: int,
                   client_factory=DetectMateClient,
                   timeout_s: float = 120.0, poll_s: float = 0.5,
                   sleep=None, out=print) -> int:
    """``client.py model deploy``: roll one checkpoint version across a
    replica tier, one replica at a time, through the router's admin plane —
    drain → promote → verify → undrain per replica, so a bad checkpoint
    never takes more than the replica under rollout out of dispatch.

    Replica admin URLs come from the router's own ``GET /admin/replicas``
    snapshot (``router_admin_urls``); every replica must point its
    ``rollout_dir`` at the shared store that holds ``version``. On any
    promote/verify failure the failed replica is rolled back and undrained,
    every ALREADY-promoted replica is rolled back too, and the deploy exits
    non-zero — the tier converges back to the pre-deploy version instead of
    serving a split brain."""
    import time as _time

    sleep = sleep if sleep is not None else _time.sleep
    router = client_factory(router_url)
    snap = router.replicas()
    if snap is None:
        out("error: the target stage is not a replica router")
        return 2
    replicas = snap.get("replicas", [])
    missing = [r["addr"] for r in replicas if not r.get("admin_url")]
    if missing:
        out(f"error: replicas without admin URLs (router_admin_urls): "
            f"{missing}")
        return 2

    def wait_state(addr: str, want: Tuple[str, ...]) -> bool:
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            for rep in (router.replicas() or {}).get("replicas", []):
                if rep["addr"] == addr and rep["state"] in want:
                    return True
            sleep(poll_s)
        return False

    promoted: List[Tuple[str, str]] = []   # (addr, admin_url)

    def rollback_all(failed_addr: str, failed_admin: str) -> None:
        for addr, admin in [(failed_addr, failed_admin), *reversed(promoted)]:
            try:
                client_factory(admin).model_action("rollback")
                out(f"  rolled back {addr}")
            except (urllib.error.URLError, OSError) as exc:
                out(f"  rollback of {addr} FAILED: {exc} — resolve by hand")
        try:
            router.replica_undrain(failed_addr)
        except (urllib.error.URLError, OSError):
            pass

    for rep in replicas:
        addr, admin = rep["addr"], rep["admin_url"]
        out(f"deploy v{version} -> {addr}")
        router.replica_drain(addr)
        if not wait_state(addr, ("drained",)):
            out(f"  {addr} never drained within {timeout_s:.0f}s; aborting")
            rollback_all(addr, admin)
            return 1
        try:
            result = client_factory(admin).model_action("promote",
                                                        version=version)
            live = (client_factory(admin).model_status() or {}) \
                .get("live_version")
            if result.get("result") != "promoted" or live != version:
                raise ValueError(
                    f"replica reports result={result.get('result')!r} "
                    f"live_version={live!r}")
        except (urllib.error.URLError, OSError, ValueError) as exc:
            out(f"  {addr} REJECTED v{version}: {exc}")
            rollback_all(addr, admin)
            return 1
        router.replica_undrain(addr)
        if not wait_state(addr, ("active",)):
            out(f"  {addr} did not return to active within "
                f"{timeout_s:.0f}s; aborting")
            rollback_all(addr, admin)
            return 1
        promoted.append((addr, admin))
        out(f"  {addr} serving v{version}, back in dispatch")
    out(f"deployed v{version} to {len(promoted)} replica(s)")
    return 0


def run_model(client: DetectMateClient, args) -> int:
    """``client.py model``: drive the model lifecycle behind /admin/model."""
    if args.action == "status":
        status = client.model_status()
        if status is None:
            print("model lifecycle is not enabled on this stage",
                  file=sys.stderr)
            return 1
        print(json.dumps(status, indent=2))
        return 0
    if args.action == "history":
        print(json.dumps(client.model_history(limit=args.limit), indent=2))
        return 0
    if args.action == "deploy":
        if args.version is None:
            print("error: model deploy requires --version", file=sys.stderr)
            return 2
        return rolling_deploy(args.router or client.url, args.version,
                              timeout_s=args.timeout)
    try:
        result = client.model_action(args.action, version=args.version,
                                     block=args.block)
    except urllib.error.HTTPError as exc:
        print(f"model {args.action} rejected ({exc.code}): "
              f"{exc.read().decode('utf-8', errors='replace')}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


def run_profile(client: DetectMateClient, seconds: float, wait: bool,
                out: str) -> int:
    """``client.py profile``: start a capture; with ``--wait``, poll until it
    completes and download the artifact zip. Exit 1 when the capture errors
    or the service rejects it (another capture running → HTTP 409)."""
    import time as _time

    started = client.profile_start(seconds=seconds)
    print(json.dumps(started, indent=2))
    if not wait:
        return 0
    deadline = _time.monotonic() + seconds + 30.0
    status = client.profile_status()
    while status.get("running") and _time.monotonic() < deadline:
        _time.sleep(min(0.25, max(0.05, seconds / 4)))
        status = client.profile_status()
    last = status.get("last") or {}
    if status.get("running") or last.get("state") != "done":
        print(f"capture did not complete cleanly: {json.dumps(status)}",
              file=sys.stderr)
        return 1
    data = client.profile_latest()
    with open(out, "wb") as fh:
        fh.write(data)
    print(f"wrote {out} ({len(data)} bytes) from {last.get('dir')}")
    return 0


def run_replay(client: DetectMateClient, args) -> int:
    """``client.py replay``: re-drive a recorded WAL spool through the
    stage behind ``/admin/replay``. ``status`` reads the manager + spool
    state; a start without ``--no-wait`` blocks until the run completes and
    exits non-zero when it errors. ``--shadow`` scores a dmroll candidate
    (``--version``, or the store's newest) against the recorded traffic
    and prints the offline divergence report."""
    import time as _time

    if args.action == "status":
        print(json.dumps(client.replay_status(), indent=2))
        return 0
    payload: dict = {"mode": "shadow" if args.shadow else "pipeline",
                     "wait": not args.no_wait}
    if args.wal_dir:
        payload["wal_dir"] = args.wal_dir
    if args.limit is not None:
        payload["limit"] = args.limit
    if args.start_seq:
        payload["start_seq"] = args.start_seq
    if args.force:
        payload["force"] = True
    if args.shadow:
        if args.version is not None:
            payload["version"] = args.version
        if args.store_dir:
            payload["store_dir"] = args.store_dir
    try:
        result = client.replay_start(payload)
    except urllib.error.HTTPError as exc:
        print(f"replay rejected ({exc.code}): "
              f"{exc.read().decode('utf-8', errors='replace')}",
              file=sys.stderr)
        return 1
    if args.no_wait:
        print(json.dumps(result, indent=2))
        return 0
    # waited runs return the finished outcome directly; poll anyway in case
    # the server answered "started" (an older build)
    deadline = _time.monotonic() + args.timeout
    while (result.get("state") == "started"
           and _time.monotonic() < deadline):
        _time.sleep(0.5)
        status = client.replay_status()
        if not status.get("running") and status.get("last"):
            result = status["last"]
            break
    print(json.dumps(result, indent=2))
    return 0 if result.get("state") == "done" else 1


def run_dlq(client: DetectMateClient, args) -> int:
    """``client.py dlq``: inspect / requeue / purge the dead-letter queue
    behind ``/admin/dlq``. ``status`` (default) prints the snapshot and
    exits non-zero when poison is waiting, so a pipeline health sweep can
    gate on it; ``requeue``/``purge`` act on ``--id`` or everything."""
    try:
        if args.action == "status":
            result = client.dlq_status(limit=args.limit)
            print(json.dumps(result, indent=2))
            return 1 if result.get("depth_frames", 0) else 0
        result = client.dlq_action(args.action, entry_id=args.id)
    except urllib.error.HTTPError as exc:
        print(f"dlq {args.action} rejected ({exc.code}): "
              f"{exc.read().decode('utf-8', errors='replace')}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


def run_faults(client: DetectMateClient, args) -> int:
    """``client.py faults``: the fault-injection plane behind
    ``/admin/faults``. ``status`` (default) prints the armed plan + fired
    log; ``arm PLAN.json`` posts a seeded plan; ``disarm`` ends the chaos
    run and prints the final fired schedule (the determinism artifact)."""
    try:
        if args.action == "status":
            print(json.dumps(client.faults_status(tail=args.tail), indent=2))
            return 0
        if args.action == "disarm":
            print(json.dumps(client.faults_disarm(), indent=2))
            return 0
        if not args.plan_file:
            print("error: faults arm requires a PLAN.json path",
                  file=sys.stderr)
            return 2
        with open(args.plan_file, "r", encoding="utf-8") as fh:
            plan = json.load(fh)
        result = client.faults_arm(plan)
    except urllib.error.HTTPError as exc:
        print(f"faults {args.action} rejected ({exc.code}): "
              f"{exc.read().decode('utf-8', errors='replace')}",
              file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


def _parse_mix(spec: str) -> dict:
    """``anomaly=0.005,json=0.01,invalid_utf8=0.005`` → mix dict."""
    mix = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        if "=" not in part:
            raise ValueError(f"mix entry {part!r} is not key=fraction")
        key, _, value = part.partition("=")
        mix[key.strip()] = float(value)
    return mix


def trace_waterfall(trace: dict, width: int = 48) -> str:
    """One assembled trace as a stage waterfall: each hop a bar positioned
    by its recv offset inside the trace's e2e window, so wire/queue gaps
    and the widest stage read directly off the terminal."""
    lines = [
        "trace %s  verdict=%s  complete=%s" % (
            trace.get("trace_id"), trace.get("verdict", "?"),
            trace.get("complete")),
    ]
    e2e = trace.get("e2e_seconds")
    if e2e is not None:
        lines[0] += f"  e2e={e2e * 1000.0:.3f}ms"
    if trace.get("tenant_bucket") is not None:
        lines[0] += f"  tenant_bucket={trace['tenant_bucket']}"
    if trace.get("flags"):
        lines[0] += "  flags=%s" % ",".join(trace["flags"])
    hops = trace.get("hops") or []
    if not hops:
        lines.append("  (no hop spans — flag-only trace)")
        return "\n".join(lines)
    t0 = trace.get("ingest_ns") or hops[0]["recv_ns"]
    t1 = max(h["send_ns"] for h in hops)
    span = max(1, t1 - t0)
    name_w = max(len(h["stage"]) for h in hops)
    for hop in hops:
        start = round((hop["recv_ns"] - t0) / span * width)
        end = max(start + 1, round((hop["send_ns"] - t0) / span * width))
        bar = " " * start + "#" * (end - start)
        dwell_ms = max(0, hop["send_ns"] - hop["recv_ns"]) / 1e6
        offset_ms = max(0, hop["recv_ns"] - t0) / 1e6
        lines.append("  %-*s |%-*s| %8.3fms  (+%.3fms)" % (
            name_w, hop["stage"], width, bar[:width], dwell_ms, offset_ms))
    return "\n".join(lines)


def run_load(client: DetectMateClient, args) -> int:
    """``client.py load``: drive the open-loop load generator. ``start
    --wait`` polls until the run's schedule (+ settle) completes, stops it,
    and exits non-zero on client-visible loss — the scriptable smoke-soak."""
    import time as _time

    if args.action == "status":
        print(json.dumps(client.load_status(), indent=2))
        return 0
    if args.action == "stop":
        final = client.load_stop()
        print(json.dumps(final, indent=2))
        return 0
    profile = {"target_addr": args.target, "rate": args.rate,
               "burst": args.burst, "seconds": args.seconds,
               "settle_s": args.settle, "seed": args.seed,
               "warm_lines": args.warm_lines}
    if args.listen:
        profile["listen_addr"] = args.listen
    if args.mix:
        profile["mix"] = _parse_mix(args.mix)
    try:
        started = client.load_start(profile)
    except urllib.error.HTTPError as exc:
        print(f"load start rejected ({exc.code}): "
              f"{exc.read().decode('utf-8', errors='replace')}",
              file=sys.stderr)
        return 1
    print(json.dumps(started, indent=2))
    if not args.wait:
        return 0
    deadline = _time.monotonic() + args.seconds + args.settle + 30.0
    status = client.load_status()
    while status.get("running") and _time.monotonic() < deadline:
        _time.sleep(1.0)
        status = client.load_status()
    final = client.load_stop()
    print(json.dumps(final, indent=2))
    loss = (final.get("scorecard") or {}).get("loss")
    return 0 if loss == 0 else 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="detectmate-client", description="Admin client for DetectMate TPU services"
    )
    parser.add_argument("--url", default="http://127.0.0.1:8000", help="service admin URL")
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("start")
    sub.add_parser("stop")
    sub.add_parser("shutdown")
    sub.add_parser("status")
    sub.add_parser("metrics")
    sub.add_parser("checkpoint")
    health = sub.add_parser(
        "health",
        help="pipeline health roll-up across stages (/admin/health)")
    health.add_argument(
        "targets", nargs="*",
        help="stage admin URLs, per-stage settings YAMLs, or a pipeline "
             "YAML with a 'stages: {name: url}' mapping; none = --url only")
    health.add_argument("--deep", action="store_true",
                        help="print per-check detail for failing stages")
    replicas_p = sub.add_parser(
        "replicas",
        help="replica-router roll-up across stages (/admin/replicas)")
    replicas_p.add_argument(
        "targets", nargs="*",
        help="stage admin URLs, per-stage settings YAMLs, or a pipeline "
             "YAML with a 'stages: {name: url}' mapping; none = --url only")
    replicas_p.add_argument("--drain", metavar="REPLICA_ADDR",
                           help="operator-drain this replica on the (single) "
                                "targeted router stage first")
    replicas_p.add_argument("--undrain", metavar="REPLICA_ADDR",
                           help="lift an operator drain on the (single) "
                                "targeted router stage first")
    events_p = sub.add_parser(
        "events", help="read the structured event ring (/admin/events)")
    events_p.add_argument("--limit", type=int, default=None,
                          help="only the newest N events")
    xla_p = sub.add_parser(
        "xla", help="read the XLA compile ledger + device-batch spans "
                    "(/admin/xla)")
    xla_p.add_argument("--limit", type=int, default=None,
                       help="only the newest N compile events / spans")
    profile_p = sub.add_parser(
        "profile",
        help="start an on-demand jax.profiler capture (/admin/profile)")
    profile_p.add_argument("--seconds", type=float, default=1.0,
                           help="capture duration (default 1.0)")
    profile_p.add_argument("--wait", action="store_true",
                           help="block until the capture completes, then "
                                "download the artifact zip")
    profile_p.add_argument("-o", "--out", default="profile.zip",
                           help="artifact path for --wait (default "
                                "profile.zip)")
    load_p = sub.add_parser(
        "load", help="drive the open-loop load generator (/admin/load)")
    load_p.add_argument("action", choices=["start", "status", "stop"],
                        help="start a run, read the live scorecard, or "
                             "stop and print the final scorecard")
    load_p.add_argument("--target", help="pipeline ingress address the "
                                         "generator dials (required for "
                                         "start)")
    load_p.add_argument("--listen", help="sink address the scorecard "
                                         "collector listens on (the "
                                         "terminal stage dials it)")
    load_p.add_argument("--rate", type=float, default=2000.0,
                        help="offered arrival rate, lines/s (default 2000)")
    load_p.add_argument("--burst", type=int, default=256,
                        help="lines per traced frame (default 256)")
    load_p.add_argument("--seconds", type=float, default=30.0,
                        help="run length; 0 = until stopped (default 30)")
    load_p.add_argument("--settle", type=float, default=5.0,
                        help="post-send drain window before outstanding "
                             "traces count as loss (default 5)")
    load_p.add_argument("--warm-lines", type=int, default=0,
                        help="untraced all-normal preamble lines (scorer "
                             "training) before the measured phase")
    load_p.add_argument("--mix", help="edge-row fractions, e.g. "
                                      "anomaly=0.005,json=0.01,"
                                      "invalid_utf8=0.005")
    load_p.add_argument("--seed", type=int, default=7)
    load_p.add_argument("--wait", action="store_true",
                        help="block until the schedule+settle completes, "
                             "stop the run, and exit non-zero on loss")
    model_p = sub.add_parser(
        "model", help="model lifecycle: status/history and the "
                      "promote/rollback/pin verbs (/admin/model), plus a "
                      "rolling fleet deploy over a replica router")
    model_p.add_argument(
        "action", choices=["status", "history", "promote", "rollback",
                           "pin", "unpin", "cycle", "deploy"],
        help="status/history read the lifecycle state; promote cuts the "
             "shadowing candidate (or --version N from the store) over; "
             "rollback reinstalls the previous live version; pin freezes "
             "the served version (cycles suspend) and unpin resumes; "
             "cycle runs one sample→fine-tune→shadow cycle now; deploy "
             "rolls --version across a replica tier (drain → promote → "
             "undrain per replica via the router admin plane)")
    model_p.add_argument("--version", type=int, default=None,
                         help="checkpoint version for promote/pin/deploy")
    model_p.add_argument("--block", action="store_true",
                         help="cycle: block until the shadow gate resolves")
    model_p.add_argument("--limit", type=int, default=None,
                         help="history: only the newest N checkpoints")
    model_p.add_argument("--router", default=None,
                         help="deploy: the replica router's admin URL "
                              "(default: --url)")
    model_p.add_argument("--timeout", type=float, default=120.0,
                         help="deploy: per-replica drain/active wait "
                              "(default 120 s)")
    replay_p = sub.add_parser(
        "replay", help="replay a recorded WAL spool through the stage "
                       "(/admin/replay): deterministic pipeline re-drive, "
                       "or --shadow offline canary scoring")
    replay_p.add_argument("action", nargs="?", default="run",
                          choices=["run", "status"],
                          help="run (default) starts a replay; status "
                               "reads the manager + spool state")
    replay_p.add_argument("--wal-dir",
                          help="spool directory (default: the stage's "
                               "configured wal_dir)")
    replay_p.add_argument("--shadow", action="store_true",
                          help="score a dmroll candidate against the "
                               "recorded traffic and print the divergence "
                               "report instead of re-driving the pipeline")
    replay_p.add_argument("--version", type=int, default=None,
                          help="shadow: candidate checkpoint version "
                               "(default: the store's newest)")
    replay_p.add_argument("--store-dir",
                          help="shadow: checkpoint store root (default: "
                               "the stage's rollout_dir)")
    replay_p.add_argument("--limit", type=int, default=None,
                          help="replay at most N recorded frames")
    replay_p.add_argument("--start-seq", type=int, default=0,
                          help="skip records at or below this sequence")
    replay_p.add_argument("--force", action="store_true",
                          help="pipeline mode: replay even while the "
                               "engine is running (interleaves!)")
    replay_p.add_argument("--no-wait", action="store_true",
                          help="return immediately; poll `replay status`")
    replay_p.add_argument("--timeout", type=float, default=600.0,
                          help="wait budget in seconds (default 600)")
    tenants_p = sub.add_parser(
        "tenants", help="admission-control snapshot: per-tier admitted/shed "
                        "counters + the degradation-ladder state "
                        "(/admin/tenants)")
    tenants_p.add_argument("--limit", type=int, default=None,
                           help="only the top N tenants by shed count")
    sub.add_parser(
        "drift", help="drift monitor: live-vs-baseline KS/PSI, hysteresis "
                      "state, top drifting features (/admin/drift)")
    sub.add_parser(
        "slo", help="multi-window SLO burn rates, per-stage dwell "
                    "attribution, and the capacity model (/admin/slo)")
    dlq_p = sub.add_parser(
        "dlq", help="dead-letter queue: inspect, requeue, or purge "
                    "quarantined poison frames (/admin/dlq)")
    dlq_p.add_argument("action", nargs="?", default="status",
                       choices=["status", "requeue", "purge"],
                       help="status (default, non-zero exit when poison is "
                            "waiting), requeue, or purge")
    dlq_p.add_argument("--id", type=int, default=None,
                       help="one DLQ entry id (default: all entries)")
    dlq_p.add_argument("--limit", type=int, default=None,
                       help="show at most N newest entries")
    faults_p = sub.add_parser(
        "faults", help="deterministic fault injection: arm a seeded plan, "
                       "read its fired log, disarm (/admin/faults)")
    faults_p.add_argument("action", nargs="?", default="status",
                          choices=["status", "arm", "disarm"],
                          help="status (default), arm, or disarm")
    faults_p.add_argument("plan_file", nargs="?", default=None,
                          help="arm: JSON fault-plan file "
                               "(seed + specs, docs/fault_injection.md)")
    faults_p.add_argument("--tail", type=int, default=None,
                          help="status: show the last N fired faults")
    trace = sub.add_parser(
        "trace", help="pipeline traces: the local flight recorder "
                      "(/admin/trace), or — against the telemetry "
                      "collector stage — `trace list` and `trace show "
                      "<id>` over the assembled cross-stage traces "
                      "(/admin/traces)")
    trace.add_argument("action", nargs="?", default=None,
                       choices=["list", "show"],
                       help="list: the collector's retained traces; "
                            "show: one trace as a stage waterfall; omit "
                            "for the local flight-recorder snapshot")
    trace.add_argument("trace_id", nargs="?", default=None,
                       help="show: the 16-hex trace id")
    trace.add_argument("--chrome", action="store_true",
                       help="fetch Chrome trace-event JSON (Perfetto-"
                            "loadable; cross-stage on the collector)")
    trace.add_argument("-o", "--out",
                       help="write the result to a file instead of stdout")
    reconf = sub.add_parser("reconfigure")
    reconf.add_argument("config_file", help="YAML file with the new component config")
    reconf.add_argument("--persist", action="store_true")
    args = parser.parse_args(argv)

    client = DetectMateClient(args.url)
    try:
        if args.command == "health":
            return health_rollup(args.url, args.targets, deep=args.deep)
        if args.command == "replicas":
            return replicas_rollup(args.url, args.targets,
                                   drain=args.drain, undrain=args.undrain)
        if args.command == "profile":
            return run_profile(client, args.seconds, args.wait, args.out)
        if args.command == "load":
            if args.action == "start" and not args.target:
                print("error: load start requires --target", file=sys.stderr)
                return 2
            return run_load(client, args)
        if args.command == "model":
            return run_model(client, args)
        if args.command == "replay":
            return run_replay(client, args)
        if args.command == "dlq":
            return run_dlq(client, args)
        if args.command == "faults":
            return run_faults(client, args)
        if args.command == "tenants":
            result = client.tenants(limit=args.limit)
            if result is None:
                print("admission control is not enabled on this stage "
                      "(shed_enabled)", file=sys.stderr)
                return 1
            print(json.dumps(result, indent=2))
            return 0
        if args.command == "drift":
            result = client.drift()
            if result is None:
                print("drift monitoring is not enabled on this stage "
                      "(drift_enabled)", file=sys.stderr)
                return 1
            print(json.dumps(result, indent=2))
            return 0
        if args.command == "events":
            result = client.events(limit=args.limit)
        elif args.command == "xla":
            result = client.xla(limit=args.limit)
        elif args.command == "reconfigure":
            with open(args.config_file, "r", encoding="utf-8") as fh:
                config = yaml.safe_load(fh) or {}
            result = client.reconfigure(config, persist=args.persist)
        elif args.command == "trace":
            if args.action == "list":
                result = client.traces()
            elif args.action == "show":
                if not args.trace_id:
                    print("trace show requires a trace id "
                          "(see `trace list`)", file=sys.stderr)
                    return 2
                result = client.traces(trace_id=args.trace_id)
                if not args.out:
                    print(trace_waterfall(result))
                    return 0
            else:
                result = client.trace(chrome=args.chrome)
            if args.out:
                with open(args.out, "w", encoding="utf-8") as fh:
                    json.dump(result, fh, indent=2)
                print(f"wrote {args.out}")
                return 0
        else:
            result = getattr(client, args.command)()
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    except (urllib.error.URLError, OSError) as exc:
        print(f"request failed: {exc}", file=sys.stderr)
        return 1
    if isinstance(result, str):
        print(result)
    else:
        print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
