"""``detectmate-client`` CLI: drive a service's admin HTTP API.

Parity with the reference client (reference: src/service/client.py:27-120):
subcommands ``start`` / ``stop`` / ``status`` / ``metrics`` /
``reconfigure [--persist]`` against ``--url``, plus the TPU-build additions
``checkpoint`` (save component state to the service's checkpoint_dir),
``trace [--chrome] [-o FILE]`` (read the pipeline flight recorder; --chrome
fetches a Perfetto-loadable trace-event document), ``events`` (the
structured-event ring) and ``health`` — which fans out across every stage of
a pipeline (stage URLs, service settings YAMLs, or a pipeline YAML with a
``stages:`` mapping), prints a roll-up table, and exits non-zero when any
stage is degraded, unhealthy, or unreachable.
Uses stdlib urllib — no extra dependencies.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request
from pathlib import Path
from typing import Any, List, Optional, Tuple

import yaml


class DetectMateClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> Any:
        body = None if payload is None else json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            self.url + path,
            data=body,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            if "json" in ctype:
                return json.loads(raw)
            return raw.decode("utf-8", errors="replace")

    def start(self) -> Any:
        return self._request("POST", "/admin/start")

    def stop(self) -> Any:
        return self._request("POST", "/admin/stop")

    def shutdown(self) -> Any:
        return self._request("POST", "/admin/shutdown")

    def status(self) -> Any:
        return self._request("GET", "/admin/status")

    def metrics(self) -> str:
        return self._request("GET", "/metrics")

    def reconfigure(self, config: dict, persist: bool = False) -> Any:
        return self._request(
            "POST", "/admin/reconfigure", {"config": config, "persist": persist}
        )

    def checkpoint(self) -> Any:
        """Save component state to the service's checkpoint_dir now."""
        return self._request("POST", "/admin/checkpoint")

    def trace(self, chrome: bool = False) -> Any:
        """Read the pipeline flight recorder (slowest + sampled traces);
        ``chrome=True`` returns a Perfetto-loadable trace-event document."""
        suffix = "?format=chrome" if chrome else ""
        return self._request("GET", "/admin/trace" + suffix)

    def health(self, deep: bool = False) -> Any:
        """Read the self-diagnosis state (``GET /admin/health``). A non-200
        answer IS an answer here — the body still carries the report — so
        the HTTP error is unwrapped instead of raised."""
        path = "/admin/health" + ("?deep=1" if deep else "")
        try:
            return self._request("GET", path)
        except urllib.error.HTTPError as exc:
            body = exc.read()
            try:
                return json.loads(body)
            except json.JSONDecodeError:
                return {"state": "unknown",
                        "detail": body.decode("utf-8", errors="replace")}

    def events(self, limit: Optional[int] = None) -> Any:
        """Read the structured event ring (``GET /admin/events``)."""
        suffix = f"?limit={int(limit)}" if limit is not None else ""
        return self._request("GET", "/admin/events" + suffix)


def resolve_stages(default_url: str, targets: List[str]) -> List[Tuple[str, str]]:
    """Targets → ordered ``(stage_name, admin_url)`` pairs. Accepted forms:

    * a stage admin URL (``http://host:port``),
    * a service settings YAML (the per-stage files a pipeline already has —
      the URL is derived from its ``http_host``/``http_port``),
    * a pipeline YAML with a ``stages:`` mapping of name → URL.

    No targets = just ``--url`` (single-stage roll-up)."""
    if not targets:
        return [("service", default_url)]
    stages: List[Tuple[str, str]] = []
    for target in targets:
        if target.startswith(("http://", "https://")):
            stages.append((target, target))
            continue
        with open(target, "r", encoding="utf-8") as fh:
            doc = yaml.safe_load(fh) or {}
        if not isinstance(doc, dict):
            raise ValueError(f"{target}: expected a YAML mapping")
        if isinstance(doc.get("stages"), dict):
            if not doc["stages"]:
                raise ValueError(f"{target}: 'stages:' mapping is empty — "
                                 "expected name: url entries")
            for name, url in doc["stages"].items():
                stages.append((str(name), str(url)))
            continue
        host = doc.get("http_host", "127.0.0.1")
        port = doc.get("http_port", 8000)
        name = (doc.get("component_name") or doc.get("component_type")
                or Path(target).stem)
        stages.append((str(name), f"http://{host}:{port}"))
    return stages


def health_rollup(default_url: str, targets: List[str],
                  deep: bool = False) -> int:
    """Fan ``/admin/health?deep=1`` out over every stage, print the roll-up
    table, and return the exit code: 0 only when every stage is healthy."""
    stages = resolve_stages(default_url, targets)
    rows = []
    exit_code = 0
    for name, url in stages:
        try:
            report = DetectMateClient(url).health(deep=True)
            state = report.get("state", "unknown")
            failing = [c for c in report.get("checks", [])
                       if c.get("status") != "pass"]
        except (urllib.error.URLError, OSError) as exc:
            state, failing = "unreachable", [{"name": "admin_endpoint",
                                             "detail": str(exc)}]
        if state != "healthy":
            exit_code = 1
        rows.append((name, state, url, failing))
    name_w = max([5, *(len(r[0]) for r in rows)])
    state_w = max([5, *(len(r[1]) for r in rows)])
    print(f"{'STAGE':<{name_w}}  {'STATE':<{state_w}}  URL / failing checks")
    for name, state, url, failing in rows:
        summary = ", ".join(c.get("name", "?") for c in failing)
        print(f"{name:<{name_w}}  {state:<{state_w}}  {url}"
              + (f"  [{summary}]" if summary else ""))
        if deep:
            for check in failing:
                print(f"{'':<{name_w}}  {'':<{state_w}}    "
                      f"{check.get('name', '?')}: {check.get('detail', '')}")
    return exit_code


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="detectmate-client", description="Admin client for DetectMate TPU services"
    )
    parser.add_argument("--url", default="http://127.0.0.1:8000", help="service admin URL")
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("start")
    sub.add_parser("stop")
    sub.add_parser("shutdown")
    sub.add_parser("status")
    sub.add_parser("metrics")
    sub.add_parser("checkpoint")
    health = sub.add_parser(
        "health",
        help="pipeline health roll-up across stages (/admin/health)")
    health.add_argument(
        "targets", nargs="*",
        help="stage admin URLs, per-stage settings YAMLs, or a pipeline "
             "YAML with a 'stages: {name: url}' mapping; none = --url only")
    health.add_argument("--deep", action="store_true",
                        help="print per-check detail for failing stages")
    events_p = sub.add_parser(
        "events", help="read the structured event ring (/admin/events)")
    events_p.add_argument("--limit", type=int, default=None,
                          help="only the newest N events")
    trace = sub.add_parser(
        "trace", help="read the pipeline flight recorder (/admin/trace)")
    trace.add_argument("--chrome", action="store_true",
                       help="fetch Chrome trace-event JSON (Perfetto-loadable)")
    trace.add_argument("-o", "--out",
                       help="write the result to a file instead of stdout")
    reconf = sub.add_parser("reconfigure")
    reconf.add_argument("config_file", help="YAML file with the new component config")
    reconf.add_argument("--persist", action="store_true")
    args = parser.parse_args(argv)

    client = DetectMateClient(args.url)
    try:
        if args.command == "health":
            return health_rollup(args.url, args.targets, deep=args.deep)
        if args.command == "events":
            result = client.events(limit=args.limit)
        elif args.command == "reconfigure":
            with open(args.config_file, "r", encoding="utf-8") as fh:
                config = yaml.safe_load(fh) or {}
            result = client.reconfigure(config, persist=args.persist)
        elif args.command == "trace":
            result = client.trace(chrome=args.chrome)
            if args.out:
                with open(args.out, "w", encoding="utf-8") as fh:
                    json.dump(result, fh, indent=2)
                print(f"wrote {args.out}")
                return 0
        else:
            result = getattr(client, args.command)()
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    except (urllib.error.URLError, OSError) as exc:
        print(f"request failed: {exc}", file=sys.stderr)
        return 1
    if isinstance(result, str):
        print(result)
    else:
        print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
