"""Replica supervision: probe, state machine inputs, watermark acks.

Thread topology (the part that keeps the router's lock discipline simple):

* the **engine thread** owns every replica socket — dispatch, requeue
  resends, and re-dials all happen there (``ReplicaRouter.dispatch/tick``);
* the **supervisor thread** (this module) only does blocking HTTP I/O —
  ``GET /admin/health?deep=1`` and a ``/metrics`` watermark read per
  replica per interval — and hands each :class:`ProbeResult` to
  ``ReplicaRouter.apply_probe``, which runs the state machine under the
  router lock. The supervisor never touches a socket.

States (exported as the ``router_replica_state`` gauge):

* ``ACTIVE (3)``     — dispatchable.
* ``RECOVERING (2)`` — probe dispatchable again after a drain; whatever
  was still unacked is requeued at this transition (the re-dial drops the
  old socket's buffered frames — at-least-once), the engine re-dials the
  replica, and it must stay dispatchable for ``RECOVERY_POLLS``
  consecutive polls before dispatch resumes (fail fast, recover slow —
  same hysteresis shape as the watchdog).
* ``DRAINING (1)``   — probe went unhealthy/unreachable (or an operator
  posted a drain): new dispatch stopped, in-flight frames get
  ``router_drain_timeout_s`` to settle via the ack watermark. A merely
  "degraded" probe never drains — deep health reports degraded for
  transient/benign conditions (and a drained replica is ingest-stalled
  by construction), so degraded counts as dispatchable throughout.
* ``DRAINED (0)``    — settled (window emptied) or timed out (window moved
  to the requeue queue for redelivery to healthy peers — at-least-once).

The **warm-up gate** (dmwarm): a scorer replica registers the
``scorer_warmup_pending`` deep-health check at the top of ``setup_io``
(``engine/device_obs.WarmupPendingCheck``), and it reports UNHEALTHY —
not degraded — until the warm bucket set is AOT-compiled and
``mark_warmup_complete`` lands. Because this supervisor's verdict is the
deep-health state, a booting replica stays out of dispatch until its warm
set is compiled: scale-out never routes a frame onto a replica whose
first dispatch would pay a synchronous XLA compile. No router-side code
is warm-up-aware; the gate rides the existing unhealthy→no-dispatch
state machine.

The **ack watermark**: the router counts lines dispatched per replica; the
probe reads the replica's cumulative ``data_read_lines_total`` from its
``/metrics``. Because each replica has exactly ONE feeder (this router —
the tier topology guarantees it), the replica's read counter advancing by
N lines acks the oldest N dispatched lines, so the head of the unacked
window pops exactly. The baseline is captured at the first successful
poll, which UNDER-acks anything the replica read before that poll — the
safe direction: an under-acked frame is at worst redelivered (duplicate
scoring), never silently dropped from the window (loss). A replica
restart invalidates the anchor; it is detected two ways — the counter
running BACKWARD, and the deep-health report's ``started_unix`` changing
(which also catches a restart whose new counter already passed the old
baseline) — and either way the window requeues and the baseline re-arms.
"""
from __future__ import annotations

import json
import logging
import re
import threading
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, List, Optional, Tuple

from ..engine import metrics as m

if TYPE_CHECKING:  # the annotation types the seam for mypy AND dmlint's
    from .router import ReplicaRouter  # affinity receiver inference

STATE_DRAINED = 0
STATE_DRAINING = 1
STATE_RECOVERING = 2
STATE_ACTIVE = 3
STATE_NAMES = {
    STATE_DRAINED: "drained",
    STATE_DRAINING: "draining",
    STATE_RECOVERING: "recovering",
    STATE_ACTIVE: "active",
}

# consecutive healthy polls a recovering replica needs before dispatch
# resumes (the watchdog's recover-slow default)
RECOVERY_POLLS = 2


def _fnv64(text: str) -> int:
    value = 0xCBF29CE484222325
    for byte in text.encode("utf-8"):
        value = ((value ^ byte) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return value


@dataclass
class ProbeResult:
    """One supervision poll of one replica."""

    status: str                       # "healthy" | "degraded" | "unhealthy" | "unreachable"
    detail: str = ""
    backlog: Optional[float] = None   # replica's engine_ingress_backlog
    read_lines: Optional[float] = None  # replica's cumulative data_read_lines_total
    component_id: Optional[str] = None
    started_unix: Optional[float] = None  # replica process start time (restart signal)
    capacity: Optional[float] = None  # replica's replica_capacity_lines_per_s


class Replica:
    """One downstream replica: its socket, supervision state, and the
    unacked credit window. All mutable fields are guarded by the OWNING
    router's lock (``ReplicaRouter._lock``); metric children are resolved
    once here so the dispatch hot path never calls ``.labels()``."""

    def __init__(self, index: int, addr: str, admin_url: Optional[str],
                 labels: dict, policy_name: str) -> None:
        self.index = index
        self.addr = addr
        self.admin_url = admin_url.rstrip("/") if admin_url else None
        self.id_hash = _fnv64(addr)          # rendezvous-hash identity
        # dmlint: thread(engine)
        self.sock = None
        self.state = STATE_ACTIVE
        self.state_detail = "never probed"
        self.backlog = 0.0
        self.capacity: Optional[float] = None  # dmdrift calibrated lines/s
        # unacked credit window: (lines, wire) FIFO; maxlen is enforced by
        # the dispatchable() credit check, not the deque, so a full window
        # backpressures instead of silently evicting unacked frames
        self.window: deque = deque()
        self.window_head_lines = 0.0     # cumulative lines of popped entries
        self.sent_lines = 0.0            # cumulative lines dispatched
        self.acked_lines = 0.0           # watermark-confirmed lines
        self.read_base: Optional[float] = None  # replica counter at 1st poll
        self.started_unix: Optional[float] = None  # last seen process start time
        self.component_id: Optional[str] = None
        self.frames_total = 0
        self.requeued_total = 0
        self.send_failures = 0
        self.healthy_streak = 0
        self.needs_redial = False
        self.drain_deadline: Optional[float] = None
        self.manual_drain = False
        self._m_frames = m.ROUTER_FRAMES().labels(
            replica=addr, policy=policy_name, **labels)
        self._m_state = m.ROUTER_REPLICA_STATE().labels(replica=addr, **labels)
        self._m_inflight = m.ROUTER_INFLIGHT().labels(replica=addr, **labels)
        self._m_state.set(self.state)
        self._m_inflight.set(0)

    @property
    def inflight(self) -> int:
        """Unacked frames outstanding (the credit window's fill)."""
        return len(self.window)

    # -- accounting helpers (caller holds the router lock) ---------------
    def note_sent(self, lines: int) -> None:
        self.frames_total += 1
        self.sent_lines += lines
        self._m_frames.inc()
        self._m_inflight.set(len(self.window))

    def set_state(self, state: int, detail: str) -> None:
        self.state = state
        self.state_detail = detail
        self._m_state.set(state)

    def apply_watermark(self, read_lines: float) -> None:
        """Advance the ack watermark from the replica's cumulative read
        counter and pop fully-covered window heads."""
        if self.read_base is None:
            # first observation (or re-arm after ``note_restart``): anchor
            # so the delta continues from the current acked level —
            # everything read before this poll is under-acked, the safe
            # side (at the initial anchor ``acked_lines`` is 0, so this is
            # exactly "baseline = current reading")
            self.read_base = read_lines - self.acked_lines
            return
        if read_lines < self.read_base:
            # counter reset (replica process restarted): re-anchor; frames
            # in the window stay unacked and ride the drain/requeue path
            self.read_base = read_lines - self.acked_lines
        self.acked_lines = min(self.sent_lines,
                               max(self.acked_lines,
                                   read_lines - self.read_base))
        while (self.window and self.window_head_lines + self.window[0][0]
               <= self.acked_lines):
            lines, _wire = self.window.popleft()
            self.window_head_lines += lines
        self._m_inflight.set(len(self.window))

    def note_restart(self) -> List[Tuple[int, bytes]]:
        """The probe observed a process restart (start-time change): every
        in-flight frame is gone with the old process, and the read counter
        restarted — possibly already past the old baseline, which is why
        counter monotonicity alone cannot detect this. Empty the window for
        the caller to requeue and re-baseline at the next watermark sample
        (under-acks the interim, the safe side)."""
        taken = self.take_window()
        self.read_base = None
        return taken

    def take_window(self) -> List[Tuple[int, bytes]]:
        """Move every unacked frame out (drain timeout): the caller
        redelivers them to healthy peers."""
        taken = list(self.window)
        for lines, _wire in taken:
            self.window_head_lines += lines
        self.window.clear()
        self.acked_lines = self.sent_lines
        self._m_inflight.set(0)
        return taken

    def snapshot(self) -> dict:
        return {
            "addr": self.addr,
            "admin_url": self.admin_url,
            "state": STATE_NAMES[self.state],
            "state_value": self.state,
            "detail": self.state_detail,
            "backlog": self.backlog,
            "capacity_lines_per_s": self.capacity,
            "inflight": len(self.window),
            "frames_total": self.frames_total,
            "requeued_total": self.requeued_total,
            "sent_lines": self.sent_lines,
            "acked_lines": self.acked_lines,
            "send_failures": self.send_failures,
            "component_id": self.component_id,
        }


# -- the default HTTP probe --------------------------------------------------

# one compiled matcher per poll loop, not per line: value rows of the
# series the probe reads off the replica's exposition (ack watermark,
# ingress backlog, and the dmdrift capacity model's calibrated rate)
_SERIES_ROW_RE = re.compile(
    r'^(data_read_lines_total|engine_ingress_backlog|'
    r'replica_capacity_lines_per_s)\{([^}]*)\}\s+([0-9.eE+-]+)',
    re.M)
_CID_RE = re.compile(r'component_id="([^"]*)"')


class HttpProbe:
    """Poll one replica's admin plane: deep health for the verdict, then a
    ``/metrics`` read for the ack watermark + ingress backlog (filtered to
    the replica's own ``component_id``, learned from the health report —
    in-process fleets share one registry, so the filter is load-bearing)."""

    def __init__(self, timeout_s: float = 2.0) -> None:
        self._timeout = timeout_s

    def __call__(self, replica: Replica) -> ProbeResult:
        if not replica.admin_url:
            return ProbeResult("healthy", "no admin_url: send-failure "
                                          "supervision only")
        try:
            report = self._get_json(replica.admin_url
                                    + "/admin/health?deep=1")
        except urllib.error.HTTPError as exc:
            # a 503 IS an answer: the body carries the failing-check report
            try:
                report = json.loads(exc.read())
            except (json.JSONDecodeError, OSError):
                return ProbeResult("unhealthy", f"deep health HTTP {exc.code}")
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            return ProbeResult("unreachable", str(exc))
        status = str(report.get("state", "unknown"))
        if status not in ("healthy", "degraded", "unhealthy"):
            status = "unhealthy"
        failing = [c.get("name", "?") for c in report.get("checks", [])
                   if c.get("status") != "pass"]
        detail = ", ".join(failing) if failing else "all checks passing"
        cid = report.get("component_id") or replica.component_id
        started = report.get("started_unix")
        backlog, read_lines, capacity = self._watermark(replica, cid)
        return ProbeResult(status, detail, backlog=backlog,
                           read_lines=read_lines, component_id=cid,
                           started_unix=(float(started)
                                         if started is not None else None),
                           capacity=capacity)

    def _get_json(self, url: str) -> Any:
        with urllib.request.urlopen(url, timeout=self._timeout) as resp:
            return json.loads(resp.read())

    def _watermark(self, replica: Replica, cid: Optional[str]
                   ) -> Tuple[Optional[float], Optional[float],
                              Optional[float]]:
        if not cid:
            return None, None, None
        try:
            with urllib.request.urlopen(replica.admin_url + "/metrics",
                                        timeout=self._timeout) as resp:
                text = resp.read().decode("utf-8", errors="replace")
        except (urllib.error.URLError, OSError, TimeoutError):
            return None, None, None
        backlog = read_lines = capacity = None
        for name, labels, value in _SERIES_ROW_RE.findall(text):
            cid_match = _CID_RE.search(labels)
            if cid_match is None or cid_match.group(1) != cid:
                continue
            if name == "engine_ingress_backlog":
                backlog = float(value)
            elif name == "replica_capacity_lines_per_s":
                capacity = float(value)
            else:
                read_lines = (read_lines or 0.0) + float(value)
        return backlog, read_lines, capacity


class ReplicaSupervisor(threading.Thread):
    """The polling thread: probe every replica each interval and hand the
    results to ``router.apply_probe`` (which owns the state machine). A
    probe that raises is itself an ``unreachable`` verdict — the supervisor
    must outlive a misbehaving replica admin plane."""

    def __init__(self, router: "ReplicaRouter", interval_s: float,
                 probe: Optional[Callable[[Replica], ProbeResult]] = None,
                 logger: Optional[logging.Logger] = None) -> None:
        super().__init__(name="ReplicaSupervisor", daemon=True)
        self._router = router
        self._interval = interval_s
        self._probe = probe or HttpProbe(timeout_s=min(2.0, interval_s))
        self._logger = logger or logging.getLogger("router.supervisor")
        self._halt = threading.Event()

    # blocking HTTP + state handoffs only; this supervision thread NEVER
    # touches a socket (DM-A003 enforces it)
    # dmlint: thread(supervisor)
    def poll_once(self) -> None:
        for replica in self._router.replicas:
            try:
                result = self._probe(replica)
            except Exception as exc:  # noqa: BLE001 — probe crash == unreachable
                result = ProbeResult("unreachable", f"probe crashed: {exc!r}")
            self._router.apply_probe(replica, result)
        self._router.process_drains()

    # dmlint: thread(supervisor)
    def run(self) -> None:
        # dmlint: hot-loop
        while not self._halt.wait(self._interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — supervision must not die silently
                self._logger.exception("replica supervision poll failed")

    def stop(self) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=5.0)
