"""The replica router the engine embeds when ``router_replicas`` is set.

Instead of duplicating every outgoing frame to all peers (the engine's
fan-out contract), the router delivers each frame to exactly ONE healthy
downstream replica, chosen by the configured :mod:`balancer` policy, under
per-replica credit flow control. Replica health comes from the
:class:`~detectmateservice_tpu.router.supervisor.ReplicaSupervisor` (deep
health + ack watermark polls) and from send failures observed inline; a
failed replica is drained — dispatch stops, in-flight frames get
``router_drain_timeout_s`` to settle, what stays unacked is requeued to
healthy peers (at-least-once) — and re-dialed when its probe recovers.

Threading contract (mirrors the engine's own design):

* **engine thread**: ``dispatch`` (per outgoing frame) and ``tick`` (per
  loop iteration) — the only code that touches replica sockets;
* **supervisor thread**: ``apply_probe`` / ``process_drains`` — state and
  bookkeeping only, never a socket;
* **admin threads**: ``snapshot`` / ``drain`` / ``undrain``.

All shared replica state is guarded by ``self._lock``; socket sends happen
strictly outside it. Structured events (``replica_drain`` /
``replica_drained`` / ``replica_recovering`` / ``replica_restarted`` /
``replica_undrain``) are
collected under the lock and emitted after release through the service's
``HealthMonitor.emit_event`` — the same ring ``/admin/events`` serves.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional

from ..engine import metrics as m
from ..engine.framing import peek_tenant_id, peek_trace_id
from ..engine.socket import TransportAgain, TransportError
from ..settings import TLS_SCHEME_PREFIXES, ServiceSettings
from ..utils.threadcheck import assert_affinity
from .balancer import StickyTracePolicy, make_policy
from .supervisor import (
    RECOVERY_POLLS,
    STATE_ACTIVE,
    STATE_DRAINED,
    STATE_DRAINING,
    STATE_NAMES,
    STATE_RECOVERING,
    ProbeResult,
    Replica,
    ReplicaSupervisor,
)

_RETRY_SLEEP_S = 0.01   # the engine's reference retry backoff


class ReplicaRouter:
    def __init__(
        self,
        settings: ServiceSettings,
        factory: Any,
        logger: Optional[logging.Logger] = None,
        labels: Optional[dict] = None,
        monitor: Optional[Any] = None,
        probe: Optional[Callable[[Replica], ProbeResult]] = None,
        abort_check: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.settings = settings
        self.logger = logger or logging.getLogger("router")
        self._factory = factory
        self._labels = dict(labels or dict(
            component_type=settings.component_type,
            component_id=settings.component_id or "router"))
        self._monitor = monitor
        self._abort = abort_check
        self._policy = make_policy(settings.router_policy)
        self._sticky = isinstance(self._policy, StickyTracePolicy)
        self._credit = settings.router_credit_window
        self._drain_timeout_s = settings.router_drain_timeout_s
        self._retry_count = settings.engine_retry_count
        self._block = settings.out_backpressure == "block"
        self._lock = threading.RLock()
        self._requeue: deque = deque()       # (lines, wire) awaiting redelivery
        self._requeue_total = 0
        self._m_requeue = m.ROUTER_REQUEUE().labels(**self._labels)
        # dmdrift fleet aggregates: the supervisor probe reads each
        # replica's replica_capacity_lines_per_s off its exposition; the
        # router republishes the fleet sum (and dispatch-rate ÷ capacity)
        # under its own labels — the predictive scale-out signal
        self._m_capacity = m.REPLICA_CAPACITY().labels(**self._labels)
        self._m_headroom = m.CAPACITY_HEADROOM().labels(**self._labels)
        self._cap_rate_anchor: Optional[tuple] = None  # (t, total_sent_lines)

        admin_urls = list(settings.router_admin_urls or [])
        self.replicas: List[Replica] = []
        for index, addr in enumerate(settings.router_replicas):
            replica = Replica(
                index, addr,
                admin_urls[index] if index < len(admin_urls) else None,
                self._labels, self._policy.name)
            self.replicas.append(replica)
        try:
            for replica in self.replicas:
                replica.sock = self._dial(replica.addr)
        except Exception:
            self.close()
            raise

        # supervision runs when there is something to poll: admin URLs for
        # the HTTP probe, or an injected probe (tests, in-process fleets)
        self._supervisor: Optional[ReplicaSupervisor] = None
        if probe is not None or any(r.admin_url for r in self.replicas):
            self._supervisor = ReplicaSupervisor(
                self, settings.router_health_interval_s,
                probe=probe, logger=self.logger)
            self._supervisor.start()
        self.logger.info(
            "replica router up: %d replicas, policy=%s, credit_window=%d, "
            "drain_timeout=%.1fs, supervision=%s",
            len(self.replicas), self._policy.name, self._credit,
            self._drain_timeout_s,
            "on" if self._supervisor is not None else "send-failure only")

    def _dial(self, addr: str) -> Any:
        is_tls = addr.startswith(TLS_SCHEME_PREFIXES)
        return self._factory.create_output(
            addr, self.logger,
            self.settings.tls_output if is_tls else None,
            dial_timeout=self.settings.out_dial_timeout,
            buffer_size=self.settings.engine_buffer_size)

    # -- engine-thread API (machine-checked: # dmlint: thread pragmas) ----
    # dmlint: thread(engine)
    def dispatch(self, wire: bytes, lines: int) -> bool:
        """Deliver one wire frame to one replica. True when it left the
        process; False when it had to be dropped (no dispatchable replica
        within the backpressure budget). Runs on the engine hot path: one
        lock acquire per pick, sends outside the lock."""
        assert_affinity("engine")
        trace_id = peek_trace_id(wire) if self._sticky else None
        # one startswith probe for tenant-unattributed frames — the policy's
        # tenant tie-break (least_backlog) spreads a hot tenant's frames
        # across equally-loaded replicas (dmshed)
        tenant = peek_tenant_id(wire)
        retries = 0
        tried: set = set()
        while True:
            with self._lock:
                candidates = [r for r in self.replicas
                              if r.state == STATE_ACTIVE
                              and r.sock is not None
                              and len(r.window) < self._credit
                              and r.index not in tried]
                choice = self._policy.pick(candidates, trace_id, tenant)
                sock = choice.sock if choice is not None else None
            if choice is None:
                # every dispatchable replica was tried (or none exists):
                # behave per the engine's backpressure contract
                tried.clear()
                if self._abort is not None and self._abort():
                    return False
                if self._block:
                    time.sleep(0.001)    # flow control, stop-aware via abort
                    continue
                retries += 1
                if retries >= self._retry_count:
                    return False
                time.sleep(_RETRY_SLEEP_S)
                continue
            try:
                sock.send(wire, block=False)
            except TransportAgain:
                # transport buffer full: that replica is saturated right
                # now — try the next one immediately, no backoff
                tried.add(choice.index)
                continue
            except TransportError as exc:
                self._fail_replica(choice, f"send failed: {exc}")
                tried.add(choice.index)
                continue
            with self._lock:
                if choice.state in (STATE_ACTIVE, STATE_DRAINING):
                    choice.window.append((lines, wire))
                    choice.note_sent(lines)
                else:
                    # the supervisor settled this replica between our send
                    # and this append (DRAINING→DRAINED on an empty window,
                    # or a recovery took the window): a frame parked in a
                    # settled window is never requeued — queue it for
                    # redelivery instead (a duplicate beats a loss)
                    self._requeue.append((lines, wire))
            return True

    # dmlint: thread(any) — one lock acquire + two scans, no socket
    def unacked_total(self) -> int:
        """Frames dispatched but not yet watermark-settled, plus requeued
        frames awaiting redelivery. The durable-ingress spool gates its ack
        watermark on this hitting zero: a spool sequence only acks once the
        replica tier holds nothing of it (wal/spool.py ack semantics)."""
        with self._lock:
            return (sum(len(r.window) for r in self.replicas)
                    + len(self._requeue))

    # dmlint: thread(engine)
    def tick(self) -> None:
        """Deferred engine-thread work: re-dial recovered replicas, enforce
        drain deadlines when no supervisor polls, redeliver requeued
        frames. Called once per engine loop iteration — the no-work path is
        one lock acquire and three cheap scans."""
        assert_affinity("engine")
        with self._lock:
            redials = [r for r in self.replicas if r.needs_redial]
            work = bool(self._requeue) or bool(redials) or any(
                r.state == STATE_DRAINING for r in self.replicas)
        if not work:
            return
        for replica in redials:
            old_sock = None
            try:
                sock = self._dial(replica.addr)
            except Exception as exc:  # noqa: BLE001 — tick() runs unguarded
                # on the engine hot loop: ANY dial failure (TransportError,
                # a ValueError on a bad address, raw OSError variants) must
                # retry next tick, not kill the EngineLoop thread
                self.logger.warning("re-dial of replica %s failed: %s "
                                    "(will retry)", replica.addr, exc)
                continue
            with self._lock:
                old_sock, replica.sock = replica.sock, sock
                replica.needs_redial = False
                # without a supervisor there is no probe to promote a
                # recovering replica — the successful re-dial is the best
                # available signal, so dispatch resumes here
                if (self._supervisor is None
                        and replica.state == STATE_RECOVERING):
                    replica.set_state(STATE_ACTIVE, "re-dialed (unsupervised)")
            if old_sock is not None:
                try:
                    old_sock.close()
                except TransportError:
                    pass
        if self._supervisor is None:
            self.process_drains()
        self._drain_requeue()

    def _drain_requeue(self) -> None:
        """Redeliver queued frames to healthy replicas — one non-blocking
        pass; what cannot go now stays queued for the next tick. Only the
        engine thread pops, so peek-then-pop is race-free."""
        while True:
            with self._lock:
                if not self._requeue:
                    return
                lines, wire = self._requeue[0]
                candidates = [r for r in self.replicas
                              if r.state == STATE_ACTIVE
                              and r.sock is not None
                              and len(r.window) < self._credit]
                choice = self._policy.pick(
                    candidates,
                    peek_trace_id(wire) if self._sticky else None,
                    peek_tenant_id(wire))
                sock = choice.sock if choice is not None else None
            if choice is None:
                return
            try:
                sock.send(wire, block=False)
            except TransportAgain:
                return                      # retry on the next tick
            except TransportError as exc:
                self._fail_replica(choice, f"requeue send failed: {exc}")
                continue
            with self._lock:
                self._requeue.popleft()
                if choice.state in (STATE_ACTIVE, STATE_DRAINING):
                    choice.window.append((lines, wire))
                    choice.note_sent(lines)
                    choice.requeued_total += 1
                    self._requeue_total += 1
                    self._m_requeue.inc()
                else:
                    # supervisor settled the replica between send and
                    # append — keep the frame queued (the wire copy may
                    # still land: at-least-once tolerates the duplicate)
                    self._requeue.append((lines, wire))

    # teardown runs on the stopping thread after the engine thread is
    # dmlint: thread(any) — joined (the join is the happens-before edge)
    def close(self) -> None:
        if self._supervisor is not None:
            self._supervisor.stop()
            self._supervisor = None
        for replica in self.replicas:
            sock, replica.sock = replica.sock, None
            if sock is not None:
                try:
                    sock.close()
                except TransportError:
                    pass

    # -- supervision inputs (supervisor thread / engine thread) ----------
    # state machine under the lock, no socket ops; designed to run from
    # the supervisor poll, the engine tick, and tests
    # dmlint: thread(any)
    def apply_probe(self, replica: Replica, result: ProbeResult) -> None:
        events: list = []
        with self._lock:
            if result.backlog is not None:
                replica.backlog = float(result.backlog)
            if result.capacity is not None:
                replica.capacity = float(result.capacity)
                self._update_capacity_aggregate_locked()
            if result.component_id:
                replica.component_id = result.component_id
            if result.started_unix is not None:
                if (replica.started_unix is not None
                        and result.started_unix != replica.started_unix):
                    # the replica process restarted between polls — even if
                    # its new read counter already exceeds the old baseline
                    # (so counter monotonicity alone cannot see it). Frames
                    # in flight at the restart are gone: requeue the whole
                    # window and re-baseline the watermark before applying
                    # this poll's reading (duplicates possible, loss not).
                    taken = replica.note_restart()
                    self._requeue.extend(taken)
                    events.append(self._event(
                        "replica_restarted", replica, requeued=len(taken),
                        detail="replica restart observed between polls; "
                               "watermark re-anchored"))
                replica.started_unix = result.started_unix
            if result.read_lines is not None:
                replica.apply_watermark(float(result.read_lines))
            # "degraded" is advisory, not a drain signal: deep health
            # reports it for transient/benign conditions (output briefly
            # blocked, loop beat lag, ingest stall — which a DRAINED
            # replica exhibits by construction, since it receives no
            # traffic). It neither drains nor blocks recovery; only
            # "unhealthy"/"unreachable" drain.
            dispatchable = result.status in ("healthy", "degraded")
            if replica.manual_drain:
                # the operator owns the state; the watermark above still
                # advances so an operator drain settles cleanly
                replica.state_detail = (f"operator drain "
                                        f"(probe: {result.status})")
            elif dispatchable:
                replica.healthy_streak += 1
                if replica.state in (STATE_DRAINING, STATE_DRAINED):
                    # at-least-once: the re-dial below closes the old
                    # socket (dropping any frames buffered in it), and a
                    # restarted replica re-anchors the watermark — so the
                    # unacked window must be requeued NOW, not kept
                    taken = replica.take_window()
                    self._requeue.extend(taken)
                    replica.set_state(STATE_RECOVERING,
                                      "probe dispatchable again; re-dialing")
                    replica.healthy_streak = 1
                    replica.drain_deadline = None
                    replica.needs_redial = True
                    events.append(self._event(
                        "replica_recovering", replica, requeued=len(taken),
                        detail=f"probe {result.status}; awaiting re-dial + "
                               f"{RECOVERY_POLLS} clean polls"))
                elif (replica.state == STATE_RECOVERING
                        and replica.healthy_streak >= RECOVERY_POLLS
                        and not replica.needs_redial
                        and replica.sock is not None):
                    replica.set_state(STATE_ACTIVE, "recovered")
                    replica.send_failures = 0
                    events.append(self._event("replica_undrain", replica,
                                              detail="dispatch resumed"))
                elif replica.state == STATE_ACTIVE:
                    replica.state_detail = (
                        (result.detail or "healthy")
                        if result.status == "healthy"
                        else f"degraded: {result.detail}")
            else:
                replica.healthy_streak = 0
                if replica.state in (STATE_ACTIVE, STATE_RECOVERING):
                    self._begin_drain(
                        replica, f"{result.status}: {result.detail}", events)
                else:
                    replica.state_detail = (f"{result.status}: "
                                            f"{result.detail}")
        self._emit(events)

    # dmlint: thread(any) — same contract as apply_probe
    def process_drains(self, now: Optional[float] = None) -> None:
        """Settle or expire draining replicas: an emptied window is a clean
        drain; a window still unacked at the deadline moves to the requeue
        queue for redelivery (at-least-once)."""
        events: list = []
        with self._lock:
            now = time.monotonic() if now is None else now
            for replica in self.replicas:
                if replica.state != STATE_DRAINING:
                    continue
                if not replica.window:
                    replica.set_state(STATE_DRAINED,
                                      "drained clean (in-flight settled)")
                    replica.drain_deadline = None
                    events.append(self._event("replica_drained", replica,
                                              requeued=0))
                elif (replica.drain_deadline is not None
                        and now >= replica.drain_deadline):
                    taken = replica.take_window()
                    self._requeue.extend(taken)
                    replica.set_state(
                        STATE_DRAINED,
                        f"drain timeout: {len(taken)} unacked frames "
                        "requeued to healthy peers")
                    replica.drain_deadline = None
                    events.append(self._event("replica_drained", replica,
                                              requeued=len(taken)))
        self._emit(events)

    def _fail_replica(self, replica: Replica, detail: str) -> None:
        events: list = []
        with self._lock:
            replica.send_failures += 1
            if replica.state in (STATE_ACTIVE, STATE_RECOVERING):
                self._begin_drain(replica, detail, events)
        self._emit(events)

    def _begin_drain(self, replica: Replica, reason: str,
                     events: list) -> None:
        """Caller holds the lock."""
        replica.set_state(STATE_DRAINING, reason)
        replica.drain_deadline = time.monotonic() + self._drain_timeout_s
        events.append(self._event(
            "replica_drain", replica, reason=reason,
            inflight=len(replica.window),
            drain_timeout_s=self._drain_timeout_s))

    # -- admin-plane API --------------------------------------------------
    # dmlint: thread(admin)
    def drain(self, addr: str) -> dict:
        """Operator drain: stop dispatching to ``addr`` now; in-flight
        frames settle (or requeue at the deadline) exactly like a
        supervisor-initiated drain, but the replica stays down until an
        explicit ``undrain`` — probes cannot resurrect it."""
        replica = self._find(addr)
        events: list = []
        with self._lock:
            replica.manual_drain = True
            if replica.state in (STATE_ACTIVE, STATE_RECOVERING):
                self._begin_drain(replica, "operator drain", events)
        self._emit(events)
        self.process_drains()
        with self._lock:
            return replica.snapshot()

    # dmlint: thread(admin)
    def undrain(self, addr: str) -> dict:
        replica = self._find(addr)
        events: list = []
        with self._lock:
            replica.manual_drain = False
            replica.healthy_streak = 0
            if replica.state in (STATE_DRAINED, STATE_DRAINING):
                # same at-least-once rule as probe-driven recovery: the
                # re-dial drops the old socket's buffered frames, so the
                # unacked window is requeued rather than kept
                taken = replica.take_window()
                self._requeue.extend(taken)
                replica.set_state(STATE_RECOVERING,
                                  "operator undrain; re-dialing")
                replica.drain_deadline = None
                replica.needs_redial = True
                events.append(self._event(
                    "replica_recovering", replica, requeued=len(taken),
                    detail="operator undrain; awaiting re-dial"))
        self._emit(events)
        with self._lock:
            return replica.snapshot()

    def _update_capacity_aggregate_locked(self) -> None:
        """Republish fleet capacity + headroom from the per-replica probe
        readings: fleet capacity is the sum over replicas that reported
        one, offered rate is the router's own dispatch rate differenced
        between aggregate updates (probe cadence — no hot-path cost)."""
        caps = [r.capacity for r in self.replicas if r.capacity]
        if not caps:
            return
        fleet = float(sum(caps))
        now = time.monotonic()
        total_sent = float(sum(r.sent_lines for r in self.replicas))
        anchor = self._cap_rate_anchor
        self._cap_rate_anchor = (now, total_sent)
        self._m_capacity.set(fleet)
        if anchor is not None and now > anchor[0] and fleet > 0:
            offered = max(0.0, total_sent - anchor[1]) / (now - anchor[0])
            self._m_headroom.set(offered / fleet)

    # dmlint: thread(any) — reads under the lock only
    def snapshot(self) -> dict:
        with self._lock:
            replicas = [r.snapshot() for r in self.replicas]
            caps = [r.capacity for r in self.replicas if r.capacity]
            return {
                "policy": self._policy.name,
                "credit_window": self._credit,
                "drain_timeout_s": self._drain_timeout_s,
                "supervised": self._supervisor is not None,
                "requeue_pending": len(self._requeue),
                "requeue_total": self._requeue_total,
                "replicas": replicas,
                "dispatchable": sum(
                    1 for r in replicas
                    if r["state"] == STATE_NAMES[STATE_ACTIVE]),
                "fleet_capacity_lines_per_s": (
                    round(float(sum(caps)), 3) if caps else None),
            }

    def _find(self, addr: str) -> Replica:
        for replica in self.replicas:
            if replica.addr == addr:
                return replica
        raise ValueError(f"no replica with address {addr!r}; configured: "
                         f"{[r.addr for r in self.replicas]}")

    # -- events ------------------------------------------------------------
    def _event(self, kind: str, replica: Replica, **extra: Any) -> dict:
        doc = {"kind": kind, "replica": replica.addr,
               "state": STATE_NAMES[replica.state]}
        doc.update(extra)
        return doc

    def _emit(self, events: list) -> None:
        for event in events:
            if self._monitor is not None:
                self._monitor.emit_event(event)
            else:
                self.logger.warning("router event %s: %s",
                                    event.get("kind"), event)
