"""Balancing policies: which dispatchable replica gets the next frame.

A policy is a pure choice function over the replicas the router already
filtered down to *dispatchable* (state ACTIVE, credit available) — health
and flow control are the supervisor's and router's jobs, not the policy's.
``pick`` runs once per outgoing wire frame on the engine hot loop, so
policies hold no locks and allocate nothing beyond what the choice needs.

* ``round_robin``   — rotate; the baseline fairness policy.
* ``least_backlog`` — the default: route to the replica with the fewest
  unacked frames in its credit window, ties broken by the last-polled
  ingress backlog (``engine_ingress_backlog`` piggybacked on the
  supervisor's watermark poll), then by the frame's tenant's recent
  dispatch spread, then by rotation. Lexicographic on purpose: inflight
  is the router's OWN live knowledge in frames, backlog a stale poll in
  messages — summing them lets hundreds of backlog messages drown out the
  signal that actually predicts queueing, the unacked window. The tenant
  tie-break (dmshed) spreads ONE tenant's frames across equally-loaded
  replicas, so a hot tenant queues behind the fleet, not behind itself —
  accounting is per bounded tenant bucket (crc32, like the metric
  labels), decayed so it tracks RECENT traffic, never history. Under
  even replicas and no tenant attribution this degenerates to round
  robin; under a slow replica it shifts traffic away *before* the credit
  window hard-stops dispatch.
* ``sticky_trace``  — rendezvous (highest-random-weight) hash of the PR-1
  trace id over the replica set: one source's frames stay on one replica
  (per-source ordering holds there) while it is dispatchable, and only
  that replica's traces re-home when membership changes — no global
  reshuffle on a drain.
"""
from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple


class RoundRobinPolicy:
    name = "round_robin"

    def __init__(self) -> None:
        self._next = 0

    def pick(self, replicas: Sequence, trace_id: Optional[int],
             tenant: Optional[str] = None) -> Optional[Any]:
        if not replicas:
            return None
        choice = replicas[self._next % len(replicas)]
        self._next = (self._next + 1) % (1 << 30)
        return choice


class LeastBacklogPolicy:
    name = "least_backlog"

    # tenant accounting is bounded by construction: counts live per
    # (tenant bucket, replica index), never per raw tenant id, and are
    # halved every _DECAY_EVERY attributed picks so the table reflects
    # recent traffic (an idle tenant's history cannot skew a later choice)
    _TENANT_BUCKETS = 32
    _DECAY_EVERY = 256

    def __init__(self) -> None:
        self._next = 0
        self._picks = 0
        self._recent: Dict[Tuple[int, int], int] = {}

    def pick(self, replicas: Sequence, trace_id: Optional[int],
             tenant: Optional[str] = None) -> Optional[Any]:
        if not replicas:
            return None
        # rotating start index breaks ties fairly without a second pass
        start = self._next % len(replicas)
        self._next = (self._next + 1) % (1 << 30)
        bucket = (None if tenant is None else
                  zlib.crc32(tenant.encode("utf-8")) % self._TENANT_BUCKETS)
        best = None
        best_load = None
        for i in range(len(replicas)):
            replica = replicas[(start + i) % len(replicas)]
            recent = (0 if bucket is None else
                      self._recent.get((bucket, replica.index), 0))
            load = (replica.inflight, replica.backlog, recent)
            if best_load is None or load < best_load:
                best, best_load = replica, load
        if bucket is not None and best is not None:
            key = (bucket, best.index)
            self._recent[key] = self._recent.get(key, 0) + 1
            self._picks += 1
            if self._picks >= self._DECAY_EVERY:
                self._picks = 0
                self._recent = {k: v >> 1
                                for k, v in self._recent.items() if v > 1}
        return best


def _mix64(value: int) -> int:
    """splitmix64 finalizer: cheap, well-distributed 64-bit mixing for the
    rendezvous weights (no hashlib call per frame per replica)."""
    value = (value ^ (value >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
    value = (value ^ (value >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
    return value ^ (value >> 31)


class StickyTracePolicy:
    name = "sticky_trace"

    def __init__(self) -> None:
        # untraced frames (no v2 header) cannot stick — rotate them
        self._fallback = RoundRobinPolicy()

    def pick(self, replicas: Sequence, trace_id: Optional[int],
             tenant: Optional[str] = None) -> Optional[Any]:
        if not replicas:
            return None
        if trace_id is None:
            return self._fallback.pick(replicas, None)
        best = None
        best_weight = -1
        for replica in replicas:
            weight = _mix64(trace_id ^ replica.id_hash)
            if weight > best_weight:
                best, best_weight = replica, weight
        return best


_POLICIES = {
    RoundRobinPolicy.name: RoundRobinPolicy,
    LeastBacklogPolicy.name: LeastBacklogPolicy,
    StickyTracePolicy.name: StickyTracePolicy,
}

POLICY_NAMES: List[str] = sorted(_POLICIES)


def make_policy(name: str) -> Any:
    try:
        return _POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown router policy {name!r}; expected one of {POLICY_NAMES}"
        ) from None
