"""Balancing policies: which dispatchable replica gets the next frame.

A policy is a pure choice function over the replicas the router already
filtered down to *dispatchable* (state ACTIVE, credit available) — health
and flow control are the supervisor's and router's jobs, not the policy's.
``pick`` runs once per outgoing wire frame on the engine hot loop, so
policies hold no locks and allocate nothing beyond what the choice needs.

* ``round_robin``   — rotate; the baseline fairness policy.
* ``least_backlog`` — the default: route to the replica with the fewest
  unacked frames in its credit window, ties broken by the last-polled
  ingress backlog (``engine_ingress_backlog`` piggybacked on the
  supervisor's watermark poll), then by rotation. Lexicographic on
  purpose: inflight is the router's OWN live knowledge in frames, backlog
  a stale poll in messages — summing them lets hundreds of backlog
  messages drown out the signal that actually predicts queueing, the
  unacked window. Under even replicas this degenerates to round robin;
  under a slow replica it shifts traffic away *before* the credit window
  hard-stops dispatch.
* ``sticky_trace``  — rendezvous (highest-random-weight) hash of the PR-1
  trace id over the replica set: one source's frames stay on one replica
  (per-source ordering holds there) while it is dispatchable, and only
  that replica's traces re-home when membership changes — no global
  reshuffle on a drain.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence


class RoundRobinPolicy:
    name = "round_robin"

    def __init__(self) -> None:
        self._next = 0

    def pick(self, replicas: Sequence, trace_id: Optional[int]) -> Optional[Any]:
        if not replicas:
            return None
        choice = replicas[self._next % len(replicas)]
        self._next = (self._next + 1) % (1 << 30)
        return choice


class LeastBacklogPolicy:
    name = "least_backlog"

    def __init__(self) -> None:
        self._next = 0

    def pick(self, replicas: Sequence, trace_id: Optional[int]) -> Optional[Any]:
        if not replicas:
            return None
        # rotating start index breaks ties fairly without a second pass
        start = self._next % len(replicas)
        self._next = (self._next + 1) % (1 << 30)
        best = None
        best_load = None
        for i in range(len(replicas)):
            replica = replicas[(start + i) % len(replicas)]
            load = (replica.inflight, replica.backlog)
            if best_load is None or load < best_load:
                best, best_load = replica, load
        return best


def _mix64(value: int) -> int:
    """splitmix64 finalizer: cheap, well-distributed 64-bit mixing for the
    rendezvous weights (no hashlib call per frame per replica)."""
    value = (value ^ (value >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
    value = (value ^ (value >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
    return value ^ (value >> 31)


class StickyTracePolicy:
    name = "sticky_trace"

    def __init__(self) -> None:
        # untraced frames (no v2 header) cannot stick — rotate them
        self._fallback = RoundRobinPolicy()

    def pick(self, replicas: Sequence, trace_id: Optional[int]) -> Optional[Any]:
        if not replicas:
            return None
        if trace_id is None:
            return self._fallback.pick(replicas, None)
        best = None
        best_weight = -1
        for replica in replicas:
            weight = _mix64(trace_id ^ replica.id_hash)
            if weight > best_weight:
                best, best_weight = replica, weight
        return best


_POLICIES = {
    RoundRobinPolicy.name: RoundRobinPolicy,
    LeastBacklogPolicy.name: LeastBacklogPolicy,
    StickyTracePolicy.name: StickyTracePolicy,
}

POLICY_NAMES: List[str] = sorted(_POLICIES)


def make_policy(name: str) -> Any:
    try:
        return _POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown router policy {name!r}; expected one of {POLICY_NAMES}"
        ) from None
