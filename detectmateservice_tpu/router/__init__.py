"""Replica-parallel serving tier: one pipeline, N scorer replicas.

PAPER.md §0/§7 describes the production topology the reference only
gestures at — one parser feeding a *tier* of detector processes wired by
NNG addresses. This package is that tier's routing stage:

* :mod:`balancer` — pluggable dispatch policies (``least_backlog``,
  ``round_robin``, ``sticky_trace``),
* :mod:`supervisor` — per-replica health/state machine driven by each
  replica's ``/admin/health?deep=1`` and ingest watermark, with
  drain → requeue → re-dial semantics,
* :mod:`router` — the :class:`ReplicaRouter` the engine embeds when
  ``settings.router_replicas`` is non-empty.

The router is *just another stage*: it runs the same engine hot loop,
watchdog heartbeats, v2 trace stamping, and metrics registry as every
other component — ``router_frames_total`` / ``router_replica_state`` /
``router_requeue_total`` / ``router_inflight`` are REGISTERED_SERIES, so
dmlint's cross-artifact contracts (dashboard, alerts, docs) apply.
"""
from .balancer import (
    LeastBacklogPolicy,
    RoundRobinPolicy,
    StickyTracePolicy,
    make_policy,
)
from .router import ReplicaRouter
from .supervisor import (
    STATE_ACTIVE,
    STATE_DRAINED,
    STATE_DRAINING,
    STATE_RECOVERING,
    Replica,
    ReplicaSupervisor,
)

__all__ = [
    "LeastBacklogPolicy",
    "RoundRobinPolicy",
    "StickyTracePolicy",
    "make_policy",
    "ReplicaRouter",
    "Replica",
    "ReplicaSupervisor",
    "STATE_ACTIVE",
    "STATE_DRAINED",
    "STATE_DRAINING",
    "STATE_RECOVERING",
]
