"""Service settings: YAML + environment configuration with typed addresses.

Capability parity with the reference's ``ServiceSettings``
(reference: src/service/settings.py:40-173):

* typed transport URLs restricted to the schemes the data plane supports
  (reference: settings.py:31-37),
* ``DETECTMATE_``-prefixed environment overrides with ``__`` nesting, env
  winning over YAML per-field (reference: settings.py:80-84,134-173),
* deterministic UUIDv5 component identity, stable across restarts
  (reference: settings.py:93-114),
* TLS cross-field validation failing at startup (reference: settings.py:116-132).

This build has no ``pydantic_settings`` dependency; the env layer is a small
explicit merge, which is what the reference's ``from_yaml`` does anyway.

TPU-build additions (not in the reference): micro-batching knobs
(``engine_batch_size``, ``engine_batch_timeout_ms``), accelerator backend
selection, and mesh shape for multi-chip scale-out.
"""
from __future__ import annotations

import json
import os
import sys
import uuid
from typing import Annotated, Any, Dict, List, Mapping, Optional

import yaml
from pydantic import (
    AfterValidator,
    BaseModel,
    ConfigDict,
    Field,
    ValidationError,
    model_validator,
)

ENV_PREFIX = "DETECTMATE_"
ENV_NESTED_DELIMITER = "__"

# nng+tcp / nng+tls+tcp are TPU-build additions beyond the reference scheme
# set: the NNG SP Pair0 wire protocol over plain TCP (resp. inside a real TLS
# session — byte-compatible with NNG's mbedTLS ``tls+tcp`` transport), so real
# NNG/fluentd peers can dial this data plane, encrypted or not
# (engine/socket.py NngTcpSocketFactory / NngTlsTcpSocketFactory).
SUPPORTED_SCHEMES = ("ipc", "tcp", "tls+tcp", "nng+tcp", "nng+tls+tcp", "ws",
                     "inproc")

# The TLS-bearing scheme prefixes. ONE home, used by both settings
# cross-validation (material must exist) and the engine's socket setup
# (material must be FORWARDED to the factory) — those two drifted once,
# breaking every encrypted NNG output at dial while validation passed.
TLS_SCHEME_PREFIXES = ("tls+tcp://", "nng+tls+tcp://")


# ws:// historical note: through round 2, ws rode libzmq's WebSocket
# transport — a compile-time option this image's libzmq lacks, so settings
# validation probed zmq.has("ws") and failed the scheme up front. Round 3
# replaced that with an in-tree RFC 6455 transport (engine/socket.py
# WsSocketFactory, NNG ws dialect), making the scheme unconditionally
# available; the probe is gone.


class SettingsError(Exception):
    """Raised for invalid service settings."""


def _validate_addr(addr: str) -> str:
    """Validate a transport address against the supported scheme set.

    Mirrors the reference's NngAddr union constraints (settings.py:31-37):
    unknown schemes are rejected at validation time
    (pinned by tests/test_engine_multi_output.py:328-342 in the reference).
    """
    if "://" not in addr:
        raise ValueError(f"address {addr!r} has no scheme; expected one of {SUPPORTED_SCHEMES}")
    scheme, rest = addr.split("://", 1)
    if scheme not in SUPPORTED_SCHEMES:
        raise ValueError(f"unsupported scheme {scheme!r} in {addr!r}; expected one of {SUPPORTED_SCHEMES}")
    if not rest:
        raise ValueError(f"address {addr!r} has an empty target")
    if scheme in ("tcp", "tls+tcp", "nng+tcp", "nng+tls+tcp", "ws"):
        host_port = rest.split("/", 1)[0]
        if ":" not in host_port:
            raise ValueError(f"address {addr!r} requires an explicit port")
    return addr


TransportAddr = Annotated[str, AfterValidator(_validate_addr)]


class TlsInputConfig(BaseModel):
    """Server-side TLS material for the engine listener (reference: settings.py:11-17)."""

    model_config = ConfigDict(extra="forbid")
    cert_key_file: str


class TlsOutputConfig(BaseModel):
    """Client-side TLS material for output dialers (reference: settings.py:20-27)."""

    model_config = ConfigDict(extra="forbid")
    ca_file: str
    server_name: Optional[str] = None


class ServiceSettings(BaseModel):
    """All per-process service configuration (reference: settings.py:40-173)."""

    model_config = ConfigDict(extra="forbid", validate_assignment=True)

    # -- identity (reference: settings.py:49-52) --------------------------
    component_name: Optional[str] = None
    component_id: Optional[str] = None
    component_type: str = "core"
    component_config_class: Optional[str] = None

    # -- logging (reference: settings.py:55-58) ---------------------------
    log_level: str = "INFO"
    log_dir: str = "./logs"
    log_to_console: bool = True
    log_to_file: bool = True

    # -- engine data channel (reference: settings.py:61-65) ---------------
    engine_addr: TransportAddr = "ipc:///tmp/detectmate.engine.ipc"
    # N-shard ingress (the multi-ingress regime, docs/benchmarks.md): when
    # non-empty the engine listens on ALL of these — one socket, fd and
    # kernel buffer per shard, each fed by its own sender — merged into the
    # single dispatch loop. engine_addr keeps identity/reply duties; it is
    # NOT implicitly included in the shard set.
    engine_ingress_addrs: List[TransportAddr] = Field(default_factory=list)
    engine_autostart: bool = True
    engine_recv_timeout: int = Field(default=100, ge=1)  # ms
    engine_retry_count: int = Field(default=10, ge=1)
    engine_buffer_size: int = Field(default=100, ge=0, le=8192)

    # -- outputs (reference: settings.py:68-70) ---------------------------
    out_addr: List[TransportAddr] = Field(default_factory=list)
    out_dial_timeout: int = Field(default=1000, ge=0)  # ms

    # -- TLS (reference: settings.py:73-74) -------------------------------
    tls_input: Optional[TlsInputConfig] = None
    tls_output: Optional[TlsOutputConfig] = None

    # -- admin HTTP (reference: settings.py:77-78) ------------------------
    http_host: str = "127.0.0.1"
    http_port: int = Field(default=8000, ge=0, le=65535)

    # -- component config file (reference: settings.py:86) ----------------
    config_file: Optional[str] = None

    # -- TPU-build additions ----------------------------------------------
    # engine_batch_size == 1 keeps the reference's strict per-message
    # contract; > 1 enables micro-batched dispatch to the accelerator.
    engine_batch_size: int = Field(default=1, ge=1, le=16384)
    engine_batch_timeout_ms: float = Field(default=2.0, ge=0.0)
    # pack up to N results per outgoing wire frame (engine/framing.py):
    # amortizes the per-message socket cost that caps stage-to-stage rates
    # (~80k msg/s per Python sender, measured). 1 = single-message wire,
    # compatible with reference-style peers; receivers auto-detect either.
    engine_frame_batch: int = Field(default=1, ge=1, le=8192)
    # ingress batch-frame auto-detection rests on every pipeline payload
    # being protobuf (no valid protobuf message starts with the 0xD7 magic —
    # wire type 7 does not exist). A pipeline carrying NON-protobuf payloads
    # that could legitimately begin with b"\xd7DM\x01" (UTF-8 "×DM…") must
    # disable detection or such a payload would be mis-split/dropped.
    engine_frame_autodetect: bool = True
    # pipeline tracing (engine/framing.py v2 frames): opt-in PER SENDER like
    # engine_frame_batch — when true this engine stamps hop records and emits
    # v2 traced frames downstream; framework receivers auto-detect and strip
    # or propagate them. Leave false (the default) on links whose peer is a
    # v1-only or raw-protobuf consumer: wire bytes then stay byte-identical
    # to the untraced format. Requires engine_frame_autodetect (v2 headers
    # ride the same magic-byte detection as batch frames).
    engine_trace: bool = False
    # stage name stamped into hop records; defaults to component_name or
    # component_type so a 3-stage pipeline reads parser→detector→output
    trace_stage: Optional[str] = None
    # terminal-stage override. Default (None) = auto: a stage with no
    # forwarding outputs finalizes traces (observes e2e, feeds the flight
    # recorder). Set true on a stage that DOES forward (e.g. an output
    # writer with a downstream sink that is not a framework engine): it
    # finalizes instead of propagating, and its downstream sees plain v1.
    trace_terminal: Optional[bool] = None
    # egress e2e observation for a FORWARDING stage: when true this stage
    # observes pipeline_e2e_latency_seconds (and feeds its flight recorder)
    # as each frame leaves, while STILL propagating the v2 trace downstream
    # — unlike trace_terminal, which finalizes and strips. Set it on the
    # last framework stage of a pipeline whose sink is an external consumer
    # that keys on trace ids (e.g. the loadgen scorecard collector): the
    # internal e2e then measures ingest→egress, and the collector's
    # client-observed latency minus it is the ingress/egress blind spot
    # (docs/walkthrough.md "read the client skew").
    trace_observe_e2e: bool = False
    # flight recorder bounds (engine/tracing.py): N slowest traces kept,
    # ring of sampled traces, and the 1-in-K completed-trace sampling rate
    trace_slowest: int = Field(default=32, ge=1, le=1024)
    trace_sampled: int = Field(default=128, ge=1, le=8192)
    trace_sample_every: int = Field(default=64, ge=1)
    # fan-out under backpressure: "drop" = the reference contract (bounded
    # retries with 10 ms sleeps, then drop + count — engine.py:286-296);
    # "block" = flow control (send blocks until the peer drains), the right
    # mode INSIDE a high-rate pipeline where a slower downstream stage must
    # throttle its upstream instead of losing data in 100 ms retry windows.
    out_backpressure: str = Field(default="drop", pattern="^(drop|block)$")
    # drain-then-close: in "block" mode a stop() no longer abandons the
    # in-flight message immediately — pending sends share ONE window of this
    # many milliseconds (starting when the stop flag is first observed by a
    # blocked send) to land before being dropped+counted. Aggregate across
    # all messages the final flush emits, so the le=1500 cap keeps it under
    # the engine's 2 s stop-join deadline.
    out_stop_drain_ms: float = Field(default=250.0, ge=0.0, le=1500.0)
    # -- zero-copy host path (engine/shm.py, PR 7) ------------------------
    # Colocated links only: when true AND every out_addr is ipc:// or
    # inproc://, outgoing frames ride a refcounted shared-memory slot (the
    # wire carries a ~40-byte reference; inproc peers get the identical
    # payload object, zero copies). Anything else — a remote scheme in
    # out_addr, an oversized payload, no free slot because a receiver is
    # slow/dead — copy-downgrades that frame to plain bytes: byte-identical
    # payload, just slower. Receivers auto-detect reference frames (rides
    # engine_frame_autodetect, like batch frames).
    zero_copy_framing: bool = False
    # slot pool geometry: payloads larger than zero_copy_slot_bytes always
    # copy-downgrade; all slots held by slow readers ⇒ copy-downgrade too
    # (shm_frames_total{mode="copy"} is the signal)
    zero_copy_slots: int = Field(default=32, ge=2, le=4096)
    zero_copy_slot_bytes: int = Field(default=262144, ge=4096, le=67108864)
    # output fan-out batching: up to this many wire frames per native
    # send_many call (one GIL crossing per micro-batch on the output pump,
    # the send-side twin of the ingest recv_many). 1 = per-frame sends.
    send_batch_max: int = Field(default=64, ge=1, le=8192)
    # transport_backend selects the data-plane implementation: "native" is
    # the in-tree C++ transport (native/transport), "zmq" the Python pyzmq
    # backend; both are wire-compatible. "auto" prefers native when built.
    transport_backend: str = Field(default="auto", pattern="^(auto|zmq|native)$")
    backend: str = Field(default="auto", pattern="^(auto|cpu|tpu)$")
    mesh_shape: Optional[Dict[str, int]] = None  # e.g. {"data": 8}
    # component state checkpointing (core.py): restore at setup_io when a
    # checkpoint exists, save at clean shutdown and on POST /admin/checkpoint
    checkpoint_dir: Optional[str] = None
    # on-demand jax.profiler capture (POST /admin/profile): captures land in
    # numbered subdirectories of profile_dir (default: a per-process dir
    # under the system temp dir), pruned to the newest profile_max_captures
    # so a capture-happy operator cannot fill the disk
    profile_dir: Optional[str] = None
    profile_max_captures: int = Field(default=4, ge=1, le=64)
    # device observability (engine/device_obs.py): when true, a compile on
    # the dispatch path after warm-up completes emits an unexpected_recompile
    # structured event and arms the xla_recompile_storm watchdog check (the
    # scorer_xla_recompiles_unexpected_total counter feeding the
    # RecompileStorm alert moves either way)
    recompile_alert_enabled: bool = True
    # multi-host chip plane (parallel/distributed.py): when a coordinator is
    # set, jax.distributed joins this process's devices into the global mesh
    # (ICI within a pod, DCN across pods). Env (via the standard settings
    # env layer — names match the fields): DETECTMATE_COORDINATOR_ADDRESS /
    # DETECTMATE_NUM_PROCESSES / DETECTMATE_PROCESS_ID.
    coordinator_address: Optional[str] = None  # "host:port"
    num_processes: int = Field(default=1, ge=1)
    process_id: int = Field(default=0, ge=0)
    # -- replica-parallel serving tier (router/, PR 9) --------------------
    # Non-empty turns this stage into a REPLICA ROUTER: instead of
    # duplicating every outgoing frame to all ``out_addr`` peers, each frame
    # is load-balanced to exactly ONE of these downstream replica addresses
    # (the PAPER §0/§7 production topology: one parser feeding a tier of
    # detector processes). Mutually exclusive with ``out_addr`` — a router
    # routes, it does not also fan out.
    router_replicas: List[TransportAddr] = Field(default_factory=list)
    # Admin-plane URL per replica, parallel to router_replicas (same length
    # or empty). With URLs the supervisor polls each replica's deep health
    # (GET /admin/health?deep=1) and ingest watermark (/metrics) to drive
    # drain/undrain and the least_backlog policy; without them health is
    # inferred from send failures only (no proactive drain).
    router_admin_urls: List[str] = Field(default_factory=list)
    # balancing policy: least_backlog routes to the replica with the fewest
    # unacked frames + lowest polled ingress backlog; round_robin rotates;
    # sticky_trace rendezvous-hashes the PR-1 trace id so one source's
    # frames keep per-source ordering on a single replica while it stays
    # healthy.
    router_policy: str = Field(default="least_backlog",
                               pattern="^(least_backlog|round_robin|sticky_trace)$")
    # drain window: a replica whose probe goes unhealthy/unreachable stops
    # receiving new frames immediately; after this many seconds its still-
    # unacked frames are requeued to healthy peers (at-least-once — a frame
    # the dead replica did process may be scored twice; duplicates are
    # harmless to detection, loss is not).
    router_drain_timeout_s: float = Field(default=5.0, ge=0.0, le=600.0)
    # credit window: max unacked frames outstanding per replica. Acks ride
    # the supervisor's watermark poll (the replica's data_read_lines_total
    # covering the window head); a full window removes the replica from
    # dispatch until credit frees — per-replica flow control.
    router_credit_window: int = Field(default=64, ge=1, le=8192)
    # supervisor poll cadence (deep health + watermark per replica)
    router_health_interval_s: float = Field(default=2.0, ge=0.05, le=300.0)

    # -- model lifecycle: dmroll (rollout/, PR 10) ------------------------
    # Turns the served model into a versioned, continuously refreshed
    # artifact: a background trainer fine-tunes candidates on a sampled
    # tail of live traffic, candidates shadow-score a traffic copy, and a
    # promotion gate hot-swaps them onto the dispatch path with zero
    # unexpected XLA recompiles (docs/model_lifecycle.md). Requires a
    # component with the rollout hooks (jax_scorer).
    rollout_enabled: bool = False
    # versioned checkpoint store root (crash-atomic manifest, keep-N
    # rotation). Point every replica of a tier at the SAME directory and
    # `client.py model deploy` rolls one version across the fleet.
    rollout_dir: Optional[str] = None
    # continuous fine-tune cadence; each cycle = sample → fine-tune →
    # checkpoint → shadow → (promote | holdback)
    rollout_interval_s: float = Field(default=600.0, ge=0.05)
    # dispatch-path traffic tap: fraction of dispatched rows offered to the
    # reservoir, and the reservoir's bounded size (rows; memory bound is
    # capacity * seq_len * 4 bytes)
    rollout_sample_ratio: float = Field(default=0.05, gt=0.0, le=1.0)
    rollout_sample_capacity: int = Field(default=4096, ge=16, le=262144)
    # a cycle only fine-tunes once this many sampled rows are banked
    rollout_min_fit_rows: int = Field(default=256, ge=1)
    rollout_train_epochs: int = Field(default=1, ge=1, le=100)
    # shadow-scoring canary gate: a candidate must shadow at least this
    # many rows, then promotes only when mean |score delta| and the
    # alert-decision flip ratio both stay under their ceilings; otherwise
    # it is held back (structured model_canary_holdback event)
    rollout_min_shadow_samples: int = Field(default=512, ge=1)
    rollout_shadow_timeout_s: float = Field(default=300.0, gt=0.0)
    rollout_max_mean_delta: float = Field(default=0.25, ge=0.0)
    rollout_max_flip_ratio: float = Field(default=0.01, ge=0.0, le=1.0)
    # false = candidates stop at the gate and wait for an operator
    # POST /admin/model {"action": "promote"}
    rollout_auto_promote: bool = True
    # keep-N checkpoint rotation (live/pinned/newest never pruned)
    rollout_keep_checkpoints: int = Field(default=4, ge=1, le=64)

    # -- drift & capacity observability: dmdrift (obs/) -------------------
    # When true, a background DriftMonitor (obs/drift.py) compares the live
    # score distribution (the dmroll TrafficSampler reservoir, which also
    # carries per-row scores) against a baseline pinned at promote time and
    # persisted in the CheckpointStore manifest: rolling two-sample KS and
    # PSI over scores plus per-feature PSI on the token rows, exported as
    # model_drift_score{stat} / model_drift_features_over_threshold, with
    # hysteresis-gated drift_detected/drift_cleared events and a
    # GET /admin/drift snapshot (docs/drift.md). Requires rollout_enabled —
    # the detector's reservoir and versioned store are the substrate.
    drift_enabled: bool = False
    # evaluation cadence of the drift monitor thread
    drift_interval_s: float = Field(default=30.0, ge=0.05)
    # rows kept in the pinned baseline (score sample + per-feature
    # histogram edges); bounded so the manifest entry stays small
    drift_baseline_size: int = Field(default=512, ge=16, le=65536)
    # an evaluation is skipped (stats hold their last value) until at least
    # this many scored rows are in the live window
    drift_min_rows: int = Field(default=64, ge=8)
    # detection thresholds: KS statistic on scores, PSI on scores, and the
    # per-feature PSI above which a token column counts as drifting
    drift_ks_threshold: float = Field(default=0.25, ge=0.0, le=1.0)
    drift_psi_threshold: float = Field(default=0.2, ge=0.0)
    drift_feature_psi_threshold: float = Field(default=0.25, ge=0.0)
    # hysteresis: drift_detected only after this many CONSECUTIVE
    # over-threshold evaluations; drift_cleared only after this many
    # consecutive clean ones — no event flapping at the threshold
    drift_trigger_intervals: int = Field(default=3, ge=1, le=1000)
    drift_clear_intervals: int = Field(default=2, ge=1, le=1000)
    # sustained drift kicks RolloutManager.run_cycle(reason="drift") early,
    # but never more often than this cooldown (0 disables the auto-cycle —
    # drift then only pages, it does not retrain)
    drift_min_cycle_interval_s: float = Field(default=900.0, ge=0.0)
    # When true, a CapacityMonitor (obs/capacity.py) maintains the modeled
    # per-replica scoring capacity: pure arithmetic from the dispatch tap
    # (rows ÷ device-seconds) while traffic is live, a bounded synthetic
    # micro-probe through rollout_scores during idle windows — exported as
    # replica_capacity_lines_per_s + capacity_headroom_ratio (offered rate
    # ÷ modeled capacity), the predictive scale-out signal the router
    # aggregates (ops/k8s-replicas.yaml).
    capacity_enabled: bool = False
    # capacity model refresh cadence
    capacity_interval_s: float = Field(default=15.0, ge=0.05)
    # rows per idle micro-probe burst (rides the warm train-bucket compile
    # shape; bounded so a probe can never starve live traffic)
    capacity_probe_rows: int = Field(default=256, ge=1, le=65536)
    # only probe after the dispatch path has been idle this long (0 = never
    # probe; live-traffic arithmetic is then the only capacity source)
    capacity_probe_idle_s: float = Field(default=30.0, ge=0.0)
    # sliding window over which offered rate and busy-time capacity are
    # averaged
    capacity_window_s: float = Field(default=60.0, ge=1.0)

    # -- durable ingress: dmwal (wal/, PR 11) -----------------------------
    # When true, the engine appends every ingress frame to a WAL-backed
    # spool (wal/spool.py) BEFORE processing it, acks the sequence once the
    # frame's results have left the process (router watermark settling when
    # the replica tier is armed), and — after a crash — replays the unacked
    # suffix through the pipeline before accepting new traffic: a parser or
    # router kill -9 no longer loses the in-flight window
    # (docs/durability.md). Off (the default) leaves the hot path
    # byte-identical to the pre-WAL build.
    durable_ingress: bool = False
    # spool directory (segment files + crash-atomic MANIFEST.json);
    # required when durable_ingress is on. Point replay/backfill tooling
    # (client.py replay, POST /admin/replay) at the same directory.
    wal_dir: Optional[str] = None
    # roll to a new segment file once the active one exceeds this many
    # bytes; retention prunes whole sealed segments, so smaller segments =
    # finer-grained reclamation, more files
    wal_segment_bytes: int = Field(default=64 * 1024 * 1024,
                                   ge=4096, le=4 * 1024 * 1024 * 1024)
    # fsync batching: appends are made durable at most this long after they
    # land (0 = fsync EVERY append — the strict-durability mode; the
    # default trades a bounded window of unsynced tail for throughput,
    # measured by wal_fsync_seconds_total)
    wal_fsync_interval_ms: float = Field(default=50.0, ge=0.0, le=10000.0)
    # bounded retention: sealed, fully-acked segments are pruned from the
    # front once the spool exceeds wal_retain_bytes, or once a sealed
    # segment's newest record is older than wal_retain_age_s. The UNACKED
    # suffix is never pruned by either bound — SpoolDepthHigh/SpoolAgeHigh
    # (ops/alerts.yml) page before disk becomes the operator's problem.
    wal_retain_bytes: int = Field(default=1024 * 1024 * 1024, ge=4096)
    wal_retain_age_s: float = Field(default=86400.0, gt=0.0)
    # disk-fault policy (wal/spool.py): what the spool does when an
    # append/fsync/manifest OSError (EIO/ENOSPC) is absorbed — the error
    # itself can never kill the EngineLoop thread. degrade (default):
    # keep serving NON-durably with wal_spool_degraded raised, re-arming
    # on the next successful write; shed: drop frames that could not be
    # made durable (durability over availability); halt: escalate as
    # WalError and stop the stage.
    wal_on_disk_error: str = Field(default="degrade",
                                   pattern="^(degrade|shed|halt)$")

    # -- fault injection + dead-letter quarantine: dmfault (faults/) ------
    # JSON FaultPlan file ({"seed": int, "specs": [{site, kind, rate,
    # start_op, stop_op, delay_ms, match}, ...]}) armed at service start;
    # None (the default) arms nothing and every fault site costs one
    # is-None branch. POST /admin/faults arms/disarms at runtime.
    fault_plan_file: Optional[str] = None
    # dead-letter quarantine (wal/deadletter.py): a frame whose processing
    # raised on every one of dlq_max_attempts attempts moves to the DLQ
    # (reason + error + tenant/seq context) instead of crash-looping
    # recovery replay or being silently dropped-and-acked.
    dlq_max_attempts: int = Field(default=3, ge=1, le=100)
    # bound on retained quarantined frames; at capacity the oldest entry
    # is evicted (newest evidence wins)
    dlq_max_frames: int = Field(default=1024, ge=1, le=1048576)
    # DLQ directory; defaults to <wal_dir>/dlq when durable_ingress is on,
    # memory-only quarantine otherwise
    dlq_dir: Optional[str] = None

    # -- warm-start serving: dmwarm (utils/profiling.py, PR 17) -----------
    # When true, the JAX persistent compilation cache is armed in Service
    # construction — BEFORE the component's first jit — so a restarted
    # replica (or a dmroll candidate swap on the same host) reuses every
    # already-seen (kernel, bucket) compile instead of paying cold-start.
    # Point every replica of a tier at the SAME compile_cache_dir and HPA
    # scale-out boots against a warm cache (docs/walkthrough.md "make
    # scale-out honest"). Off (the default) keeps the env-only behavior
    # (DETECTMATE_JAX_CACHE), which is OFF on CPU backends.
    compile_cache_enabled: bool = False
    # shared cache root; entries land under a machine-fingerprint
    # subdirectory (utils/profiling._machine_fingerprint) so heterogeneous
    # hosts can share the directory without ever loading each other's
    # machine-tuned artifacts. An explicit dir persists EVERY compile
    # (min-compile-time floor drops to 0) — required for CPU-sim parity
    # runs, harmless on TPU. None + enabled = the env/default-home path.
    compile_cache_dir: Optional[str] = None

    # -- multi-tenant admission control: dmshed (shed/) -------------------
    # When true, the engine ingress runs per-tenant token-bucket admission
    # BEFORE spooling/processing each frame: frames carry an optional
    # tenant block (engine/framing.py MAGIC_TEN), quotas come from
    # tenants_file (or the tenant_default_* fields for unmapped/anonymous
    # tenants), and refused frames are counted + shed instead of growing
    # an unbounded backlog (docs/overload.md). Off (the default) leaves
    # the hot path byte-identical to the pre-shed build — the tenant
    # block, when present, is still stripped cleanly.
    shed_enabled: bool = False
    # tenants.yaml quota map: tier + rate (sustained lines/s) + burst
    # headroom per tenant, with a 'default' entry for unmapped tenants.
    # None = every tenant rides the tenant_default_* quota below.
    tenants_file: Optional[str] = None
    tenant_default_tier: str = Field(
        default="best_effort", pattern="^(guaranteed|burst|best_effort)$")
    tenant_default_rate: float = Field(default=10000.0, gt=0.0)
    # None = 2x tenant_default_rate (one second of doubled arrivals)
    tenant_default_burst: Optional[float] = Field(default=None, gt=0.0)
    # cardinality bound for the tenant_bucket metric label: tenant ids
    # hash into this many stable buckets (never per-tenant label values)
    shed_tenant_buckets: int = Field(default=16, ge=1, le=256)
    # retry hint stamped into the structured NACK a refused frame gets in
    # reply mode (never an empty reply — the dm_nack payload carries
    # reason, tier, and this backoff)
    shed_retry_after_ms: float = Field(default=100.0, ge=0.0, le=60000.0)
    # global degradation ladder (engine/health.py DegradationLadder):
    # aggregate process backlog (detector pending + router unacked + spool
    # depth) at which the ladder climbs to shed_best_effort / shed_burst /
    # emergency. Climb is immediate to the highest exceeded threshold;
    # recovery steps down one state per shed_ladder_recovery_intervals
    # consecutive clean watchdog evaluations (watchdog-style hysteresis).
    shed_ladder_backlog_t1: float = Field(default=256.0, gt=0.0)
    shed_ladder_backlog_t2: float = Field(default=1024.0, gt=0.0)
    shed_ladder_backlog_t3: float = Field(default=4096.0, gt=0.0)
    shed_ladder_recovery_intervals: int = Field(default=2, ge=1)

    # -- self-diagnosis (engine/health.py) --------------------------------
    # "json" renders every log record as one JSON object per line (component
    # identity + message + attached structured event), for fleet log
    # aggregation; "plain" keeps the reference's human format.
    log_format: str = Field(default="plain", pattern="^(plain|json)$")
    # per-process health watchdog: a daemon thread evaluates the subsystem
    # checks (process_wedged / ingest_stalled / output_saturated /
    # device_inflight_stuck) every interval and rolls them into the
    # engine_health_state Enum behind GET /admin/health.
    watchdog_enabled: bool = True
    watchdog_interval_s: float = Field(default=2.0, ge=0.05, le=300.0)
    # heartbeat age (or continuous blocked-send / stuck-inflight time) at
    # which a check degrades resp. goes unhealthy. stall must exceed
    # engine_recv_timeout or an idle loop's recv tick would false-alarm.
    watchdog_stall_seconds: float = Field(default=10.0, gt=0.0)
    watchdog_unhealthy_seconds: float = Field(default=30.0, gt=0.0)
    # hysteresis: checks degrade on the FIRST failing evaluation but only
    # recover after this many consecutive clean ones (no alert flapping)
    watchdog_recovery_intervals: int = Field(default=2, ge=1)
    # 0 (default) = an idle ingress is healthy; > 0 = this stage expects
    # traffic, and that many seconds of ingress silence is a degradation
    watchdog_ingest_stall_seconds: float = Field(default=0.0, ge=0.0)
    # bounded ring of structured events behind GET /admin/events
    event_ring_size: int = Field(default=512, ge=8, le=65536)

    # -- cross-stage telemetry: dmtel (telemetry/) ------------------------
    # Span export (every traced stage): where this engine ships its
    # completed hop spans — the collector stage's telemetry_collector_addr.
    # Requires engine_trace: spans ARE the hop records the tracing path
    # stamps. Unset (default) = hop records stay in the local flight
    # recorder only, exactly the pre-dmtel behavior.
    telemetry_addr: Optional[TransportAddr] = None
    # bounded hot-path span queue; when full, spans are dropped (counted in
    # telemetry_spans_export_dropped_total) — never the pipeline's frames
    telemetry_queue_size: int = Field(default=4096, ge=16, le=1048576)
    # sender-thread drain cadence: spans batch for up to this long before
    # one JSON encode + one socket send ships them
    telemetry_flush_interval_ms: float = Field(default=50.0, ge=1.0,
                                               le=10000.0)
    # Collector (one stage per pipeline, like the router): assemble spans
    # into whole-pipeline traces, tail-sample, serve GET /admin/traces.
    telemetry_collector: bool = False
    telemetry_collector_addr: Optional[TransportAddr] = None
    # tail sampling: the anomalous tail (error / shed / quarantined /
    # fault / slow / incomplete) is ALWAYS kept; healthy traces are kept at
    # this ratio by a deterministic hash of the trace id
    telemetry_sample_healthy_ratio: float = Field(default=0.05, ge=0.0,
                                                  le=1.0)
    # e2e latency above which a completed trace is "slow" (kept 100%)
    telemetry_slo_ms: float = Field(default=1000.0, gt=0.0)
    # watermark settle window: a trace with its terminal hop completes once
    # the newest send_ns seen across ALL spans has advanced this far past
    # the trace's own newest hop (out-of-order stragglers had their chance)
    telemetry_settle_ms: float = Field(default=200.0, ge=0.0, le=60000.0)
    # collector-clock deadline after which a trace is flushed regardless —
    # without a terminal hop it counts as incomplete (itself a signal)
    telemetry_trace_timeout_s: float = Field(default=5.0, gt=0.0, le=600.0)
    # bounded ring of kept traces behind GET /admin/traces
    telemetry_retain_traces: int = Field(default=256, ge=8, le=65536)
    # optional OTLP/HTTP traces endpoint (e.g. http://tempo:4318/v1/traces):
    # kept traces are pushed as OTLP/JSON by a dedicated export thread
    telemetry_otlp_url: Optional[str] = None

    # -- derived identity (reference: settings.py:93-114) -----------------
    @model_validator(mode="after")
    def _ensure_component_id(self) -> "ServiceSettings":
        if not self.component_id:
            if self.component_name:
                seed = f"detectmate/{self.component_type}/{self.component_name}"
            else:
                seed = f"detectmate/{self.component_type}|{self.engine_addr}"
            object.__setattr__(
                self, "component_id", uuid.uuid5(uuid.NAMESPACE_URL, seed).hex
            )
        return self

    # -- watchdog cross-validation ----------------------------------------
    @model_validator(mode="after")
    def _check_watchdog(self) -> "ServiceSettings":
        if self.watchdog_unhealthy_seconds < self.watchdog_stall_seconds:
            raise ValueError(
                "watchdog_unhealthy_seconds must be >= watchdog_stall_seconds "
                f"({self.watchdog_unhealthy_seconds} < {self.watchdog_stall_seconds})")
        return self

    # -- router cross-validation ------------------------------------------
    @model_validator(mode="after")
    def _check_router(self) -> "ServiceSettings":
        if self.router_replicas and self.out_addr:
            raise ValueError(
                "router_replicas and out_addr are mutually exclusive: a "
                "router load-balances each frame to ONE replica; plain "
                "fan-out duplicates to every out_addr")
        if (self.router_admin_urls
                and len(self.router_admin_urls) != len(self.router_replicas)):
            raise ValueError(
                "router_admin_urls must be empty or match router_replicas "
                f"1:1 ({len(self.router_admin_urls)} urls for "
                f"{len(self.router_replicas)} replicas)")
        return self

    # -- rollout cross-validation -----------------------------------------
    @model_validator(mode="after")
    def _check_rollout(self) -> "ServiceSettings":
        if self.rollout_enabled and not self.rollout_dir:
            raise ValueError(
                "rollout_enabled requires rollout_dir (the versioned "
                "checkpoint store root)")
        return self

    # -- drift cross-validation -------------------------------------------
    @model_validator(mode="after")
    def _check_drift(self) -> "ServiceSettings":
        if self.drift_enabled and not self.rollout_enabled:
            raise ValueError(
                "drift_enabled requires rollout_enabled: the drift monitor "
                "reads the dmroll traffic reservoir and pins its baseline "
                "in the rollout checkpoint store")
        return self

    # -- durable-ingress cross-validation ---------------------------------
    @model_validator(mode="after")
    def _check_wal(self) -> "ServiceSettings":
        if self.durable_ingress and not self.wal_dir:
            raise ValueError(
                "durable_ingress requires wal_dir (the WAL spool directory)")
        return self

    # -- compile-cache cross-validation -----------------------------------
    @model_validator(mode="after")
    def _check_compile_cache(self) -> "ServiceSettings":
        """A non-writable ``compile_cache_dir`` must fail at startup, not at
        the first compile (where enable_compilation_cache swallows the
        OSError and the operator's shared cache silently never fills)."""
        if self.compile_cache_enabled and self.compile_cache_dir:
            probe = os.path.join(self.compile_cache_dir,
                                 f".dmwarm_probe_{os.getpid()}")
            try:
                os.makedirs(self.compile_cache_dir, exist_ok=True)
                with open(probe, "w", encoding="utf-8") as fh:
                    fh.write("ok")
                os.unlink(probe)
            except OSError as exc:
                raise ValueError(
                    f"compile_cache_dir {self.compile_cache_dir!r} is not "
                    f"writable: {exc}")
        return self

    # -- shed cross-validation --------------------------------------------
    @model_validator(mode="after")
    def _check_shed(self) -> "ServiceSettings":
        if not (self.shed_ladder_backlog_t1 <= self.shed_ladder_backlog_t2
                <= self.shed_ladder_backlog_t3):
            raise ValueError(
                "shed ladder thresholds must be ordered t1 <= t2 <= t3 "
                f"({self.shed_ladder_backlog_t1} / "
                f"{self.shed_ladder_backlog_t2} / "
                f"{self.shed_ladder_backlog_t3})")
        if (self.tenant_default_burst is not None
                and self.tenant_default_burst < self.tenant_default_rate):
            raise ValueError(
                "tenant_default_burst must be >= tenant_default_rate "
                f"({self.tenant_default_burst} < {self.tenant_default_rate})")
        return self

    # -- telemetry cross-validation ---------------------------------------
    @model_validator(mode="after")
    def _check_telemetry(self) -> "ServiceSettings":
        if self.telemetry_collector and not self.telemetry_collector_addr:
            raise ValueError(
                "telemetry_collector requires telemetry_collector_addr "
                "(the address the collector listens for span frames on)")
        if self.telemetry_addr and not self.engine_trace:
            raise ValueError(
                "telemetry_addr requires engine_trace: spans are built "
                "from the hop records the tracing path stamps")
        return self

    # -- TLS cross-validation (reference: settings.py:116-132) ------------
    @model_validator(mode="after")
    def _check_tls(self) -> "ServiceSettings":
        # both TLS-bearing schemes (framework-private tls+tcp and the
        # NNG-wire-compatible nng+tls+tcp) need their material up front —
        # fail at startup, not at first connection
        tls_schemes = TLS_SCHEME_PREFIXES
        if self.engine_addr.startswith(tls_schemes) and self.tls_input is None:
            raise ValueError(
                f"engine_addr uses {self.engine_addr.split('://')[0]}:// "
                "but tls_input is not configured")
        if (any(a.startswith(tls_schemes) for a in self.engine_ingress_addrs)
                and self.tls_input is None):
            raise ValueError("an engine_ingress_addr uses a TLS scheme but tls_input is not configured")
        if any(a.startswith(tls_schemes) for a in self.out_addr) and self.tls_output is None:
            raise ValueError("an out_addr uses a TLS scheme but tls_output is not configured")
        if (any(a.startswith(tls_schemes) for a in self.router_replicas)
                and self.tls_output is None):
            raise ValueError("a router_replicas address uses a TLS scheme "
                             "but tls_output is not configured")
        return self

    # -- loading -----------------------------------------------------------
    @classmethod
    def from_yaml(cls, path: str) -> "ServiceSettings":
        """Load from YAML, apply env overrides (env wins), validate.

        Exits the process on validation failure, like the reference CLI
        (reference: settings.py:134-173; precedence pinned by
        tests/test_config_reading.py:122-145).
        """
        try:
            with open(path, "r", encoding="utf-8") as fh:
                data = yaml.safe_load(fh) or {}
            if not isinstance(data, dict):
                raise SettingsError(f"settings file {path} must contain a mapping")
            merged = _deep_merge(data, _env_overrides())
            return cls.model_validate(merged)
        except (OSError, yaml.YAMLError, ValidationError, SettingsError) as exc:
            print(f"Invalid service settings ({path}): {exc}", file=sys.stderr)
            raise SystemExit(1)

    @classmethod
    def from_env(cls) -> "ServiceSettings":
        return cls.model_validate(_env_overrides())


def _env_overrides(environ: Optional[Mapping[str, str]] = None) -> Dict[str, Any]:
    """Collect ``DETECTMATE_*`` environment variables into a nested dict.

    ``__`` nests into sub-models (reference: settings.py:80-84). List- and
    dict-typed fields accept JSON values.
    """
    environ = environ if environ is not None else os.environ
    out: Dict[str, Any] = {}
    for key, value in environ.items():
        if not key.startswith(ENV_PREFIX):
            continue
        path = key[len(ENV_PREFIX):].lower().split(ENV_NESTED_DELIMITER)
        parsed: Any = value
        stripped = value.strip()
        if stripped and stripped[0] in "[{":
            try:
                parsed = json.loads(stripped)
            except json.JSONDecodeError:
                parsed = value
        node = out
        for part in path[:-1]:
            node = node.setdefault(part, {})
            if not isinstance(node, dict):
                break
        else:
            node[path[-1]] = parsed
    return out


def _deep_merge(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    """Merge ``override`` onto ``base``, override winning per-field."""
    merged = dict(base)
    for key, value in override.items():
        if key in merged and isinstance(merged[key], dict) and isinstance(value, dict):
            merged[key] = _deep_merge(merged[key], value)
        else:
            merged[key] = value
    return merged
