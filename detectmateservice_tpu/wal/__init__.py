"""dmwal: durable WAL-backed ingress spool + deterministic replay.

Three layers (docs/durability.md):

* ``segment`` — length+CRC framed, sequence-numbered frame records in
  append-only segment files; torn-tail containment by construction.
* ``spool`` — the engine-facing ``IngressSpool``: append before processing,
  ack on downstream send, fsync batching, crash-atomic manifest commits,
  bounded retention that never prunes the unacked suffix.
* ``replay`` — ``ReplayDriver`` (byte-deterministic re-drive of a recorded
  spool through a component) and ``shadow_replay`` (offline dmroll canary
  divergence against recorded traffic), behind ``/admin/replay``.
"""
from .replay import (  # noqa: F401
    REPLAY,
    ReplayBusyError,
    ReplayDriver,
    ReplayError,
    ReplayManager,
    shadow_replay,
    start_service_replay,
)
from .segment import (  # noqa: F401
    Record,
    WalError,
    iter_records,
    list_segments,
    read_spool,
    scan_segment,
    segment_name,
)
from .deadletter import DeadLetterSpool  # noqa: F401
from .spool import IngressSpool  # noqa: F401
