"""DeadLetterSpool: the poison-frame quarantine behind the DLQ verbs.

A frame whose processing keeps raising is POISON: retrying it forever
wedges the stage (the confirmed failure mode this fixes — under
``durable_ingress`` a poison frame in the WAL's unacked suffix turned
every restart into the same crash-replay loop), and dropping it silently
destroys the evidence. The engine instead gives each frame a bounded
number of processing attempts (``dlq_max_attempts``) and then moves it
HERE, with its reason, last error, attempt count, and whatever
tenant/sequence context the ingress still had — processing converges, the
frame survives for a human.

Storage is one JSONL file (``dlq.jsonl``) in the DLQ directory: one JSON
object per line, the frame bytes base64-encoded inline. Appends go
through an unbuffered handle and fsync per record — quarantine is a cold
path (it has already cost ``dlq_max_attempts`` failed dispatches), so the
per-record durability tax is noise, and it means a quarantined frame
survives the very crash its poison may be about to cause. A torn final
line (power loss mid-append) is skipped on load, same contract as the WAL
segment reader. Requeue/purge compact the file through the proven
temp + fsync + ``os.replace`` + dir-fsync commit.

The spool is bounded (``dlq_max_frames``): at capacity the OLDEST entry
is evicted (newest evidence wins), counted on the snapshot. With no
directory configured (``durable_ingress`` off and no ``dlq_dir``) it runs
memory-only — quarantine still converges, the evidence just does not
survive a restart.

Threading: ``quarantine`` runs on the engine thread, the admin verbs
(``snapshot``/``requeue``/``purge``) on web threads — every method takes
the one internal lock; all paths are cold by construction.
"""
from __future__ import annotations

import base64
import binascii
import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..engine import metrics as m
from ..utils.atomicio import fsync_dir

_DLQ_FILE = "dlq.jsonl"
_EVENT_INTERVAL_S = 1.0     # per-reason frame_quarantined event rate limit


class DeadLetterSpool:
    def __init__(self, directory: Optional[str], *,
                 max_frames: int = 1024,
                 labels: Optional[Dict[str, str]] = None,
                 events: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 logger: Optional[logging.Logger] = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.directory = Path(directory) if directory else None
        self.max_frames = max(1, int(max_frames))
        self._labels = {"component_type": "dlq", "component_id": "dlq"}
        self._labels.update(labels or {})
        self._events = events
        self.logger = logger or logging.getLogger("dlq")
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: List[Dict[str, Any]] = []    # oldest first
        self._next_id = 1
        self.quarantined_total = 0
        self.requeued_total = 0
        self.purged_total = 0
        self.evicted_total = 0
        self._fh = None
        self._last_event_t: Dict[str, float] = {}
        # hoisted metric children (DM-H001): per-reason on first sight
        self._m_quarantined: Dict[str, Any] = {}
        self._m_requeued = m.DLQ_REQUEUED().labels(**self._labels)
        if self.directory is not None:
            self.directory.mkdir(parents=True, exist_ok=True)
            self._load()
            self._open_append()

    # -- persistence -----------------------------------------------------
    @property
    def path(self) -> Optional[Path]:
        return (self.directory / _DLQ_FILE) if self.directory else None

    def _load(self) -> None:
        """Rebuild the quarantine from disk; a torn/garbled line (power
        loss mid-append) ends the readable prefix, like the WAL's
        torn-tail rule."""
        path = self.path
        if path is None or not path.exists():
            return
        kept: List[Dict[str, Any]] = []
        with open(path, "rb") as fh:
            for lineno, line in enumerate(fh, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                    doc["frame"] = base64.b64decode(doc.pop("frame_b64"))
                except (ValueError, KeyError, TypeError,
                        binascii.Error) as exc:
                    self.logger.warning(
                        "DLQ %s: unreadable line %d ends the readable "
                        "prefix (%s)", path.name, lineno, exc)
                    break
                kept.append(doc)
        self._entries = kept
        if kept:
            self._next_id = max(e.get("id", 0) for e in kept) + 1

    def _open_append(self) -> None:
        # unbuffered like the WAL segments: an append that returned reaches
        # the kernel; the per-record fsync below makes it power-loss-proof
        # dmlint: ignore[DM-L001] every caller holds _lock (compaction paths) or predates publication (__init__)
        self._fh = open(self.path, "ab", buffering=0)

    def _append_record(self, entry: Dict[str, Any]) -> None:
        if self._fh is None:
            return
        doc = dict(entry)
        doc["frame_b64"] = base64.b64encode(doc.pop("frame")).decode("ascii")
        line = json.dumps(doc, sort_keys=True).encode("utf-8") + b"\n"
        try:
            self._fh.write(line)
            os.fsync(self._fh.fileno())
        except OSError as exc:
            # the disk may be the very fault being injected/suffered; the
            # in-memory quarantine still converges processing
            self.logger.error("DLQ append failed (%s); entry %d held "
                              "in memory only", exc, entry["id"])

    def _compact(self) -> None:
        """Rewrite the file to match ``self._entries`` (after requeue/
        purge/evict) through the temp+fsync+replace+dir-fsync commit."""
        path = self.path
        if path is None:
            return
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        tmp = path.with_name(f".{path.name}.tmp.{os.getpid()}")
        try:
            with open(tmp, "wb") as fh:
                for entry in self._entries:
                    doc = dict(entry)
                    doc["frame_b64"] = base64.b64encode(
                        doc.pop("frame")).decode("ascii")
                    fh.write(json.dumps(doc, sort_keys=True).encode("utf-8")
                             + b"\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            fsync_dir(path.parent)
        except OSError as exc:
            self.logger.error("DLQ compaction failed: %s", exc)
        self._open_append()

    # -- engine-side ------------------------------------------------------
    def quarantine(self, frame: bytes, *, reason: str, error: str = "",
                   attempts: int = 0, tenant: Optional[str] = None,
                   seq: Optional[int] = None,
                   trace_id: Optional[str] = None) -> int:
        """Move one poison frame aside; returns its DLQ entry id."""
        with self._lock:
            entry = {
                "id": self._next_id,
                "reason": reason,
                "error": error[:512],
                "attempts": attempts,
                "tenant": tenant,
                "seq": seq,
                "trace_id": trace_id,
                "frame_bytes": len(frame),
                "quarantined_unix": round(self._clock(), 3),
                "frame": bytes(frame),
            }
            self._next_id += 1
            self._entries.append(entry)
            self.quarantined_total += 1
            evicted = None
            if len(self._entries) > self.max_frames:
                evicted = self._entries.pop(0)
                self.evicted_total += 1
            self._append_record(entry)
            if evicted is not None:
                self._compact()
        child = self._m_quarantined.get(reason)
        if child is None:
            child = m.DLQ_QUARANTINED().labels(reason=reason, **self._labels)
            self._m_quarantined[reason] = child
        child.inc()
        self.logger.error(
            "frame quarantined to DLQ: id=%d reason=%s attempts=%d "
            "bytes=%d error=%s", entry["id"], reason, attempts, len(frame),
            error[:200])
        self._maybe_emit(entry)
        return entry["id"]

    def _maybe_emit(self, entry: Dict[str, Any]) -> None:
        if self._events is None:
            return
        now = time.monotonic()
        last = self._last_event_t.get(entry["reason"], -_EVENT_INTERVAL_S)
        if now - last < _EVENT_INTERVAL_S:
            return
        self._last_event_t[entry["reason"]] = now
        self._events({
            "kind": "frame_quarantined",
            "dlq_id": entry["id"],
            "reason": entry["reason"],
            "error": entry["error"],
            "attempts": entry["attempts"],
            "tenant": entry["tenant"],
            "seq": entry["seq"],
            "frame_bytes": entry["frame_bytes"],
            # dmlint: ignore[DM-L001] advisory depth in an event body: GIL-atomic len read, exactness not required
            "dlq_depth": len(self._entries),
        })

    # -- admin verbs -------------------------------------------------------
    def requeue(self, entry_id: Optional[int] = None
                ) -> List[Tuple[int, bytes]]:
        """Remove entries (one, or all with no id) and return their frames
        for re-injection. Requeue is at-most-once: a frame handed back is
        no longer the DLQ's to protect."""
        with self._lock:
            taken, kept = self._split(entry_id)
            self._entries = kept
            if taken:
                self.requeued_total += len(taken)
                self._compact()
        if taken:
            self._m_requeued.inc(len(taken))
        return [(e["id"], e["frame"]) for e in taken]

    def purge(self, entry_id: Optional[int] = None) -> int:
        with self._lock:
            taken, kept = self._split(entry_id)
            self._entries = kept
            if taken:
                self.purged_total += len(taken)
                self._compact()
        return len(taken)

    def _split(self, entry_id: Optional[int]
               ) -> Tuple[List[Dict], List[Dict]]:
        if entry_id is None:
            return list(self._entries), []
        taken = [e for e in self._entries if e["id"] == entry_id]
        kept = [e for e in self._entries if e["id"] != entry_id]
        return taken, kept

    # -- observability -----------------------------------------------------
    def depth_frames(self) -> float:
        """Gauge read (scrape threads, Gauge.set_function): length read of
        a list the GIL keeps internally consistent."""
        # dmlint: ignore[DM-L001] lock-free gauge read: GIL-atomic len of a list replaced only under _lock
        return float(len(self._entries))

    def snapshot(self, limit: int = 64) -> Dict[str, Any]:
        with self._lock:
            entries = [{k: v for k, v in e.items() if k != "frame"}
                       for e in self._entries[-limit:]]
            return {
                "depth_frames": len(self._entries),
                "max_frames": self.max_frames,
                "quarantined_total": self.quarantined_total,
                "requeued_total": self.requeued_total,
                "purged_total": self.purged_total,
                "evicted_total": self.evicted_total,
                "directory": str(self.directory) if self.directory else None,
                "entries": entries,
            }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
