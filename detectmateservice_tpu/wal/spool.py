"""IngressSpool: the durable WAL-backed ingress buffer behind
``durable_ingress``.

Write path (engine hot loop, single-threaded by design — every mutator runs
on the engine thread, like the router's socket ops): ``append`` buffers one
record into the active segment, ``ack`` advances the in-memory watermark
when the frame's results have left the process, and ``tick`` — called once
per engine loop iteration — batches the durability work: an fsync every
``wal_fsync_interval_ms``, a manifest commit (the crash-atomic
temp+fsync+rename pattern shared with ``utils/checkpoint.write_json_atomic``)
whenever the persisted ack watermark lags, segment roll bookkeeping, and
bounded retention.

Crash semantics, by construction:

* segment writes are UNBUFFERED (``buffering=0``): once ``append`` returns,
  the record is in the kernel — a process kill (kill -9) loses nothing
  appended; only a POWER loss can take the un-fsynced tail, and never as a
  *torn* record surviving recovery (length+CRC framing stops the reader at
  the damage; the writer truncates it away on reopen);
* a crash between fsync and manifest commit loses at most the acks since
  the last commit — those records replay exactly once per crash
  (at-least-once, never at-most-once: the watermark only moves FORWARD of
  reality on disk, never ahead of it);
* a crash between segment-file creation and manifest commit hides nothing:
  recovery scans the directory, not the manifest, for segments.

Retention prunes whole *sealed* segments from the front once the spool
exceeds ``wal_retain_bytes`` or a sealed segment's newest record exceeds
``wal_retain_age_s`` — but NEVER a segment still holding unacked records:
the unacked suffix is the crash-recovery contract and outlives any size or
age bound (the ``SpoolDepthHigh``/``SpoolAgeHigh`` alerts page long before
an operator has to think about disk).

Observability reads (``depth_frames``/``spool_bytes``/
``oldest_unacked_age_seconds``) come from scrape threads via
``Gauge.set_function`` and are single-int/tuple reads — lock-free on
purpose, same discipline as the heartbeat gauges.

Disk-fault policy (``wal_on_disk_error``): an ``OSError`` out of the
append/fsync/manifest path — a real EIO/ENOSPC or one injected at the
``wal_append``/``wal_fsync`` fault sites — is ABSORBED here, never allowed
to escape into the engine loop tick and kill the EngineLoop thread. Every
absorbed error is counted (``wal_fsync_errors_total``) and the first error
of a bad stretch emits a structured ``wal_degraded`` event + one log line
(transition-edge logging, not a per-tick storm). Then the policy decides:

* ``degrade`` (default) — keep serving NON-durably: ``append`` reports the
  frame un-spooled (the engine processes it anyway, it just loses crash
  replay), the ``wal_spool_degraded`` gauge goes to 1, and every later
  append retries the disk so the first success re-arms durability (gauge
  back to 0, ``wal_degraded`` event with ``state: restored``);
* ``shed`` — same absorption, but the engine DROPS frames the spool could
  not make durable (durability over availability);
* ``halt`` — escalate as ``WalError``: the operator asked the stage to
  stop rather than serve non-durably.
"""
from __future__ import annotations

import logging
import os
import time
from collections import deque
from pathlib import Path
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from .. import faults
from ..utils.atomicio import fsync_dir, write_json_atomic
from ..utils.threadcheck import assert_affinity
from .segment import (
    Record,
    WalError,
    iter_records,
    list_segments,
    pack_record,
    scan_segment,
    segment_name,
)

_MANIFEST = "MANIFEST.json"
_SCHEMA = "dmwal-v1"


class _Segment:
    """In-memory bookkeeping for one on-disk segment file."""

    __slots__ = ("path", "first_seq", "last_seq", "bytes", "created_unix",
                 "newest_append_unix", "sealed")

    def __init__(self, path: Path, first_seq: int, last_seq: Optional[int],
                 nbytes: int, created_unix: float,
                 newest_append_unix: float, sealed: bool) -> None:
        self.path = path
        self.first_seq = first_seq
        self.last_seq = last_seq
        self.bytes = nbytes
        self.created_unix = created_unix
        self.newest_append_unix = newest_append_unix
        self.sealed = sealed

    def doc(self) -> Dict:
        return {"file": self.path.name, "first_seq": self.first_seq,
                "last_seq": self.last_seq, "bytes": self.bytes,
                "created_unix": round(self.created_unix, 3),
                "sealed": self.sealed}


class IngressSpool:
    def __init__(self, directory: str, *,
                 segment_bytes: int = 64 * 1024 * 1024,
                 fsync_interval_ms: float = 50.0,
                 retain_bytes: int = 1024 * 1024 * 1024,
                 retain_age_s: float = 86400.0,
                 fsync_observer: Optional[Callable[[float], None]] = None,
                 on_disk_error: str = "degrade",
                 events: Optional[Callable[[Dict], object]] = None,
                 disk_error_observer: Optional[Callable[[], None]] = None,
                 logger: Optional[logging.Logger] = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = max(4096, int(segment_bytes))
        self.fsync_interval_s = max(0.0, float(fsync_interval_ms)) / 1000.0
        self.retain_bytes = int(retain_bytes)
        self.retain_age_s = float(retain_age_s)
        self._fsync_observer = fsync_observer
        if on_disk_error not in ("degrade", "shed", "halt"):
            raise WalError(
                f"wal_on_disk_error {on_disk_error!r} not in degrade|shed|halt")
        self.on_disk_error = on_disk_error
        self._events = events
        self._disk_error_observer = disk_error_observer
        self._degraded = False                  # serving non-durably
        self.disk_errors = 0                    # absorbed OSErrors, total
        self.logger = logger or logging.getLogger("wal")
        self._clock = clock                     # wall clock (ages, stamps)

        self._acked = self._load_manifest_ack()
        self._segments: List[_Segment] = []
        self._last_appended = self._acked
        # (seq, append_unix) of every unacked record, oldest first — the
        # oldest-unacked-age gauge and the exact-age retention both read
        # the head; rebuilt from the recorded append stamps on reopen
        self._unacked_times: deque = deque()
        self._scan_existing()

        self._fh = None                         # active segment handle
        self._active: Optional[_Segment] = None
        self._open_active()

        self._dirty_bytes = 0                   # appended since last fsync
        self._last_fsync = time.monotonic()
        self._manifest_dirty = True             # commit once at open
        # manifest commits (ack persistence + retention) are a json write
        # plus two fsyncs — batched on their own, coarser cadence: a crash
        # then replays at most this window's acks, once (at-least-once)
        self._manifest_interval_s = max(self.fsync_interval_s, 1.0)
        self._last_manifest = 0.0
        self._closed = False
        self.tick(force=True)

    # -- recovery scan --------------------------------------------------
    def _load_manifest_ack(self) -> int:
        path = self.directory / _MANIFEST
        if not path.exists():
            return 0
        import json

        try:
            doc = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError) as exc:
            # write_json_atomic makes a torn manifest impossible; an
            # unreadable one is real damage — fail loud, silently starting
            # from ack 0 would replay the whole retained spool
            raise WalError(f"unreadable WAL manifest {path}: {exc}")
        if doc.get("schema") != _SCHEMA:
            raise WalError(
                f"WAL manifest {path} has schema {doc.get('schema')!r}, "
                f"this build reads {_SCHEMA!r}")
        return int(doc.get("acked_seq", 0))

    def _scan_existing(self) -> None:
        """Rebuild segment bookkeeping from the directory (the recovery
        truth), truncating a torn tail off the NEWEST segment so the append
        handle continues from a clean record boundary. Damage in a sealed
        (non-last) segment is reported, never repaired — its readable
        prefix stays served."""
        paths = list_segments(self.directory)
        for i, path in enumerate(paths):
            scan = scan_segment(path)
            last = i == len(paths) - 1
            if scan.torn:
                if last:
                    self.logger.warning(
                        "WAL %s: torn tail truncated at byte %d "
                        "(%d intact records)", path.name, scan.valid_end,
                        scan.records)
                    with open(path, "rb+") as fh:
                        fh.truncate(scan.valid_end)
                        fh.flush()
                        os.fsync(fh.fileno())
                else:
                    self.logger.error(
                        "WAL %s: damaged record inside a SEALED segment — "
                        "serving the intact prefix (%d records)", path.name,
                        scan.records)
            if scan.first_seq is None:
                if last:
                    # an empty newest segment (crash right after roll):
                    # reuse it as the active segment under its name
                    first = int(path.name[4:-4])
                    self._segments.append(_Segment(
                        path, first, None, 0, path.stat().st_mtime,
                        path.stat().st_mtime, sealed=False))
                continue
            stat = path.stat()
            self._segments.append(_Segment(
                path, scan.first_seq, scan.last_seq, scan.valid_end,
                stat.st_mtime, stat.st_mtime, sealed=not last))
            self._last_appended = max(self._last_appended, scan.last_seq)
        # exact unacked append stamps from the records themselves
        if self._last_appended > self._acked:
            for rec in self._iter_from(self._acked):
                self._unacked_times.append((rec.seq, rec.append_ns / 1e9))

    def _iter_from(self, after_seq: int) -> Iterator[Record]:
        for seg in self._segments:
            if seg.last_seq is not None and seg.last_seq <= after_seq:
                continue
            for rec in iter_records(seg.path):
                if rec.seq > after_seq:
                    yield rec

    def _open_active(self) -> None:
        if self._segments and not self._segments[-1].sealed \
                and self._segments[-1].bytes < self.segment_bytes:
            self._active = self._segments[-1]
        else:
            if self._segments:
                self._segments[-1].sealed = True
            first = self._last_appended + 1
            path = self.directory / segment_name(first)
            path.touch()
            fsync_dir(self.directory)
            now = self._clock()
            self._active = _Segment(path, first, None, 0, now, now,
                                    sealed=False)
            self._segments.append(self._active)
        # buffering=0: every append write() reaches the KERNEL immediately,
        # so a plain kill -9 loses nothing that append() returned for — only
        # a power loss can take the un-fsynced tail. A user-space buffer
        # here would silently widen the crash window to everything since the
        # last tick (caught live: a SIGKILL during a long burst collect ate
        # the whole burst's appends out of the Python file buffer).
        self._fh = open(self._active.path, "ab", buffering=0)

    # -- disk-fault policy ------------------------------------------------
    def _disk_error(self, op: str, exc: OSError) -> None:
        """Absorb one append/fsync/manifest ``OSError`` per the configured
        policy. Counted always; logged + event-emitted once per degraded
        TRANSITION (the first error of a bad stretch), not per tick."""
        self.disk_errors += 1
        if self._disk_error_observer is not None:
            self._disk_error_observer()
        if self.on_disk_error == "halt":
            raise WalError(
                f"WAL {op} failed with wal_on_disk_error=halt: {exc}"
            ) from exc
        if self._degraded:
            return
        self._degraded = True
        self.logger.error(
            "WAL degraded: %s failed (%s); serving %s until the disk "
            "recovers (wal_on_disk_error=%s)", op, exc,
            "non-durably" if self.on_disk_error == "degrade"
            else "with frames shed", self.on_disk_error)
        if self._events is not None:
            self._events({"kind": "wal_degraded", "state": "degraded",
                          "op": op, "errno": exc.errno, "error": str(exc),
                          "policy": self.on_disk_error,
                          "disk_errors_total": self.disk_errors})

    def _rearm(self, op: str) -> None:
        """First successful disk write after a degraded stretch: durability
        is live again."""
        self._degraded = False
        self.logger.warning(
            "WAL recovered: %s succeeded after %d absorbed disk errors; "
            "durability re-armed", op, self.disk_errors)
        if self._events is not None:
            self._events({"kind": "wal_degraded", "state": "restored",
                          "op": op, "policy": self.on_disk_error,
                          "disk_errors_total": self.disk_errors})

    # -- write path (machine-checked: engine thread only) ----------------
    # dmlint: thread(engine)
    def append(self, frame: bytes) -> Optional[int]:
        """Durably (after the next fsync tick) record one ingress frame;
        returns its sequence number — or ``None`` when a disk error was
        absorbed under degrade/shed and the frame is NOT durable (the
        engine then serves it non-durably or drops it per the policy)."""
        assert_affinity("engine")
        if self._closed:
            raise WalError("append on a closed spool")
        seq = self._last_appended + 1
        now = self._clock()
        rec = pack_record(seq, int(now * 1e9), frame)
        if self._active.bytes and \
                self._active.bytes + len(rec) > self.segment_bytes:
            self._roll()
        boundary = self._active.bytes
        try:
            inj = faults._ACTIVE
            if inj is not None:
                inj.fs("wal_append")
            self._fh.write(rec)
        except OSError as exc:
            # torn-record hygiene: a partial write would leave a record the
            # CRC framing has to truncate on the NEXT recovery — cut it back
            # to the last known-good boundary now, while we can
            try:
                self._fh.truncate(boundary)
            except OSError:
                pass        # recovery's torn-tail scan is the backstop
            self._disk_error("append", exc)
            return None
        # a successful buffered write does NOT re-arm: durability is only
        # proven by a successful fsync (an fsync-broken disk happily takes
        # writes into the page cache — re-arming here would flap the
        # degraded gauge per append and hide the outage from WalDegraded)
        self._active.bytes += len(rec)
        self._active.last_seq = seq
        self._active.newest_append_unix = now
        self._last_appended = seq
        self._unacked_times.append((seq, now))
        self._dirty_bytes += len(rec)
        if self.fsync_interval_s == 0.0:
            self._fsync()
        return seq

    # dmlint: thread(engine)
    def ack(self, seq: int) -> None:
        """Advance the ack watermark: every record with ``seq`` at or below
        it has been handed downstream and will not replay after a clean
        restart (a crash may still replay the acks not yet committed to the
        manifest — once per crash, the at-least-once bound)."""
        assert_affinity("engine")
        if seq <= self._acked:
            return
        self._acked = min(seq, self._last_appended)
        times = self._unacked_times
        while times and times[0][0] <= self._acked:
            times.popleft()
        self._manifest_dirty = True

    def _roll(self) -> None:
        """Seal the active segment and open the next: fsync the sealed data
        first (its records must be durable before the manifest can claim
        the segment is sealed), then cut over."""
        self._fsync()
        self._fh.close()
        self._active.sealed = True
        self._manifest_dirty = True
        first = self._last_appended + 1
        path = self.directory / segment_name(first)
        path.touch()
        fsync_dir(self.directory)
        now = self._clock()
        self._active = _Segment(path, first, None, 0, now, now, sealed=False)
        self._segments.append(self._active)
        self._fh = open(path, "ab", buffering=0)  # see _open_active

    def _fsync(self) -> bool:
        if self._fh is None:
            return True
        t0 = time.monotonic()
        try:
            inj = faults._ACTIVE
            if inj is not None:
                inj.fs("wal_fsync")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except OSError as exc:
            # stamp the attempt so a broken disk is retried once per fsync
            # interval, not once per engine loop iteration
            self._last_fsync = time.monotonic()
            self._disk_error("fsync", exc)
            return False
        self._dirty_bytes = 0
        self._last_fsync = time.monotonic()
        if self._degraded:
            self._rearm("fsync")
        if self._fsync_observer is not None:
            self._fsync_observer(self._last_fsync - t0)
        return True

    # dmlint: thread(engine)
    def tick(self, force: bool = False) -> None:
        """One batched-durability step: fsync when the interval elapsed (or
        ``force``), commit the manifest when the ack watermark or segment
        set moved, apply retention. Called once per engine loop iteration —
        the no-work case is two int compares."""
        assert_affinity("engine")
        now = time.monotonic()
        if self._dirty_bytes and (
                force or now - self._last_fsync >= self.fsync_interval_s):
            self._fsync()
        if self._manifest_dirty and (
                force or now - self._last_manifest
                >= self._manifest_interval_s):
            self._retain()
            try:
                self._commit_manifest()
            except OSError as exc:
                self._disk_error("manifest", exc)
            self._last_manifest = now

    def _commit_manifest(self) -> None:
        write_json_atomic(self.directory / _MANIFEST, {
            "schema": _SCHEMA,
            "acked_seq": self._acked,
            "last_appended_seq": self._last_appended,
            "committed_unix": round(self._clock(), 3),
            "segments": [seg.doc() for seg in self._segments],
        })
        self._manifest_dirty = False

    def _retain(self) -> None:
        """Prune sealed, fully-acked segments from the front while the spool
        exceeds its byte bound, or while the head segment's newest record
        exceeds the age bound. The unacked suffix is untouchable."""
        while len(self._segments) > 1:
            head = self._segments[0]
            if not head.sealed or head is self._active:
                return
            if head.last_seq is None or head.last_seq > self._acked:
                return                      # unacked suffix: never pruned
            over_bytes = self.spool_bytes() > self.retain_bytes
            over_age = (self._clock() - head.newest_append_unix
                        > self.retain_age_s)
            if not (over_bytes or over_age):
                return
            try:
                head.path.unlink()
            except OSError as exc:
                self.logger.error("WAL retention cannot remove %s: %s",
                                  head.path.name, exc)
                return
            self._segments.pop(0)
            self._manifest_dirty = True

    # runs on the stopping thread, AFTER the engine thread is joined
    # dmlint: thread(any) — the join is the happens-before edge
    def close(self) -> None:
        """Clean shutdown: final fsync + manifest commit (so a clean
        restart replays nothing), then release the handle."""
        if self._closed:
            return
        self._closed = True
        self.tick(force=True)
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- recovery / observability ---------------------------------------
    # dmlint: thread(engine)
    def recover_unacked(self) -> List[Tuple[int, bytes]]:
        """The unacked suffix, oldest first — what the engine must replay
        through the pipeline before accepting new traffic after a
        restart."""
        self._fsync()                       # make the scan read-consistent
        return [(rec.seq, rec.frame) for rec in self._iter_from(self._acked)]

    @property
    def acked_seq(self) -> int:
        return self._acked

    @property
    def last_appended_seq(self) -> int:
        return self._last_appended

    def depth_frames(self) -> float:
        return float(self._last_appended - self._acked)

    def degraded_value(self) -> float:
        """1.0 while serving non-durably after a disk error (the
        wal_spool_degraded gauge, read at scrape time)."""
        return 1.0 if self._degraded else 0.0

    def spool_bytes(self) -> float:
        return float(sum(seg.bytes for seg in self._segments))

    def oldest_unacked_age_seconds(self) -> float:
        times = self._unacked_times
        if not times:
            return 0.0
        try:
            _seq, t = times[0]
        except IndexError:          # raced a concurrent ack pop: empty now
            return 0.0
        return max(0.0, self._clock() - t)

    # lock-free single-int/tuple reads by design (scrape threads via
    # dmlint: thread(any) — Gauge.set_function, like the gauge methods
    def stats(self) -> Dict:
        return {
            "directory": str(self.directory),
            "acked_seq": self._acked,
            "last_appended_seq": self._last_appended,
            "depth_frames": int(self.depth_frames()),
            "spool_bytes": int(self.spool_bytes()),
            "oldest_unacked_age_seconds":
                round(self.oldest_unacked_age_seconds(), 3),
            "degraded": self._degraded,
            "disk_errors": self.disk_errors,
            "on_disk_error": self.on_disk_error,
            "segments": [seg.doc() for seg in self._segments],
        }
