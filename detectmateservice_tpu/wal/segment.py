"""WAL segment files: length+CRC framed, sequence-numbered frame records.

One segment is an append-only file of records:

    u32 LE body_len | u32 LE crc32(body) | body
    body = varint seq | varint append_unix_ns | frame bytes

``frame`` is the raw ingress wire frame exactly as the engine received it
(post shm-resolution, pre trace-strip) — a v1 batch frame, a v2 traced
frame, or a plain single message. Because the v2 trace header is part of
the recorded bytes, a replay re-drives *yesterday's* traffic with its
original trace ids and ingest stamps by construction; nothing has to be
reconstructed.

Torn-write containment is the whole point of the framing: a crash mid-append
leaves at most one partial record at the file tail. A reader stops at the
first record whose header is incomplete, whose declared body runs past EOF,
or whose CRC does not match — everything before that point is intact by
checksum, everything after it is unreachable garbage the writer truncates
away on reopen. Records are never rewritten, so a record that was ever
readable stays readable (single-fault disk damage in a sealed segment is
reported, not silently skipped).

Segment files are named ``seg-<first_seq, zero-padded>.wal`` so a plain
sorted directory listing *is* the sequence order; the spool's manifest
(wal/spool.py) carries only the ack watermark and retention metadata — the
directory scan, not the manifest, is the recovery truth for which records
exist (a crash between creating a segment file and committing the manifest
must not hide the segment).
"""
from __future__ import annotations

import struct
import zlib
from pathlib import Path
from typing import Iterator, List, NamedTuple, Optional, Tuple

_HEADER = struct.Struct("<II")          # body_len, crc32(body)
# a declared body larger than this is treated as tail damage, not a record:
# no single ingress frame approaches it, and honoring a garbage length would
# make one flipped bit swallow the rest of the segment as "one record"
_MAX_BODY = 256 * 1024 * 1024

SEGMENT_PREFIX = "seg-"
SEGMENT_SUFFIX = ".wal"


class WalError(RuntimeError):
    """Unrecoverable WAL damage (never raised for an ordinary torn tail)."""


def segment_name(first_seq: int) -> str:
    return f"{SEGMENT_PREFIX}{first_seq:020d}{SEGMENT_SUFFIX}"


def list_segments(directory: Path) -> List[Path]:
    """Segment files of ``directory`` in sequence order (name-sorted; the
    zero-padded first-seq name makes lexicographic == numeric order)."""
    return sorted(Path(directory).glob(
        f"{SEGMENT_PREFIX}*{SEGMENT_SUFFIX}"))


def _put_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _get_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise WalError("truncated varint in WAL record body")
        byte = data[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise WalError("varint overflow in WAL record body")


class Record(NamedTuple):
    """One recovered record: its sequence number, the wall-clock append
    stamp (epoch ns — feeds the oldest-unacked age after a restart), the
    recorded frame bytes, and the file offset of the NEXT record (i.e. the
    end of this one — the writer's truncate-to point when this is the last
    valid record)."""

    seq: int
    append_ns: int
    frame: bytes
    end_offset: int


def pack_record(seq: int, append_ns: int, frame: bytes) -> bytes:
    body = bytearray()
    _put_varint(body, seq)
    _put_varint(body, append_ns)
    body += frame
    return _HEADER.pack(len(body), zlib.crc32(body)) + bytes(body)


def _parse_body(body: bytes) -> Tuple[int, int, bytes]:
    seq, pos = _get_varint(body, 0)
    append_ns, pos = _get_varint(body, pos)
    return seq, append_ns, body[pos:]


class SegmentScan(NamedTuple):
    """Result of validating one segment file: its intact records' seq span,
    the byte offset where validity ends (== file size when clean), and
    whether a torn/damaged tail was found after it."""

    first_seq: Optional[int]
    last_seq: Optional[int]
    valid_end: int
    torn: bool
    records: int


def iter_records(path: Path, start_offset: int = 0) -> Iterator[Record]:
    """Yield the intact records of one segment, stopping (silently) at the
    first torn/damaged record — the caller decides whether that is a
    routine crash tail (last segment) or reportable damage (sealed one).
    Reads the whole segment into memory: segments are bounded by
    ``wal_segment_bytes`` and replay/recovery are cold paths."""
    data = Path(path).read_bytes()
    pos = start_offset
    while True:
        if pos + _HEADER.size > len(data):
            return                      # clean EOF or torn header
        body_len, crc = _HEADER.unpack_from(data, pos)
        body_start = pos + _HEADER.size
        body_end = body_start + body_len
        if body_len == 0 or body_len > _MAX_BODY or body_end > len(data):
            return                      # garbage length or torn body
        body = data[body_start:body_end]
        if zlib.crc32(body) != crc:
            return                      # torn or damaged record
        try:
            seq, append_ns, frame = _parse_body(body)
        except WalError:
            return                      # CRC-valid but unparseable: treat
        pos = body_end                  # as damage, stop like a torn tail
        yield Record(seq, append_ns, frame, pos)


def scan_segment(path: Path) -> SegmentScan:
    first = last = None
    end = 0
    count = 0
    for rec in iter_records(path):
        if first is None:
            first = rec.seq
        last = rec.seq
        end = rec.end_offset
        count += 1
    size = Path(path).stat().st_size
    return SegmentScan(first, last, end, torn=end != size, records=count)


def read_spool(directory: Path, start_seq: int = 0,
               limit: Optional[int] = None) -> Iterator[Record]:
    """Iterate every intact record of a spool directory with ``seq >
    start_seq`` in sequence order — the replay harness's read path, which
    must work against a spool no writer has open (an archived copy, another
    stage's directory). Duplicate seqs across a crash-torn boundary are
    collapsed (first occurrence wins)."""
    seen = start_seq
    yielded = 0
    for path in list_segments(Path(directory)):
        for rec in iter_records(path):
            if rec.seq <= seen:
                continue
            seen = rec.seq
            yield rec
            yielded += 1
            if limit is not None and yielded >= limit:
                return
