"""Deterministic replay of a recorded ingress spool.

``ReplayDriver`` re-drives the frames of a WAL directory through a
component processor exactly the way the engine would — strip the v2 trace
header (keeping the ORIGINAL trace id and ingest stamp), expand batch
frames, dispatch the contained messages in order, drain held/pipelined
results at the end — and folds every emitted output into a SHA-256 digest.
Because the recorded bytes already carry the original trace headers and no
new hop stamps are added, two replays of the same recorded segment against
the same detector version produce byte-identical outputs and therefore the
same digest: that equality is the regression-bisection and
candidate-evaluation primitive (asserted by tests/test_wal.py and
scripts/wal_smoke.py).

``shadow_replay`` is the offline twin of the dmroll shadow canary
(rollout/shadow.py): it scores every recorded row through BOTH the live
params and a candidate checkpoint from the versioned store and emits the
same divergence report the live gate uses — *yesterday's real traffic*
instead of a live sample, with zero impact on the serving path.

``ReplayManager`` (the process-wide ``REPLAY`` instance) runs one replay at
a time behind ``POST /admin/replay`` / ``GET /admin/replay`` and
``client.py replay``, with the same one-run-per-process 409 semantics as
the profiler and load manager.
"""
from __future__ import annotations

import hashlib
import logging
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..engine.framing import FramingError, unpack_batch, unwrap_trace, wrap_trace
from .segment import read_spool

_U32 = (2 ** 32 - 1)


class ReplayError(ValueError):
    """Bad replay request (unknown mode, no spool, missing seams)."""


class ReplayBusyError(RuntimeError):
    """A replay (or the live engine, for pipeline mode) is already active."""


class ReplayDriver:
    """Re-drive recorded frames through one component, deterministically.

    ``processor`` is a library component (or anything exposing
    ``process_batch(list[bytes])`` / ``process(bytes)`` and optionally
    ``flush``/``flush_final``); ``None`` echoes messages (passthrough).
    ``deliver`` (optional) receives each output as a wire frame — wrapped
    back into a v2 frame with the ORIGINAL trace context when the source
    frame carried one — for backfill into a downstream stage. ``counter``
    (optional) is called with the number of frames replayed (feeds
    ``wal_replayed_frames_total``)."""

    def __init__(self, directory: str, processor: Any, *,
                 deliver: Optional[Callable[[bytes], None]] = None,
                 counter: Optional[Callable[[int], None]] = None,
                 logger: Optional[logging.Logger] = None) -> None:
        self.directory = Path(directory)
        self.processor = processor
        self.deliver = deliver
        self.counter = counter
        self.logger = logger or logging.getLogger("wal.replay")

    # -- output accounting ----------------------------------------------
    @staticmethod
    def _fold(digest: "hashlib._Hash", trace_id: int,
              payload: bytes) -> None:
        digest.update(trace_id.to_bytes(8, "big"))
        digest.update((len(payload) & _U32).to_bytes(4, "big"))
        digest.update(payload)

    def run(self, start_seq: int = 0,
            limit: Optional[int] = None) -> Dict[str, Any]:
        t0 = time.monotonic()
        batch_fn = getattr(self.processor, "process_batch", None)
        proc_fn = getattr(self.processor, "process", None)
        digest = hashlib.sha256()
        # FIFO of original trace contexts, consumed per output — the
        # engine's attachment model, exact when outputs map 1:1 to inputs
        ctx_fifo: List = []
        frames = messages = outputs = trace_errors = 0

        def emit(outs: Sequence[Optional[bytes]]) -> None:
            nonlocal outputs
            for out in outs:
                if out is None:
                    continue
                ctx = ctx_fifo.pop(0) if ctx_fifo else None
                self._fold(digest, ctx.trace_id if ctx else 0, out)
                outputs += 1
                if self.deliver is not None:
                    self.deliver(wrap_trace(out, ctx) if ctx else out)

        first_seq = last_seq = None
        for rec in read_spool(self.directory, start_seq=start_seq,
                              limit=limit):
            frames += 1
            if first_seq is None:
                first_seq = rec.seq
            last_seq = rec.seq
            try:
                payload, ctx, damaged = unwrap_trace(rec.frame)
            except FramingError:
                trace_errors += 1
                continue
            if damaged:
                trace_errors += 1
            try:
                msgs = unpack_batch(payload)
            except FramingError:
                trace_errors += 1
                continue
            if msgs is None:
                msgs = [payload]
            msgs = [msg for msg in msgs if msg]
            if not msgs:
                continue
            messages += len(msgs)
            if ctx is not None:
                ctx_fifo.append(ctx)
            try:
                if callable(batch_fn):
                    emit(batch_fn(msgs))
                elif callable(proc_fn):
                    emit([proc_fn(msg) for msg in msgs])
                else:
                    emit(msgs)                      # passthrough
            except Exception as exc:
                self.logger.error("replay: processor raised on seq %d: %s",
                                  rec.seq, exc)
                raise
        # drain held/pipelined results exactly once, like the engine at stop
        final_fn = (getattr(self.processor, "flush_final", None)
                    or getattr(self.processor, "flush", None))
        if callable(final_fn):
            emit(final_fn())
        if self.counter is not None and frames:
            self.counter(frames)
        return {
            "mode": "pipeline",
            "directory": str(self.directory),
            "frames": frames,
            "messages": messages,
            "outputs": outputs,
            "trace_errors": trace_errors,
            "first_seq": first_seq,
            "last_seq": last_seq,
            "output_digest": digest.hexdigest(),
            "duration_s": round(time.monotonic() - t0, 3),
        }


def shadow_replay(directory: str, detector: Any, *,
                  store_dir: Optional[str] = None,
                  version: Optional[int] = None,
                  params: Any = None,
                  threshold: Optional[float] = None,
                  min_samples: int = 1,
                  max_mean_delta: float = 0.25,
                  max_flip_ratio: float = 0.01,
                  start_seq: int = 0,
                  limit: Optional[int] = None,
                  max_rows: int = 65536,
                  track_top: int = 8,
                  counter: Optional[Callable[[int], None]] = None,
                  logger: Optional[logging.Logger] = None) -> Dict[str, Any]:
    """Score a recorded spool through the live params AND a dmroll
    candidate; return the PR-10 divergence report (mean/max |Δscore|,
    alert-decision flip ratio, gate verdict) computed offline.

    The candidate comes from ``params`` directly, or is loaded from the
    versioned checkpoint store at ``store_dir`` (``version`` None = the
    newest recorded version). The recorded frames must be the DETECTOR
    stage's ingress (serialized ParserSchema rows) — the same bytes its
    live dispatch path featurizes."""
    logger = logger or logging.getLogger("wal.replay")
    if not callable(getattr(detector, "rollout_scores", None)):
        raise ReplayError(
            "shadow replay needs a rollout-capable detector "
            "(rollout_scores hook — the jax scorer)")
    import numpy as np

    from ..rollout.shadow import ShadowEvaluator

    t0 = time.monotonic()
    meta: Dict[str, Any] = {}
    if params is None:
        if not store_dir:
            raise ReplayError(
                "shadow replay needs a candidate: pass params, or store_dir "
                "(+ optional version) naming the rollout checkpoint store")
        from ..rollout.store import CheckpointStore

        store = CheckpointStore(store_dir)
        if version is None:
            history = store.history(limit=1)
            if not history:
                raise ReplayError(f"checkpoint store {store_dir} is empty")
            version = int(history[0]["version"])
        params, _opt_state, meta = detector.load_params_checkpoint(
            str(store.version_dir(version)))
    if threshold is None:
        threshold = detector.live_threshold()
    evaluator = ShadowEvaluator(threshold, max(1, min_samples),
                                max_mean_delta, max_flip_ratio,
                                track_top=track_top)

    frames = rows = skipped_rows = 0
    first_seq = last_seq = None
    pending: List[bytes] = []
    row_seqs: List[int] = []

    def score_pending() -> None:
        nonlocal rows, skipped_rows, pending, row_seqs
        if not pending:
            return
        tokens, ok = detector._featurize_raw_batch(pending)
        keep = np.flatnonzero(ok)
        skipped_rows += len(pending) - len(keep)
        if len(keep):
            kept = tokens[keep]
            live = detector.rollout_scores(None, kept)
            cand = detector.rollout_scores(params, kept)
            evaluator.observe(live, cand,
                              row_ids=[row_seqs[i] for i in keep])
            rows += len(keep)
        pending = []
        row_seqs = []

    for rec in read_spool(directory, start_seq=start_seq, limit=limit):
        frames += 1
        if first_seq is None:
            first_seq = rec.seq
        last_seq = rec.seq
        try:
            payload, _ctx, _damaged = unwrap_trace(rec.frame)
            msgs = unpack_batch(payload)
        except FramingError:
            continue
        if msgs is None:
            msgs = [payload]
        for msg in msgs:
            if msg:
                pending.append(msg)
                row_seqs.append(rec.seq)
        if len(pending) >= 512:
            score_pending()
        if rows >= max_rows:
            logger.warning("shadow replay: row cap %d reached at seq %d — "
                           "report covers a prefix of the spool",
                           max_rows, rec.seq)
            break
    score_pending()
    if counter is not None and frames:
        counter(frames)
    report = evaluator.stats()
    report.update({
        "mode": "shadow",
        "directory": str(directory),
        "candidate_version": version,
        "candidate_meta": {k: meta[k] for k in ("model", "saved_unix")
                          if k in meta},
        "threshold": float(threshold),
        "frames": frames,
        "rows_scored": rows,
        "rows_skipped": skipped_rows,
        "first_seq": first_seq,
        "last_seq": last_seq,
        "duration_s": round(time.monotonic() - t0, 3),
    })
    return report


class ReplayManager:
    """One replay per process, run on its own thread behind the admin
    plane; ``status()`` serves the live/last run (GET /admin/replay)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running_info: Optional[Dict[str, Any]] = None
        self._last: Optional[Dict[str, Any]] = None

    def start(self, info: Dict[str, Any],
              runner: Callable[[], Dict[str, Any]],
              wait: bool = False) -> Dict[str, Any]:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                raise ReplayBusyError(
                    "a replay is already running (one per process); poll "
                    "GET /admin/replay until it completes")
            self._running_info = dict(info, state="running",
                                      started_unix=round(time.time(), 3))
            thread = threading.Thread(target=self._run, args=(runner,),
                                      name="wal-replay", daemon=True)
            self._thread = thread
        thread.start()
        if wait:
            thread.join()
            with self._lock:
                return dict(self._last or {})
        return dict(info, state="started")

    def _run(self, runner: Callable[[], Dict[str, Any]]) -> None:
        with self._lock:
            info = dict(self._running_info or {})
        try:
            result = runner()
            outcome = dict(info, state="done", result=result)
        except Exception as exc:          # surfaced via status, not a crash
            outcome = dict(info, state="error", error=str(exc))
        with self._lock:
            self._last = outcome
            self._running_info = None

    def status(self) -> Dict[str, Any]:
        with self._lock:
            running = self._thread is not None and self._thread.is_alive()
            return {
                "running": running,
                "current": dict(self._running_info) if running
                           and self._running_info else None,
                "last": dict(self._last) if self._last else None,
            }


REPLAY = ReplayManager()


def start_service_replay(service: Any, payload: Dict[str, Any],
                         ) -> Dict[str, Any]:
    """The ``POST /admin/replay`` implementation: validate the request
    against THIS service's settings/component, build the runner, and hand
    it to the process-wide manager. Raises ``ReplayError`` (HTTP 400) on a
    bad request and ``ReplayBusyError`` (HTTP 409) on state conflicts."""
    from ..engine import metrics as m

    payload = payload or {}
    mode = str(payload.get("mode", "pipeline"))
    wal_dir = payload.get("wal_dir") or getattr(service.settings, "wal_dir",
                                                None)
    if not wal_dir:
        raise ReplayError("no spool to replay: pass wal_dir or configure "
                          "the stage with durable_ingress + wal_dir")
    if not Path(wal_dir).is_dir():
        raise ReplayError(f"wal_dir {wal_dir} does not exist")
    start_seq = int(payload.get("start_seq", 0))
    limit = payload.get("limit")
    limit = int(limit) if limit is not None else None
    wait = bool(payload.get("wait", False))
    labels = dict(component_type=service.settings.component_type,
                  component_id=service.settings.component_id or "unknown")
    counter = m.WAL_REPLAYED_FRAMES().labels(mode=mode, **labels).inc

    if mode == "pipeline":
        if service.engine.running and not payload.get("force"):
            raise ReplayBusyError(
                "the engine is running: a pipeline replay drives the "
                "component directly and must not interleave with live "
                "dispatch — POST /admin/stop first (or pass force:true "
                "for a stage whose component tolerates it)")
        driver = ReplayDriver(wal_dir, service.library_component,
                              counter=counter, logger=service.logger)
        info = {"mode": mode, "wal_dir": str(wal_dir),
                "start_seq": start_seq, "limit": limit}
        return REPLAY.start(info, lambda: driver.run(start_seq=start_seq,
                                                     limit=limit), wait=wait)
    if mode == "shadow":
        detector = service.library_component
        settings = service.settings
        store_dir = payload.get("store_dir") or getattr(settings,
                                                        "rollout_dir", None)
        version = payload.get("version")
        version = int(version) if version is not None else None
        info = {"mode": mode, "wal_dir": str(wal_dir), "version": version,
                "store_dir": store_dir, "start_seq": start_seq,
                "limit": limit}
        return REPLAY.start(info, lambda: shadow_replay(
            wal_dir, detector, store_dir=store_dir, version=version,
            min_samples=1,
            max_mean_delta=getattr(settings, "rollout_max_mean_delta", 0.25),
            max_flip_ratio=getattr(settings, "rollout_max_flip_ratio", 0.01),
            start_seq=start_seq, limit=limit, counter=counter,
            logger=service.logger), wait=wait)
    raise ReplayError(f"unknown replay mode {mode!r} "
                      "(expected 'pipeline' or 'shadow')")
