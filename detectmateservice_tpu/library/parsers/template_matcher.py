"""MatcherParser: log-format tokenization + template matching.

Capability parity with the reference library's
``detectmatelibrary.parsers.template_matcher.MatcherParser`` (surface
reconstructed from container/config/parser_config.yaml, the audit-log
integration config in
tests/library_integration/test_pipe_filereader_matcher_nvd.py:50-65, and
docs/getting_started.md:388-418):

* ``log_format`` is a token template like
  ``<IP> - - [<Time>] "<Method> <URL> <Protocol>" <Status> <Bytes> ...``;
  each ``<Name>`` captures one field into ``logFormatVariables``,
* the ``<Content>`` capture (or, absent one, the whole line) is normalized
  (``remove_spaces`` / ``remove_punctuation`` / ``lowercase``) and matched
  against the drain-style template file at ``path_templates`` (``<*>``
  wildcards); the matched template's 1-based index becomes ``EventID`` and the
  wildcard captures become ``variables``,
* quirk preserved: the output's ``log`` field is set to the parser name, not
  the input line (pinned in the reference by
  tests/library_integration/test_pipe_filereader_matcher_nvd.py:158-160).

The per-line template-matching hot path can run through the optional in-tree
C++ kernel (native/matchkern) when built; the Python path is the fallback.
"""
from __future__ import annotations

import json
import re
import string
import time
import uuid
from pathlib import Path
from typing import Any, List, Optional, Pattern, Tuple

from pydantic import Field

from ...schemas import LogSchema, ParserSchema, SchemaError
from ...schemas import schemas_pb2 as _pb
from ..common.core import CoreComponent, CoreConfig, LibraryError

_TOKEN_RE = re.compile(r"<([A-Za-z_][A-Za-z0-9_]*)>")
_PUNCT_TABLE = str.maketrans("", "", string.punctuation)
# explicit-presence LogSchema fields: at least one present <=> the bytes are
# a genuine envelope, not arbitrary text that happens to parse as protobuf
_LOGSCHEMA_FIELDS = ("__version__", "logID", "log", "logSource", "hostname")


class MatcherParserConfig(CoreConfig):
    method_type: str = "matcher_parser"
    log_format: Optional[str] = None
    time_format: Optional[str] = None
    # flattened from params by CoreConfig.from_dict
    remove_spaces: bool = False
    remove_punctuation: bool = False
    lowercase: bool = False
    path_templates: Optional[str] = None
    # Ingest-payload flexibility for STOCK-fluentd edges. The reference's
    # ingest edge wraps each tailed line in a LogSchema protobuf via its
    # private `fluent-plugin-detectmate` formatter (reference:
    # container/fluentin/fluent.conf:164-166); that gem is not installable
    # here, so this build's edge (container/Dockerfile_fluentd) runs stock
    # formatters, which emit either a JSON record ({"message": line,
    # "logSource": path, "hostname": host} — `<format> @type json`) or the
    # bare line (`<format> @type single_value`). When true, payloads that
    # are not LogSchema protobufs are accepted in those two shapes; when
    # false (default), non-LogSchema payloads raise — the reference's strict
    # contract, which the error-taxonomy tests pin.
    accept_raw_lines: bool = False
    # Native host-path parsing (utils/matchkern): the fused whole-row kernel
    # (dm_parse_batch/_frames) plus the decode-only LogSchema span kernel and
    # the native ParserSchema emitter used by the batched fallback path.
    # False forces every row through the pure-Python pb2 path — the parity
    # reference the differential fuzzer compares against.
    native_parse: bool = True


def decode_ingest_payload(data: bytes, accept_raw: bool):
    """Resolve one ingest payload to a LogSchema message.

    Payload shapes, tried in order (first match wins):

    1. **LogSchema protobuf** — the reference-grade envelope its
       `fluent-plugin-detectmate` formatter emits (reference:
       container/fluentin/fluent.conf:164-166). In strict mode any parse
       is taken as-is (the reference contract). With ``accept_raw`` on,
       an envelope is recognized iff the bytes parse AND at least one
       LogSchema field is present — proto3 will "parse" some arbitrary
       byte strings into all-unknown-fields messages, and those must not
       shadow the raw-line interpretations.
    2. **JSON record** — what stock fluentd's `<format> @type json` emits
       for the tail source: ``{"message": line, "logSource": path,
       "hostname": host}`` (+ trailing newline). Mapped onto LogSchema as
       message→log, logSource→logSource, hostname→hostname — the same field
       mapping the reference formatter performs.
    3. **Bare line** — `<format> @type single_value` (+ its default
       trailing newline): the line alone, no provenance.

    Shapes 2-3 are gated by ``accept_raw``; with it off, a payload that is
    not a LogSchema protobuf raises SchemaError (the reference's strict
    contract).
    """
    msg = _pb.LogSchema()
    try:
        msg.ParseFromString(data)
    except Exception as exc:
        if not accept_raw:
            raise SchemaError(f"cannot parse LogSchema: {exc}") from exc
        envelope = False
    else:
        if not accept_raw:
            # strict mode takes whatever parsed, envelope or not (the
            # reference contract) — skip the per-line presence probe, this
            # is the parser service's hot path
            return msg
        envelope = any(msg.HasField(f) for f in _LOGSCHEMA_FIELDS)
    if envelope:
        return msg
    out = _pb.LogSchema()
    if data[:1] == b"{":
        try:
            rec = json.loads(data)
        except (ValueError, UnicodeDecodeError):
            rec = None
        if isinstance(rec, dict) and ("message" in rec or "log" in rec):
            out.log = str(rec.get("message", rec.get("log", "")))
            if rec.get("logID"):
                out.logID = str(rec["logID"])
            if rec.get("logSource"):
                out.logSource = str(rec["logSource"])
            if rec.get("hostname"):
                out.hostname = str(rec["hostname"])
            return out
    line = data.decode("utf-8", errors="replace")
    if line.endswith("\n"):          # single_value's add_newline (default on)
        line = line[:-1]
    out.log = line
    return out


def split_log_format(log_format: str) -> Tuple[List[str], List[str]]:
    """Split a ``<Name>`` token template into (literal segments, capture
    names): ``len(lits) == len(names) + 1``. The ONE home of the
    capture-token grammar for both the regex path (compile_log_format) and
    the fused C kernel (matchkern.ParseKernel)."""
    lits: List[str] = []
    names: List[str] = []
    pos = 0
    for match in _TOKEN_RE.finditer(log_format):
        lits.append(log_format[pos:match.start()])
        names.append(match.group(1))
        pos = match.end()
    lits.append(log_format[pos:])
    return lits, names


def compile_log_format(log_format: str) -> Tuple[Pattern, List[str]]:
    """Turn a ``<Name>`` token template into a regex + capture-name list."""
    lits, names = split_log_format(log_format)
    pattern_parts: List[str] = ["^"]
    for i, name in enumerate(names):
        pattern_parts.append(re.escape(lits[i]))
        # the capture that ends the format is greedy; all others lazy
        trailing = i == len(names) - 1 and lits[i + 1] == ""
        pattern_parts.append("(.*)" if trailing else "(.*?)")
    pattern_parts.append(re.escape(lits[-1]))
    pattern_parts.append("$")
    return re.compile("".join(pattern_parts)), names


def compile_template(template: str) -> Pattern:
    """Turn a drain-style ``<*>`` template into a matching regex."""
    parts = [re.escape(piece) for piece in template.split("<*>")]
    return re.compile("^" + "(.*?)".join(parts[:-1]) + ("(.*)" if len(parts) > 1 else "") + parts[-1] + "$")


class MatcherParser(CoreComponent):
    config_class = MatcherParserConfig
    category = "parsers"

    def __init__(self, name: Optional[str] = None, config: Any = None) -> None:
        super().__init__(name=name, config=config)
        self.config: MatcherParserConfig
        self._parse_counters = None
        self.apply_config()

    def apply_config(self) -> None:
        """(Re)build all config-derived state — also the runtime-reconfigure
        hook, so ``POST /admin/reconfigure`` can swap log_format or the
        template file on a live parser. Everything is built into locals and
        swapped in atomically at the end: a failure (bad log_format, missing
        templates file) raises BEFORE any live state changes, so the running
        parser keeps working on its old config instead of being bricked
        half-updated."""
        format_re: Optional[Pattern] = None
        format_names: List[str] = []
        if self.config.log_format:
            format_re, format_names = compile_log_format(self.config.log_format)
        templates: List[str] = []
        template_res: List[Pattern] = []
        if self.config.path_templates:
            templates, template_res = self._read_templates(self.config.path_templates)
        native = None
        parse_native = None
        logs_native = None
        emitter = None
        try:  # optional C++ matching kernel
            from ...utils import matchkern

            if templates:
                native = matchkern.TemplateMatcher(
                    [self._normalize(t) for t in templates]
                )
            # fused whole-row kernel (round 5): decode + header extraction +
            # normalize + match + ParserSchema encode in one C pass.
            # time_format needs strptime/mktime with Python's exact quirks —
            # those configs stay on the Python path.
            if (matchkern.has_parse_kernel() and not self.config.time_format
                    and self.config.native_parse):
                from ...schemas import SCHEMA_VERSION

                flags = ((1 if self.config.remove_spaces else 0)
                         | (2 if self.config.remove_punctuation else 0)
                         | (4 if self.config.lowercase else 0))
                lits, names = (split_log_format(self.config.log_format)
                               if self.config.log_format else ([], []))
                parse_native = matchkern.ParseKernel(
                    lits=lits, names=names, norm_flags=flags,
                    accept_raw=self.config.accept_raw_lines,
                    matcher=native, raw_templates=templates,
                    method_type=self.config.method_type,
                    parser_id=self.name, version=SCHEMA_VERSION)
            # zero-copy host-path round: decode-only LogSchema span kernel +
            # native ParserSchema emitter for the batched Python path (rows
            # the fused kernel flags, and configs — e.g. time_format — the
            # fused kernel cannot take at all): no pb2 object per row on
            # either side of the Python middle
            if matchkern.has_logs_kernel() and self.config.native_parse:
                from ...schemas import SCHEMA_VERSION

                logs_native = matchkern
                emitter = matchkern.ParserEmitter(
                    SCHEMA_VERSION, self.config.method_type, self.name)
        except Exception:
            native = native or None
            parse_native = None
            logs_native = None
            emitter = None
        self._format_re, self._format_names = format_re, format_names
        self._templates, self._template_res = templates, template_res
        self._native = native
        self._parse_native = parse_native
        self._logs_native = logs_native
        self._emitter = emitter

    def _read_templates(self, path: str):
        try:
            text = Path(path).read_text(encoding="utf-8")
        except OSError as exc:
            raise LibraryError(f"{self.name}: cannot read templates file {path}: {exc}") from exc
        templates = [line.rstrip("\n") for line in text.splitlines() if line.strip()]
        return templates, [compile_template(self._normalize(t)) for t in templates]

    # ------------------------------------------------------------------
    def _normalize(self, text: str) -> str:
        if self.config.lowercase:
            text = text.lower()
        if self.config.remove_punctuation:
            # keep the <*> wildcard intact while stripping punctuation
            text = "\x00*\x00".join(
                piece.translate(_PUNCT_TABLE) for piece in text.split("<*>")
            ).replace("\x00*\x00", "<*>")
        if self.config.remove_spaces:
            text = "<*>".join(piece.replace(" ", "") for piece in text.split("<*>"))
        return text

    def match_templates(self, content: str) -> Tuple[int, str, List[str]]:
        """Return (EventID, template, variables); EventID is the 1-based index
        of the first matching template, -1 when nothing matches."""
        normalized = self._normalize(content)
        if self._native is not None:
            idx, variables = self._native.match(normalized)
            if idx >= 0:
                return idx + 1, self._templates[idx], variables
            return -1, "", []
        for idx, template_re in enumerate(self._template_res):
            found = template_re.match(normalized)
            if found:
                return idx + 1, self._templates[idx], [g for g in found.groups() if g is not None]
        return -1, "", []

    def _extract_header(self, log_line: str):
        """Shared by the single-message and batched paths: ``log_format``
        header capture + Time conversion → (header_vars, content), or None
        for an empty/whitespace line (filtered)."""
        if not log_line.strip():
            return None
        header_vars = {}
        content = log_line
        if self._format_re is not None:
            found = self._format_re.match(log_line)
            if found:
                header_vars = dict(zip(self._format_names, found.groups()))
                content = header_vars.get("Content", log_line)
        if self.config.time_format and "Time" in header_vars:
            try:
                parsed = time.strptime(header_vars["Time"], self.config.time_format)
                header_vars["Time"] = str(int(time.mktime(parsed)))
            except (ValueError, OverflowError, OSError):
                # mktime can raise OverflowError/OSError on out-of-range years;
                # a bad Time keeps its raw string, never aborts the batch
                pass
        return header_vars, content

    def parse_line(self, log_line: str, log_id: str = "",
                   received_ts: Optional[int] = None) -> Optional[ParserSchema]:
        """Parse one raw line into a ParserSchema (None = unparseable/filtered)."""
        extracted = self._extract_header(log_line)
        if extracted is None:
            return None
        header_vars, content = extracted
        event_id, template, variables = (
            self.match_templates(content) if self._templates else (-1, "", [])
        )
        now = int(time.time())
        out = ParserSchema()
        out["parserType"] = self.config.method_type
        out["parserID"] = self.name
        out["EventID"] = event_id
        out["template"] = template
        out["variables"] = variables
        out["parsedLogID"] = uuid.uuid4().hex
        out["logID"] = log_id
        # reference quirk: MatcherParser writes its own name into `log`
        out["log"] = self.name
        out["logFormatVariables"] = header_vars
        out["receivedTimestamp"] = received_ts if received_ts is not None else now
        out["parsedTimestamp"] = now
        return out

    def process(self, data: bytes) -> Optional[bytes]:
        try:
            msg = decode_ingest_payload(data, self.config.accept_raw_lines)
        except SchemaError as exc:
            raise LibraryError(f"{self.name}: cannot deserialize LogSchema: {exc}") from exc
        parsed = self.parse_line(msg.log, log_id=msg.logID)
        return parsed.serialize() if parsed is not None else None

    def process_batch(self, batch: List[bytes]) -> List[Optional[bytes]]:
        """Batched hot path (what the engine's micro-batch mode calls):
        identical field semantics to ``process`` — pinned by
        test_process_batch_matches_process — but built straight on the
        generated pb2 classes. The dict-style wrapper's field-descriptor
        lookups were ~40% of the per-line budget (11 assignments/message);
        at pipeline rates that overhead IS the parser stage's ceiling.

        With the fused C kernel available the whole row runs native
        (``dm_parse_batch``: decode + header extract + normalize + match +
        encode); rows the kernel cannot do with exact parity come back
        flagged and re-run through this Python path one by one."""
        if self._parse_native is not None:
            return self._process_batch_native(batch)
        return self._process_batch_python(batch)

    def _process_batch_native(self, batch: List[bytes]) -> List[Optional[bytes]]:
        status, blob, ends = self._parse_native.parse_batch(batch)
        return self._assemble_native_outputs(status, ends, blob,
                                             batch.__getitem__)

    def _count_parse_rows(self, native: int, fallback: int) -> None:
        """parse_native_rows_total / parse_fallback_rows_total — which path
        decoded+serialized how many rows (label children cached: this runs
        once per micro-batch on the hot path)."""
        if not native and not fallback:
            return
        if self._parse_counters is None:
            from ...engine import metrics as m

            self._parse_counters = (
                m.PARSE_NATIVE_ROWS().labels(**self.metrics_labels),
                m.PARSE_FALLBACK_ROWS().labels(**self.metrics_labels))
        if native:
            self._parse_counters[0].inc(native)
        if fallback:
            self._parse_counters[1].inc(fallback)

    def _assemble_native_outputs(self, status, ends, blob, raw_fn):
        """Shared status→outputs dispatch for the batch and frames kernels:
        1 = emitted bytes, 0 = filtered (None), -1 = re-run the row's raw
        payload (``raw_fn(i)``) through the exact-semantics Python path.

        Every flagged row — one stray JSON record or a whole ``@type json``
        burst alike — rides ONE batched fallback sub-call
        (``_process_batch_python``: native LogSchema span decode, one native
        template scan, native ParserSchema emit), spliced back in order.
        The old per-row ``parse_line`` fallback built two pb2 objects per
        flagged row even when the batch was otherwise on the native path;
        identical fields either way, pinned by test_native_kernels."""
        status_list = status.tolist()
        n = len(status_list)
        flagged = [i for i, st in enumerate(status_list) if st == -1]
        # flagged rows are counted by the fallback sub-call itself (its
        # hybrid path may still decode+emit them natively) — counting them
        # here too would double-book the partition
        self._count_parse_rows(n - len(flagged), 0)
        if len(flagged) == n:
            return self._process_batch_python([raw_fn(i) for i in range(n)])
        outs: List[Optional[bytes]] = [None] * n
        if flagged:
            sub = self._process_batch_python([raw_fn(i) for i in flagged])
            for j, i in enumerate(flagged):
                outs[i] = sub[j]
        ends_list = ends.tolist()
        for i, st in enumerate(status_list):
            if st == 1:
                outs[i] = blob[ends_list[i]:ends_list[i + 1]]
        return outs

    def process_frames(self, frames: List[bytes]):
        """Fused wire-frame hot path (engine contract, opt-in): RAW wire
        frames in, ``(outputs, n_messages, n_lines)`` out — the parser
        service's analog of the detector's ``process_frames``. Frame
        expansion AND the whole parse row run in one C pass
        (``dm_parse_frames``); the engine loop holds no per-message Python
        objects. Without the kernel — including an older committed library
        that has dm_parse_batch but not the frames symbol — frames expand
        in Python and delegate to ``process_batch``: same semantics,
        classic costs, never a dropped burst."""
        if self._parse_native is None or not self._parse_native.supports_frames:
            if self._logs_native is not None:
                # no fused kernel (e.g. time_format configured) but the
                # decode kernel is here: frame expansion + LogSchema decode
                # still run in one C pass, and only header extraction /
                # time conversion / matching touch Python strings
                view = self._logs_native.parse_logs_frames(
                    frames, self.config.accept_raw_lines)
                if view.n_corrupt_frames:
                    self.count_processing_errors(view.n_corrupt_frames,
                                                 "corrupt batch frame(s)")
                return (self._outputs_from_view(view, view.raw),
                        len(view), view.n_lines)
            from ...engine.framing import FramingError, unpack_batch

            msgs: List[bytes] = []
            n_corrupt = 0
            for frame in frames:
                try:
                    unpacked = unpack_batch(frame)
                except FramingError:
                    n_corrupt += 1
                    continue
                if unpacked is None:
                    if frame:
                        msgs.append(frame)
                else:
                    msgs.extend(m for m in unpacked if m)
            if n_corrupt:
                self.count_processing_errors(n_corrupt,
                                             "corrupt batch frame(s)")
            n_lines = sum(
                max(1, d.count(b"\n") + (0 if d.endswith(b"\n") else 1))
                for d in msgs)
            return self.process_batch(msgs), len(msgs), n_lines
        pf = self._parse_native.parse_frames(frames)
        if pf.n_corrupt_frames:
            self.count_processing_errors(pf.n_corrupt_frames,
                                         "corrupt batch frame(s)")
        outs = self._assemble_native_outputs(pf.status, pf.ends, pf.out_blob,
                                             pf.raw)
        return outs, len(pf.status), pf.n_lines

    def _process_batch_python(self, batch: List[bytes]) -> List[Optional[bytes]]:
        """Batched fallback path — the rows the fused kernel flags, plus
        every row when it is unavailable (``time_format``, ``native_parse``
        off, no compiler). With the decode/emit kernels built, the pb2
        crossings disappear from this path too (``_process_batch_hybrid``);
        the pure-pb2 body (``_process_batch_pb2``) remains the exact-parity
        reference the differential fuzzer compares both native paths
        against."""
        if self._logs_native is not None and self._emitter is not None:
            view = self._logs_native.parse_logs_batch(
                batch, self.config.accept_raw_lines)
            return self._outputs_from_view(view, batch.__getitem__)
        return self._process_batch_pb2(batch)

    def _decode_json_row(self, data: bytes) -> Tuple[str, str]:
        """``decode_ingest_payload``'s JSON / bare-line shapes minus the
        throwaway LogSchema pb2 carrier — only ``log`` / ``logID`` are ever
        read by the parse path. Field mapping identical by construction."""
        rec = None
        if data[:1] == b"{":
            try:
                rec = json.loads(data)
            except (ValueError, UnicodeDecodeError):
                rec = None
        if isinstance(rec, dict) and ("message" in rec or "log" in rec):
            log = str(rec.get("message", rec.get("log", "")))
            log_id = str(rec["logID"]) if rec.get("logID") else ""
            return log, log_id
        line = data.decode("utf-8", errors="replace")
        if line.endswith("\n"):          # single_value's add_newline
            line = line[:-1]
        return line, ""

    def _outputs_from_view(self, view, raw_fn) -> List[Optional[bytes]]:
        """Assemble outputs from a native ``LogsView`` (decode-only kernel):
        header extraction, time conversion, and template matching run on
        Python strings sliced lazily from the wire blob; serialization goes
        back through the native emitter's reusable arena. Statuses 1/2 never
        touch a pb2 object; 0 (JSON) uses the dict mapping; -1 is the exact
        per-row pb2 escape hatch (strict-mode decode failures, counted like
        the reference path)."""
        status = view.status.tolist()
        n = len(status)
        decode_errors = 0
        native_rows = fallback_rows = 0
        decoded: List[Any] = []          # (log, logID) | False (error)
        for i, st in enumerate(status):
            if st == 1 or st == 2:
                decoded.append((view.log(i), view.log_id(i)))
                native_rows += 1
                continue
            fallback_rows += 1
            if st == 0:
                decoded.append(self._decode_json_row(raw_fn(i)))
                continue
            try:                          # -1: strict parse failure et al.
                msg = decode_ingest_payload(raw_fn(i),
                                            self.config.accept_raw_lines)
            except SchemaError:
                decode_errors += 1
                decoded.append(False)
                continue
            decoded.append((msg.log, msg.logID))
        outs = self._assemble_decoded(decoded)
        if decode_errors:
            self.count_processing_errors(decode_errors,
                                         "undecodable LogSchema message(s)")
        self._count_parse_rows(native_rows, fallback_rows)
        return outs

    def _assemble_decoded(self, decoded) -> List[Optional[bytes]]:
        """(log, logID) rows → serialized ParserSchema bytes via the native
        emitter: identical field semantics to ``_process_batch_pb2``'s
        assembly loop (pinned by the differential fuzzer), one C crossing
        for the whole batch instead of a pb2 object + SerializeToString per
        row."""
        from os import urandom

        outs: List[Optional[bytes]] = [None] * len(decoded)
        emit_idx: List[int] = []
        extracted_list = []
        for i, item in enumerate(decoded):
            if item is False:
                continue
            extracted = self._extract_header(item[0])
            if extracted is None:
                continue                 # blank line: filtered
            emit_idx.append(i)
            extracted_list.append(extracted)
        if not emit_idx:
            return outs
        have_templates = bool(self._templates)
        if have_templates and self._native is not None:
            matches = self._native.match_batch(
                [self._normalize(content) for _, content in extracted_list])
        else:
            matches = None
        event_ids: List[int] = []
        templates: List[bytes] = []
        variables: List[List[bytes]] = []
        log_ids: List[bytes] = []
        kv_items: List[List[Tuple[bytes, bytes]]] = []
        for j, i in enumerate(emit_idx):
            header_vars, content = extracted_list[j]
            if not have_templates:
                event_id, template, caps = -1, "", []
            elif matches is not None:
                idx, caps = matches[j]
                if idx >= 0:
                    event_id, template = idx + 1, self._templates[idx]
                else:
                    event_id, template, caps = -1, "", []
            else:
                event_id, template, caps = self.match_templates(content)
            event_ids.append(event_id)
            templates.append(template.encode("utf-8"))
            variables.append([v.encode("utf-8") for v in caps])
            log_ids.append(decoded[i][1].encode("utf-8"))
            kv_items.append([
                (k.encode("utf-8"),
                 (v if v is not None else "").encode("utf-8"))
                for k, v in header_vars.items()])
        now = int(time.time())
        rand_hex = urandom(16 * len(emit_idx)).hex().encode()
        arena, offs = self._emitter.emit(event_ids, templates, variables,
                                         log_ids, kv_items, now, rand_hex)
        offs_list = offs.tolist()
        for j, i in enumerate(emit_idx):
            outs[i] = arena[offs_list[j]:offs_list[j + 1]].tobytes()
        return outs

    def _process_batch_pb2(self, batch: List[bytes]) -> List[Optional[bytes]]:
        from os import urandom

        from ...schemas import SCHEMA_VERSION, schemas_pb2 as _pb

        outs: List[Optional[bytes]] = []
        method_type = self.config.method_type
        name = self.name
        have_templates = bool(self._templates)
        decode_errors = 0

        # pass 1: decode + header extraction; collect normalized content so
        # the native template scan runs as ONE ctypes call for the whole
        # batch (per-call ctypes overhead was ~20 µs/line — the ceiling)
        prepared = []  # (msg, header_vars, content) | None (filtered) | False (error)
        contents: List[str] = []
        accept_raw = self.config.accept_raw_lines
        for data in batch:
            try:
                msg = decode_ingest_payload(data, accept_raw)
            except SchemaError:
                decode_errors += 1  # surfaced below; containment per message
                prepared.append(False)
                continue
            extracted = self._extract_header(msg.log)
            if extracted is None:
                prepared.append(None)
                continue
            header_vars, content = extracted
            prepared.append((msg, header_vars, content))
            if have_templates:
                contents.append(self._normalize(content))
        if have_templates and self._native is not None and contents:
            matches = iter(self._native.match_batch(contents))
        else:
            matches = None

        for item in prepared:
            if item is False or item is None:
                outs.append(None)
                continue
            msg, header_vars, content = item
            if not have_templates:
                event_id, template, variables = -1, "", []
            elif matches is not None:
                idx, variables = next(matches)
                if idx >= 0:
                    event_id, template = idx + 1, self._templates[idx]
                else:
                    event_id, template, variables = -1, "", []
            else:
                event_id, template, variables = self.match_templates(content)
            now = int(time.time())
            out = _pb.ParserSchema()
            setattr(out, "__version__", SCHEMA_VERSION)
            out.parserType = method_type
            out.parserID = name
            out.EventID = event_id
            out.template = template
            if variables:
                out.variables.extend(variables)
            # same 32-hex-char opaque unique id as parse_line's uuid4().hex,
            # minus the UUID-object construction (~15% of the loop budget)
            out.parsedLogID = urandom(16).hex()
            # unconditional assignment on purpose: these are explicit-presence
            # (optional) fields, and parse_line always assigns them — an
            # empty logID must still serialize its presence bit for
            # byte-parity with the single-message path
            out.logID = msg.logID
            out.log = name  # reference quirk: parser name, not the line
            for key, value in header_vars.items():
                out.logFormatVariables[key] = value if value is not None else ""
            out.receivedTimestamp = now
            out.parsedTimestamp = now
            outs.append(out.SerializeToString())
        if decode_errors:
            # the single-message path raises LibraryError per message, which
            # the engine logs and counts in processing_errors_total — batched
            # decode failures must be just as visible, in the SAME series
            self.count_processing_errors(decode_errors,
                                         "undecodable LogSchema message(s)")
        self._count_parse_rows(0, len(batch))
        return outs
