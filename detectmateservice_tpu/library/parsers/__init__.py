from .template_matcher import MatcherParser, MatcherParserConfig

__all__ = ["MatcherParser", "MatcherParserConfig"]
