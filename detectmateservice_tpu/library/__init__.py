"""In-tree component library (role of the out-of-tree ``detectmatelibrary``
PyPI package in the reference, pyproject.toml:10; surface per SURVEY.md §2.9)."""
