"""Output/aggregation components (reference fluentout role,
container/fluentout/fluent.conf:1-24)."""
from .file_sink import OutputWriter, OutputWriterConfig

__all__ = ["OutputWriter", "OutputWriterConfig"]
