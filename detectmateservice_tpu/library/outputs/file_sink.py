"""OutputWriter: the pipeline's aggregation/sink stage (fluentout role).

The reference closes its demo pipeline with a fluentd container that
nng-receives DetectorSchema protobufs and writes them to dated files
(reference: container/fluentout/fluent.conf:1-24 — nng_in + protobuf parse →
``output.%Y%m%d`` files, schema decoded via container/fluentout/schemas_pb.rb:8).
This component is that stage as a first-class service component: it consumes
``DetectorSchema`` alerts, aggregates them into ``OutputSchema`` records
(the schema's repeated fields — detectorIDs, alertIDs, logIDs... — exist
precisely because one output record may carry several alerts), appends each
record as a JSON line to a strftime-dated file, and forwards the serialized
``OutputSchema`` downstream for anything dialed after it.
"""
from __future__ import annotations

import json
import time
from typing import IO, Any, Dict, List, Optional

from ...schemas import DetectorSchema, OutputSchema, SchemaError
from ..common.core import CoreComponent, CoreConfig


class OutputWriterConfig(CoreConfig):
    method_type: str = "output_writer"
    output_dir: str = "."
    # reference fluentout writes ``output.%Y%m%d`` (fluent.conf path+time_slice)
    file_pattern: str = "output.%Y%m%d"
    # alerts aggregated into one OutputSchema record; 1 = one record per alert
    aggregate_count: int = 1
    # >0: a partial group older than this flushes on the next message/flush
    aggregate_window_ms: int = 1000
    write_files: bool = True
    # also emit the serialized OutputSchema to downstream sockets
    emit_records: bool = True


class OutputWriter(CoreComponent):
    config_class = OutputWriterConfig
    description = "OutputWriter aggregates alerts into dated OutputSchema records."

    def __init__(self, name: Optional[str] = None, config: Any = None) -> None:
        super().__init__(name=name or "OutputWriter", config=config)
        self.config: OutputWriterConfig
        self._pending: List[DetectorSchema] = []
        self._group_started: float = 0.0
        self._sink: Optional[IO[str]] = None
        self._sink_path: Optional[str] = None
        self.records_written = 0

    # -- engine contract -------------------------------------------------
    def process(self, data: bytes) -> Optional[bytes]:
        """DetectorSchema bytes in → OutputSchema bytes out (or ``None``
        while a group is still filling)."""
        try:
            alert = DetectorSchema.from_bytes(data)
        except SchemaError:
            return None  # corrupt frame: filter, never kill the loop
        if not self._pending:
            self._group_started = time.monotonic()
        self._pending.append(alert)
        if len(self._pending) >= max(1, self.config.aggregate_count):
            return self._emit_group()
        if self._window_expired():
            return self._emit_group()
        return None

    def flush(self) -> List[Optional[bytes]]:
        """Engine idle hook: emit a partial group once its window expired."""
        if self._pending and self._window_expired():
            return [self._emit_group()]
        return []

    def flush_final(self) -> List[Optional[bytes]]:
        """Stop-time drain: emit whatever is pending, then close the file."""
        out: List[Optional[bytes]] = []
        if self._pending:
            out.append(self._emit_group())
        self.teardown()
        return out

    def apply_config(self) -> None:
        """Runtime reconfigure: close the open sink so the next record
        reopens under the (possibly new) output_dir/file_pattern."""
        self.teardown()

    def teardown(self) -> None:
        if self._sink is not None:
            try:
                self._sink.close()
            finally:
                self._sink = None
                self._sink_path = None

    # -- aggregation -----------------------------------------------------
    def _window_expired(self) -> bool:
        window = self.config.aggregate_window_ms
        return (window > 0 and self._pending
                and (time.monotonic() - self._group_started) * 1000.0 >= window)

    def _emit_group(self) -> Optional[bytes]:
        alerts, self._pending = self._pending, []
        record = self._aggregate(alerts)
        if self.config.write_files:
            self._write_record(record)
        self.records_written += 1
        return record.serialize() if self.config.emit_records else None

    def _aggregate(self, alerts: List[DetectorSchema]) -> OutputSchema:
        """N DetectorSchema → one OutputSchema (repeated fields concatenate,
        alertsObtain merges; field semantics match the reference's decoded
        OutputSchema, container/fluentout/schemas_pb.rb:8)."""
        record = OutputSchema(outputTimestamp=int(time.time()))
        obtain: Dict[str, str] = {}
        descriptions: List[str] = []
        for alert in alerts:
            record["detectorIDs"].append(alert.detectorID)
            record["detectorTypes"].append(alert.detectorType)
            record["alertIDs"].append(alert.alertID)
            record["logIDs"].extend(alert.logIDs)
            record["extractedTimestamps"].extend(alert.extractedTimestamps)
            if alert.description:
                descriptions.append(alert.description)
            obtain.update(dict(alert.alertsObtain))
        if descriptions:
            record["description"] = "; ".join(descriptions)
        if obtain:
            record["alertsObtain"].update(obtain)
        return record

    # -- file sink -------------------------------------------------------
    def _write_record(self, record: OutputSchema) -> None:
        import os

        path = os.path.join(self.config.output_dir,
                            time.strftime(self.config.file_pattern))
        if path != self._sink_path:  # first write, or the date rolled over
            self.teardown()
            os.makedirs(self.config.output_dir, exist_ok=True)
            self._sink = open(path, "a", encoding="utf-8")
            self._sink_path = path
        self._sink.write(json.dumps(record.to_dict(), sort_keys=True) + "\n")
        self._sink.flush()
