from .data_buffer import BufferMode, DataBuffer

__all__ = ["BufferMode", "DataBuffer"]
