"""Buffering modes for detectors.

Parity with the reference library's ``utils.data_buffer.BufferMode``
(reference contract: docs/interfaces.md:143-167 — ``BufferMode.NO_BUF`` passed
to ``CoreDetector``). The TPU build adds ``MICRO_BATCH``: the engine-side
micro-batcher hands the detector lists of messages for fixed-shape scoring.
"""
from __future__ import annotations

import enum
from collections import deque
from typing import Deque, List, Optional


class BufferMode(enum.Enum):
    NO_BUF = "no_buf"          # process each message immediately
    FIXED = "fixed"            # buffer N messages, then process the window
    MICRO_BATCH = "micro_batch"  # engine-driven batches (TPU addition)


class DataBuffer:
    """Bounded FIFO window used by detectors in ``FIXED`` mode."""

    def __init__(self, size: int = 32):
        self._size = max(1, size)
        self._items: Deque = deque(maxlen=self._size)

    def push(self, item) -> Optional[List]:
        """Add an item; returns the full window when it fills, else None."""
        self._items.append(item)
        if len(self._items) == self._size:
            window = list(self._items)
            self._items.clear()
            return window
        return None

    def flush(self) -> List:
        window = list(self._items)
        self._items.clear()
        return window

    def __len__(self) -> int:
        return len(self._items)
