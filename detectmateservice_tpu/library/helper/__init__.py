from .from_to import From

__all__ = ["From"]
