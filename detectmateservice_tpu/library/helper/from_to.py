"""File-ingestion helper.

Parity with the reference library's ``detectmatelibrary.helper.from_to.From``
(usage evidence: tests/library_integration/test_one_pipe_to_rule_them_all.py:22,136
— ``From.log(parser, path, do_process=True)`` yields LogSchema objects, with
``None`` entries for filtered lines).
"""
from __future__ import annotations

import socket
import uuid
from pathlib import Path
from typing import Iterator, Optional

from ...schemas import LogSchema


class From:
    @staticmethod
    def log(component, path, do_process: bool = True) -> Iterator[Optional[LogSchema]]:
        """Yield one LogSchema per line of ``path``; blank/unparseable lines
        yield None so callers can filter (matching the reference idiom
        ``[log for log in From.log(...) if log is not None]``).

        ``component`` may veto lines via an ``accepts_line(str) -> bool`` hook;
        with ``do_process=False`` the raw line strings are yielded instead.
        """
        hostname = socket.gethostname()
        accepts = getattr(component, "accepts_line", None)
        with open(Path(path), "r", encoding="utf-8", errors="replace") as fh:
            for line in fh:
                line = line.rstrip("\n")
                if not line.strip():
                    yield None
                    continue
                if callable(accepts) and not accepts(line):
                    yield None
                    continue
                if not do_process:
                    yield line  # type: ignore[misc]
                    continue
                yield LogSchema(
                    logID=str(uuid.uuid4()),
                    log=line,
                    logSource=str(path),
                    hostname=hostname,
                )
