"""Detector base: train-then-detect streaming components.

Capability parity with the reference library's
``detectmatelibrary.common.detector`` surface (reconstructed from
docs/interfaces.md:141-204, tests/test_reconfigure_params.py:10, and the demo
semantics in docs/getting_started.md:420-434):

* ``CoreDetector(name, buffer_mode, config)`` with overridable
  ``train(input_)`` and ``detect(input_, output_) -> bool``,
* config structure *events → EventID → instance → {params, variables
  [{pos,name,params}], header_variables [{pos,params}]}* plus a ``global``
  scope applying to every event
  (reference: container/config/detector_config.yaml,
  tests/config/detector_config.yaml),
* the first ``data_use_training`` messages only train (and are filtered);
  afterwards ``detect`` runs and a ``DetectorSchema`` alert is emitted only
  when it returns True — "no detection" produces no output at all (pinned in
  the reference by pynng.Timeout assertions,
  tests/library_integration/test_detector_integration.py:85-87).
"""
from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, ConfigDict, Field

from ...schemas import DetectorSchema, ParserSchema, SchemaError
from ..utils.data_buffer import BufferMode, DataBuffer
from .core import CoreComponent, CoreConfig, LibraryError


class Variable(BaseModel):
    """A positional variable watched by a detector instance (``pos`` indexes
    into ``ParserSchema.variables``)."""

    model_config = ConfigDict(extra="allow")
    pos: Union[int, str]
    name: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)

    @property
    def label(self) -> str:
        return self.name if self.name is not None else str(self.pos)


class HeaderVariable(BaseModel):
    """A named variable watched via ``ParserSchema.logFormatVariables``."""

    model_config = ConfigDict(extra="allow")
    pos: str
    params: Dict[str, Any] = Field(default_factory=dict)

    @property
    def label(self) -> str:
        return self.pos


class InstanceConfig(BaseModel):
    """One named detector instance within an event (or global) scope."""

    model_config = ConfigDict(extra="allow")
    params: Dict[str, Any] = Field(default_factory=dict)
    variables: List[Variable] = Field(default_factory=list)
    header_variables: List[HeaderVariable] = Field(default_factory=list)

    def get_all(self) -> Dict[str, Union[Variable, HeaderVariable]]:
        """All watched fields keyed by label (reference usage:
        docs/interfaces.md:187)."""
        out: Dict[str, Union[Variable, HeaderVariable]] = {}
        for var in self.variables:
            out[var.label] = var
        for hvar in self.header_variables:
            out[hvar.label] = hvar
        return out


class CoreDetectorConfig(CoreConfig):
    method_type: str = "core_detector"
    data_use_training: int = 0
    # "no_buf" | "fixed" | "micro_batch": overrides the constructor default
    # so the service loader (which only passes config) can select FIXED
    # windowed detection from YAML; None keeps the component's own default
    buffer_mode: Optional[str] = None
    buffer_size: int = 32  # FIXED mode: messages per detection window
    events: Dict[Union[int, str], Dict[str, InstanceConfig]] = Field(default_factory=dict)
    global_: Dict[str, InstanceConfig] = Field(default_factory=dict, alias="global")

    def event_instances(self, event_id: Any) -> Dict[str, InstanceConfig]:
        """Instances for one event id (int/str keys both accepted)."""
        for key in (event_id, str(event_id)):
            if key in self.events:
                return self.events[key]
        try:
            as_int = int(event_id)
        except (TypeError, ValueError):
            return {}
        return self.events.get(as_int, {})


class CoreDetector(CoreComponent):
    """Streaming detector: deserialize → (train | detect) → alert | None."""

    config_class = CoreDetectorConfig
    category = "detectors"
    description = "CoreDetector base class."

    def __init__(
        self,
        name: Optional[str] = None,
        buffer_mode: BufferMode = BufferMode.NO_BUF,
        config: Any = None,
    ) -> None:
        super().__init__(name=name, config=config)
        self.config: CoreDetectorConfig
        cfg_mode = getattr(self.config, "buffer_mode", None)
        if cfg_mode:  # YAML wins over the constructor default: the service
            try:      # loader only ever passes config (config/loader.py)
                buffer_mode = BufferMode(cfg_mode)
            except ValueError as exc:
                raise LibraryError(
                    f"{self.name}: unknown buffer_mode {cfg_mode!r}; expected "
                    f"one of {[m.value for m in BufferMode]}") from exc
        self.buffer_mode = buffer_mode
        self._buffer = (DataBuffer(int(getattr(self.config, "buffer_size", 32)))
                        if buffer_mode == BufferMode.FIXED else None)
        self._pending_outputs: List[bytes] = []  # windows detected off-path
        self._trained = 0
        self._alert_ids = itertools.count(int(getattr(self.config, "start_id", 0)))

    def validate_reconfigure(self, new_config) -> None:
        """``buffer_mode`` shapes the processing topology (windowed vs
        per-message vs engine-batched) — it cannot flip on a live instance.
        Compared against the EFFECTIVE mode (constructor default included),
        with an absent field meaning "keep the current mode"."""
        new_mode = getattr(new_config, "buffer_mode", None) or self.buffer_mode.value
        if new_mode != self.buffer_mode.value:
            raise LibraryError(
                f"buffer_mode cannot change at runtime (current="
                f"{self.buffer_mode.value!r} new={new_mode!r}); restart the service")

    def apply_config(self) -> None:
        """Runtime reconfigure: a changed ``buffer_size`` rebuilds the FIXED
        window in place. Every already-buffered message is re-pushed through
        the new window; windows that fill during the carry-over are detected
        immediately and their alerts surface via ``flush()`` (the engine's
        idle hook) — no buffered message is ever silently dropped."""
        if self._buffer is not None:
            new_size = max(1, int(getattr(self.config, "buffer_size", 32)))
            if new_size != self._buffer._size:
                old_items = self._buffer.flush()
                self._buffer = DataBuffer(new_size)
                for item in old_items:
                    window = self._buffer.push(item)
                    if window is not None:
                        out = self._detect_over_window(window)
                        if out is not None:
                            self._pending_outputs.append(out)

    def flush(self) -> List[Optional[bytes]]:
        """Engine idle hook: alerts produced off the process() path (windows
        completed during a reconfigure resize) drain here."""
        out, self._pending_outputs = self._pending_outputs, []
        return out

    # -- overridables ---------------------------------------------------
    def train(self, input_: Union[ParserSchema, List[ParserSchema]]) -> None:
        """Consume training messages (first ``data_use_training`` messages)."""

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        """Populate ``output_`` and return True to emit an alert."""
        raise NotImplementedError

    # -- engine contract ------------------------------------------------
    def process(self, data: bytes) -> Optional[bytes]:
        try:
            input_ = ParserSchema.from_bytes(data)
        except SchemaError as exc:
            raise LibraryError(f"{self.name}: cannot deserialize ParserSchema: {exc}") from exc
        return self.process_parsed(input_)

    def process_parsed(self, input_: ParserSchema) -> Optional[bytes]:
        if self._trained < self.config.data_use_training:
            self.train(input_)
            self._trained += 1
            return None
        if self._buffer is not None:  # FIXED: windowed detection
            window = self._buffer.push(input_)
            if window is None:
                return None
            return self._detect_over_window(window)
        output_ = self.make_output(input_)
        if self.detect(input_, output_):
            return output_.serialize()
        return None

    # -- FIXED (windowed) mode ------------------------------------------
    def _detect_over_window(self, window: List[ParserSchema]) -> Optional[bytes]:
        """One alert per window: the skeleton comes from the newest message,
        ``logIDs``/``extractedTimestamps`` cover the whole window."""
        output_ = self.make_output(window[-1])
        output_["logIDs"] = [m["logID"] for m in window if m.get("logID")]
        stamps = [self.extract_timestamp(m) for m in window]
        output_["extractedTimestamps"] = [s for s in stamps if s is not None]
        if self.detect_window(window, output_):
            return output_.serialize()
        return None

    def detect_window(self, window: List[ParserSchema],
                      output_: DetectorSchema) -> bool:
        """FIXED-mode hook: detect over a full window. The default ORs the
        per-message ``detect`` so any detector works windowed; contextual
        detectors override this for cross-message logic."""
        hit = False
        for input_ in window:
            hit = self.detect(input_, output_) or hit
        return hit

    def flush_final(self) -> List[Optional[bytes]]:
        """Stop-time drain: pending off-path alerts plus a partial FIXED
        window — no buffered message is silently lost at shutdown."""
        out = self.flush()
        if self._buffer is not None and len(self._buffer):
            out.append(self._detect_over_window(self._buffer.flush()))
        return out

    def make_output(self, input_: ParserSchema) -> DetectorSchema:
        """Prefill a DetectorSchema alert skeleton (field semantics per the
        demo record in the reference, docs/getting_started.md:505-510)."""
        now = int(time.time())
        output_ = DetectorSchema()
        output_["detectorID"] = self.name
        output_["detectorType"] = self.config.method_type
        output_["alertID"] = str(next(self._alert_ids))
        output_["detectionTimestamp"] = now
        output_["receivedTimestamp"] = now
        if input_.get("logID"):
            output_["logIDs"] = [input_["logID"]]
        ts = self.extract_timestamp(input_)
        output_["extractedTimestamps"] = [ts if ts is not None else now]
        output_["description"] = self.description
        return output_

    @staticmethod
    def extract_timestamp(input_: ParserSchema) -> Optional[int]:
        lfv = input_["logFormatVariables"]  # live map container, no copy
        for key in ("Time", "time", "timestamp"):
            value = lfv.get(key)
            if value:
                try:
                    return int(float(value))
                except (ValueError, OverflowError):
                    # '1e400'/'inf' must mean "no timestamp", not an exception
                    # that escapes process() and drops unrelated messages
                    return None
        if input_.get("receivedTimestamp"):
            return int(input_["receivedTimestamp"])
        return None

    # -- shared helpers for concrete detectors --------------------------
    def iter_scopes(self, input_: ParserSchema):
        """Yield (scope_label, instance_name, InstanceConfig) for the global
        scope and the event scope matching ``input_.EventID``."""
        for inst_name, inst in self.config.global_.items():
            yield "Global", inst_name, inst
        event_id = input_.get("EventID")
        for inst_name, inst in self.config.event_instances(event_id).items():
            yield f"Event {event_id}", inst_name, inst

    @staticmethod
    def field_value(input_: ParserSchema, var: Union[Variable, HeaderVariable]) -> Optional[str]:
        """Resolve a watched field's value from a parsed message."""
        if isinstance(var, HeaderVariable) or isinstance(var.pos, str):
            return input_["logFormatVariables"].get(str(var.pos))  # no copy
        variables = input_["variables"]
        if 0 <= var.pos < len(variables):
            return variables[var.pos]
        return None
