"""Base component contract: ``CoreComponent`` + ``CoreConfig``.

Capability parity with the reference library's base surface (the library is
out-of-tree PyPI in the reference; its contract is reconstructed from
docs/interfaces.md:5-83 and the service tests,
tests/test_component_loader/test_detectmatelibrary_import.py:12-27):

* ``CoreComponent(name=None, config=None)`` with ``process(bytes) -> bytes|None``,
* ``CoreConfig`` is a pydantic model with a ``start_id`` field and
  ``from_dict`` / ``to_dict``,
* config normalization semantics (docs/interfaces.md:74-82): ``auto_config``
  gate, ``method_type`` check, ``all_``-prefix parameter broadcast, and
  flattening of the ``params`` sub-dict into the top level.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Type

from pydantic import BaseModel, ConfigDict, ValidationError

CATEGORIES = ("detectors", "parsers", "readers", "outputs")


class LibraryError(Exception):
    """Base error for component-library failures."""


class AutoConfigError(LibraryError):
    """auto_config is disabled but no usable parameters were provided
    (reference contract: docs/interfaces.md:74)."""


class MethodTypeError(LibraryError):
    """Configured method_type does not match the component
    (reference contract: docs/interfaces.md:76)."""


class CoreConfig(BaseModel):
    """Base configuration model for all components."""

    model_config = ConfigDict(extra="allow", populate_by_name=True)

    method_type: str = "core"
    auto_config: bool = True
    start_id: int = 0
    params: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any], name: Optional[str] = None) -> "CoreConfig":
        """Build a config from the namespaced on-disk shape.

        Accepts either the full *category → ClassName → params* document or
        the already-extracted per-component mapping, then applies the
        normalization pipeline (docs/interfaces.md:74-82): auto_config gate,
        method_type check, ``all_`` broadcast, params flattening.
        """
        section = _extract_section(data, name)
        section = normalize_config(dict(section), expected_method_type=_expected_method_type(cls))
        try:
            return cls.model_validate(section)
        except ValidationError as exc:
            raise LibraryError(f"invalid config for {name or cls.__name__}: {exc}") from exc

    def to_dict(self) -> Dict[str, Any]:
        """Dump with defaults stripped (used by ConfigManager.save,
        reference: src/service/features/config_manager.py:85-92)."""
        return self.model_dump(exclude_defaults=True, by_alias=True)


def _expected_method_type(cls: Type[CoreConfig]) -> Optional[str]:
    field = cls.model_fields.get("method_type")
    if field is not None and isinstance(field.default, str) and field.default != "core":
        return field.default
    return None


def _extract_section(data: Dict[str, Any], name: Optional[str]) -> Dict[str, Any]:
    if not isinstance(data, dict):
        raise LibraryError(f"config must be a mapping, got {type(data).__name__}")
    for category in CATEGORIES:
        block = data.get(category)
        if isinstance(block, dict):
            if name and name in block:
                return block[name]
            if len(block) == 1:
                return next(iter(block.values())) or {}
    return data


def normalize_config(section: Dict[str, Any], expected_method_type: Optional[str] = None) -> Dict[str, Any]:
    """Apply the reference library's config normalization pipeline."""
    method_type = section.get("method_type")
    if expected_method_type and method_type and method_type != expected_method_type:
        raise MethodTypeError(
            f"method_type {method_type!r} does not match expected {expected_method_type!r}"
        )
    auto_config = section.get("auto_config", True)
    params = section.get("params") or {}
    has_structure = any(
        section.get(k) for k in ("events", "global", "variables", "header_variables")
    )
    meaningful = {k for k in section if k not in ("method_type", "auto_config", "params")}
    if not auto_config and not params and not has_structure and not meaningful:
        raise AutoConfigError(
            "auto_config is disabled but no parameters were provided"
        )
    # ``all_`` broadcast: all_<key> in params becomes <key>, pushed down into
    # every variable/instance params block that does not already set it
    broadcast = {k[len("all_"):]: v for k, v in params.items() if k.startswith("all_")}
    params = {k: v for k, v in params.items() if not k.startswith("all_")}
    if broadcast:
        params.update({k: v for k, v in broadcast.items() if k not in params})
        for events_key in ("events", "global"):
            block = section.get(events_key)
            if isinstance(block, dict):
                _push_down_params(block, broadcast)
    # flatten: top level absorbs params, params key removed
    flattened = dict(section)
    flattened.pop("params", None)
    for key, value in params.items():
        flattened.setdefault(key, value)
    return flattened


def _push_down_params(node: Any, broadcast: Dict[str, Any]) -> None:
    """Recursively seed every variables/header_variables params block with the
    broadcast values (without overriding explicit per-variable params)."""
    if not isinstance(node, dict):
        return
    for var_key in ("variables", "header_variables"):
        var_list = node.get(var_key)
        if isinstance(var_list, list):
            for var in var_list:
                if isinstance(var, dict):
                    var_params = var.setdefault("params", {})
                    for k, v in broadcast.items():
                        var_params.setdefault(k, v)
    for value in node.values():
        if isinstance(value, dict):
            _push_down_params(value, broadcast)


class CoreComponent:
    """Base processing component (reference contract: docs/interfaces.md:5-44)."""

    config_class: Type[CoreConfig] = CoreConfig
    category: str = "core"

    def __init__(self, name: Optional[str] = None, config: Any = None) -> None:
        self.name = name or type(self).__name__
        if isinstance(config, dict):
            config = self.config_class.from_dict(config, self.name)
        elif config is None:
            config = self.config_class()
        elif not isinstance(config, CoreConfig):
            raise LibraryError(
                f"config must be a dict or CoreConfig, got {type(config).__name__}"
            )
        self.config = config
        # the hosting Service overwrites this with ITS metric labels
        # (settings.component_type / component_id) so component-side error
        # counts land in the same processing_errors_total series the engine
        # uses for single-message failures — dashboards keyed on the
        # service's component_id must see batched failures too
        self.metrics_labels: Dict[str, str] = dict(
            component_type=getattr(config, "method_type", self.category),
            component_id=self.name)

    def count_processing_errors(self, n: int, what: str) -> None:
        """Count + log n per-message failures the component contained
        (batched paths swallow per-message errors instead of raising)."""
        import logging

        from ...engine import metrics as m

        m.PROCESSING_ERRORS().labels(**self.metrics_labels).inc(n)
        logging.getLogger(type(self).__module__).error(
            "%s: %d %s dropped", self.name, n, what)

    def process(self, data: bytes) -> Optional[bytes]:
        """Process one message; ``None`` filters it (no output is sent)."""
        raise NotImplementedError

    def setup_io(self) -> None:
        """Hook for expensive IO/model loading (reference: core.py:209-211)."""

    def teardown(self) -> None:
        """Hook for releasing resources."""

    def reconfigure(self, config: Dict[str, Any]) -> None:
        """Apply a new (already manager-validated) config document to the
        RUNNING instance — the capability the reference admits it lacks
        (reference: core.py:299-345 updates only the ConfigManager; the
        loaded component keeps its old config). The document is re-parsed
        through the component's own config class, swapped in atomically,
        then ``apply_config`` lets subclasses rebuild derived state."""
        new_config = self.config_class.from_dict(config, self.name)
        self.validate_reconfigure(new_config)
        old_config = self.config
        self.config = new_config
        try:
            self.apply_config()
        except Exception:
            self.config = old_config  # failed apply must not leave the
            raise                     # instance half-configured

    def validate_reconfigure(self, new_config: "CoreConfig") -> None:
        """Hook: veto a runtime config change (raise LibraryError) before it
        is applied — e.g. a change that would require a full refit."""

    def apply_config(self) -> None:
        """Hook: react to a swapped-in config (rebuild derived state)."""
