from .core import CoreComponent, CoreConfig, LibraryError, AutoConfigError, MethodTypeError
from .detector import CoreDetector, CoreDetectorConfig, InstanceConfig, Variable, HeaderVariable

__all__ = [
    "CoreComponent", "CoreConfig", "LibraryError", "AutoConfigError", "MethodTypeError",
    "CoreDetector", "CoreDetectorConfig", "InstanceConfig", "Variable", "HeaderVariable",
]
