from .new_value_detector import (
    NewValueDetector,
    NewValueDetectorConfig,
    NewValueComboDetector,
    NewValueComboDetectorConfig,
)
from .random_detector import RandomDetector, RandomDetectorConfig

__all__ = [
    "NewValueDetector", "NewValueDetectorConfig",
    "NewValueComboDetector", "NewValueComboDetectorConfig",
    "RandomDetector", "RandomDetectorConfig",
]

from .jax_scorer import JaxScorerDetector, JaxScorerDetectorConfig

__all__ += ["JaxScorerDetector", "JaxScorerDetectorConfig"]

from .llm_escalation import (
    LLMEscalationDetector,
    LLMEscalationDetectorConfig,
    OpenAICompatClient,
    RuleStubLLMClient,
)

__all__ += [
    "LLMEscalationDetector", "LLMEscalationDetectorConfig",
    "OpenAICompatClient", "RuleStubLLMClient",
]
