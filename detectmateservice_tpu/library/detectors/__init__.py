from .new_value_detector import (
    NewValueDetector,
    NewValueDetectorConfig,
    NewValueComboDetector,
    NewValueComboDetectorConfig,
)
from .random_detector import RandomDetector, RandomDetectorConfig

__all__ = [
    "NewValueDetector", "NewValueDetectorConfig",
    "NewValueComboDetector", "NewValueComboDetectorConfig",
    "RandomDetector", "RandomDetectorConfig",
]
