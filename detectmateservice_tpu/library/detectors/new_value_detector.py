"""NewValueDetector: flag values never seen during training.

Capability parity with the reference library's
``detectors.new_value_detector.NewValueDetector`` (+``NewValueComboDetector``,
referenced at src/service/features/component_loader.py:22). Semantics from
docs/getting_started.md:420-434 and the demo alert record at
docs/getting_started.md:505-510:

* during the first ``data_use_training`` messages every watched field's value
  is learned; afterwards an unseen value raises an alert,
* watched fields come from per-event ``variables`` (positional into
  ``ParserSchema.variables``) and ``header_variables`` (named from
  ``logFormatVariables``), plus a ``global`` scope applying to all events
  (reference: container/config/detector_config.yaml),
* alert entries are keyed ``"{scope} - {label}"`` with value
  ``"Unknown value: '<v>'"`` and score 1.0 per unseen value, matching the
  demo's fluentd output record.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Set, Tuple

from ...schemas import DetectorSchema, ParserSchema
from ..common.detector import BufferMode, CoreDetector, CoreDetectorConfig


class NewValueDetectorConfig(CoreDetectorConfig):
    method_type: str = "new_value_detector"
    # alert only the first time a given unknown value is observed
    alert_once: bool = False


class NewValueDetector(CoreDetector):
    config_class = NewValueDetectorConfig
    description = "NewValueDetector detects values not encountered in training as anomalies."

    def __init__(self, name: Optional[str] = None, config: Any = None,
                 buffer_mode: BufferMode = BufferMode.NO_BUF) -> None:
        super().__init__(name=name or "NewValueDetector", buffer_mode=buffer_mode,
                         config=config)
        self.config: NewValueDetectorConfig
        # (scope, instance, label) -> set of seen values
        self._seen: Dict[Tuple[str, str, str], Set[str]] = {}

    # ------------------------------------------------------------------
    def _watched(self, input_: ParserSchema):
        for scope, inst_name, inst in self.iter_scopes(input_):
            for label, var in inst.get_all().items():
                value = self.field_value(input_, var)
                yield (scope, inst_name, label), scope, label, value

    def train(self, input_: ParserSchema) -> None:
        for key, _scope, _label, value in self._watched(input_):
            if value is not None:
                self._seen.setdefault(key, set()).add(value)

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        score = 0.0
        alerts: Dict[str, str] = {}
        for key, scope, label, value in self._watched(input_):
            if value is None:
                continue
            seen = self._seen.setdefault(key, set())
            if value not in seen:
                score += 1.0
                alerts[f"{scope} - {label}"] = f"Unknown value: '{value}'"
                if self.config.alert_once:
                    seen.add(value)
        if score > 0:
            output_["score"] = score
            output_["alertsObtain"].update(alerts)
            return True
        return False

    # -- state checkpointing (TPU-build addition, closes SURVEY §5.4) ----
    def state_dict(self) -> Dict[str, Any]:
        return {
            "trained": self._trained,
            "seen": {"|".join(k): sorted(v) for k, v in self._seen.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._trained = int(state.get("trained", 0))
        self._seen = {
            tuple(k.split("|", 2)): set(v) for k, v in state.get("seen", {}).items()
        }


class NewValueComboDetectorConfig(CoreDetectorConfig):
    method_type: str = "new_value_combo_detector"
    alert_once: bool = False


class NewValueComboDetector(CoreDetector):
    """Flags unseen *combinations* of the watched fields per instance."""

    config_class = NewValueComboDetectorConfig
    description = "NewValueComboDetector detects combinations of values not encountered in training as anomalies."

    def __init__(self, name: Optional[str] = None, config: Any = None,
                 buffer_mode: BufferMode = BufferMode.NO_BUF) -> None:
        super().__init__(name=name or "NewValueComboDetector", buffer_mode=buffer_mode,
                         config=config)
        self.config: NewValueComboDetectorConfig
        self._seen: Dict[Tuple[str, str], Set[Tuple]] = {}

    def _combos(self, input_: ParserSchema):
        for scope, inst_name, inst in self.iter_scopes(input_):
            combo = tuple(
                self.field_value(input_, var) for var in inst.get_all().values()
            )
            if combo and any(v is not None for v in combo):
                yield (scope, inst_name), scope, inst_name, combo

    def train(self, input_: ParserSchema) -> None:
        for key, _scope, _inst, combo in self._combos(input_):
            self._seen.setdefault(key, set()).add(combo)

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        score = 0.0
        alerts: Dict[str, str] = {}
        for key, scope, inst_name, combo in self._combos(input_):
            seen = self._seen.setdefault(key, set())
            if combo not in seen:
                score += 1.0
                alerts[f"{scope} - {inst_name}"] = f"Unknown combination: {combo!r}"
                if self.config.alert_once:
                    seen.add(combo)
        if score > 0:
            output_["score"] = score
            output_["alertsObtain"].update(alerts)
            return True
        return False

    def state_dict(self) -> Dict[str, Any]:
        return {
            "trained": self._trained,
            "seen": {"|".join(k): sorted(map(list, v)) for k, v in self._seen.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._trained = int(state.get("trained", 0))
        self._seen = {
            tuple(k.split("|", 1)): {tuple(c) for c in v}
            for k, v in state.get("seen", {}).items()
        }
