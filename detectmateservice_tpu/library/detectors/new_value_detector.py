"""NewValueDetector: flag values never seen during training.

Capability parity with the reference library's
``detectors.new_value_detector.NewValueDetector`` (+``NewValueComboDetector``,
referenced at src/service/features/component_loader.py:22). Semantics from
docs/getting_started.md:420-434 and the demo alert record at
docs/getting_started.md:505-510:

* during the first ``data_use_training`` messages every watched field's value
  is learned; afterwards an unseen value raises an alert,
* watched fields come from per-event ``variables`` (positional into
  ``ParserSchema.variables``) and ``header_variables`` (named from
  ``logFormatVariables``), plus a ``global`` scope applying to all events
  (reference: container/config/detector_config.yaml),
* alert entries are keyed ``"{scope} - {label}"`` with value
  ``"Unknown value: '<v>'"`` and score 1.0 per unseen value, matching the
  demo's fluentd output record.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Set, Tuple

from ...schemas import DetectorSchema, ParserSchema
from ..common.detector import BufferMode, CoreDetector, CoreDetectorConfig


class NewValueDetectorConfig(CoreDetectorConfig):
    method_type: str = "new_value_detector"
    # alert only the first time a given unknown value is observed
    alert_once: bool = False


class NewValueDetector(CoreDetector):
    config_class = NewValueDetectorConfig
    description = "NewValueDetector detects values not encountered in training as anomalies."

    def __init__(self, name: Optional[str] = None, config: Any = None,
                 buffer_mode: BufferMode = BufferMode.NO_BUF) -> None:
        super().__init__(name=name or "NewValueDetector", buffer_mode=buffer_mode,
                         config=config)
        self.config: NewValueDetectorConfig
        # (scope, instance, label) -> set of seen values
        self._seen: Dict[Tuple[str, str, str], Set[str]] = {}
        self._plan_cache: Dict[Any, list] = {}  # event_id -> watch plan
        self._scan_kernel = None                # native steady-state scan
        self._scan_sig = None                   # (n plans, n seen values)

    # ------------------------------------------------------------------
    def _watched(self, input_: ParserSchema):
        for scope, inst_name, inst in self.iter_scopes(input_):
            for label, var in inst.get_all().items():
                value = self.field_value(input_, var)
                yield (scope, inst_name, label), scope, label, value

    def train(self, input_: ParserSchema) -> None:
        for key, _scope, _label, value in self._watched(input_):
            if value is not None:
                self._seen.setdefault(key, set()).add(value)

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        score = 0.0
        alerts: Dict[str, str] = {}
        for key, scope, label, value in self._watched(input_):
            if value is None:
                continue
            seen = self._seen.setdefault(key, set())
            if value not in seen:
                score += 1.0
                alerts[f"{scope} - {label}"] = f"Unknown value: '{value}'"
                if self.config.alert_once:
                    seen.add(value)
        if score > 0:
            output_["score"] = score
            output_["alertsObtain"].update(alerts)
            return True
        return False

    # -- batched hot path (engine micro-batch mode) ----------------------
    def apply_config(self) -> None:
        super().apply_config()
        self._plan_cache = {}  # reconfigure may change the watched fields
        self._drop_scan_kernel()

    def _drop_scan_kernel(self) -> None:
        """Hard-invalidate the native scan. The staleness SIGNATURE only
        tracks counts, which cannot see a reconfigure that remaps watched
        fields onto the same plan/seen counts, or a state restore with a
        coincidentally equal value count — reusing the old table there
        could wrongly PROVE rows alert-free. Every plan/state REPLACEMENT
        must come through here; only monotonic value inserts may rely on
        the signature."""
        self._scan_kernel = None
        self._scan_sig = None

    def _watch_plan(self, event_id) -> list:
        """Prebuilt (key, scope, label, kind, pos) list for one event id.

        ``iter_scopes`` + ``field_value`` walk pydantic config models per
        message; the watched-field set only changes on reconfigure, so the
        batched path resolves it once per event id (cache cleared by
        apply_config). kind: True = header (by name), False = positional."""
        plan = []
        for inst_name, inst in self.config.global_.items():
            for label, var in inst.get_all().items():
                header = not isinstance(var.pos, int)
                plan.append((("Global", inst_name, label), "Global", label,
                             header, str(var.pos) if header else var.pos))
        if event_id is not None:
            scope = f"Event {event_id}"
            for inst_name, inst in self.config.event_instances(event_id).items():
                for label, var in inst.get_all().items():
                    header = not isinstance(var.pos, int)
                    plan.append(((scope, inst_name, label), scope, label,
                                 header, str(var.pos) if header else var.pos))
        return plan

    def _ensure_scan_kernel(self):
        """(Re)build the native steady-state scan when the plan set or the
        seen-value counts changed (training, restore, reconfigure, new event
        ids, alert_once inserts). A kernel that is merely STALE can only
        over-flag rows to the exact Python path — never suppress an alert —
        so the signature check is a perf refresh, not a correctness gate."""
        try:
            from ...utils import matchkern

            if not matchkern.has_nvd_kernel():
                return None
        except Exception:
            return None
        sig = (len(self._plan_cache),
               sum(len(s) for s in self._seen.values()))
        if self._scan_kernel is not None and sig == self._scan_sig:
            return self._scan_kernel
        key_ids: Dict[Tuple[str, str, str], int] = {}
        plans = {}
        for event_id, plan in self._plan_cache.items():
            rows = []
            for key, _scope, _label, header, pos in plan:
                kid = key_ids.setdefault(key, len(key_ids))
                rows.append((kid, header, pos))
            plans[event_id] = rows
        seen_items = [(kid, value)
                      for key, kid in key_ids.items()
                      for value in self._seen.get(key, ())]
        try:
            self._scan_kernel = matchkern.NvdScanKernel(plans, seen_items)
            self._scan_sig = sig
        except Exception:
            self._scan_kernel = None
        return self._scan_kernel

    def process_batch(self, batch) -> list:
        """Batched engine contract, field-equivalent to ``process`` (pinned
        by test_process_batch_matches_process): decodes straight into pb2,
        resolves watched values off the live message, and builds an alert
        only for hits — the per-message alert skeleton the wrapper path
        builds and usually throws away was most of the per-line budget."""
        if self._buffer is not None:  # FIXED/windowed: parity path handles it
            return [self.process(d) for d in batch]
        from ...schemas import schemas_pb2 as _pb

        cfg = self.config
        seen_map = self._seen
        alert_once = cfg.alert_once
        plans = self._plan_cache
        outs: list = []
        decode_errors = 0
        build_errors = 0
        # native steady-state scan (dm_nvd_scan): after training, rows the
        # exact C table PROVES alert-free skip the Python body entirely —
        # flagged rows (possible new value, decode error, unknown event)
        # fall through to it unchanged
        verdicts = None
        if self._trained >= cfg.data_use_training and plans:
            kernel = self._ensure_scan_kernel()
            if kernel is not None:
                verdicts = kernel.scan(batch).tolist()
        for row_i, data in enumerate(batch):
            if verdicts is not None and verdicts[row_i] == 0:
                outs.append(None)
                continue
            msg = _pb.ParserSchema()
            try:
                msg.ParseFromString(data)
            except Exception:
                decode_errors += 1
                outs.append(None)
                continue
            event_id = msg.EventID if msg.HasField("EventID") else None
            plan = plans.get(event_id)
            if plan is None:
                plan = plans[event_id] = self._watch_plan(event_id)
            training = self._trained < cfg.data_use_training
            if training:
                self._trained += 1
            score = 0.0
            alerts = None
            lfv = msg.logFormatVariables
            variables = msg.variables
            n_vars = len(variables)
            for key, scope, label, header, pos in plan:
                if header:
                    value = lfv.get(pos)
                else:
                    value = variables[pos] if 0 <= pos < n_vars else None
                if value is None:
                    continue
                seen = seen_map.get(key)
                if seen is None:
                    seen = seen_map.setdefault(key, set())
                if training:
                    seen.add(value)
                elif value not in seen:
                    score += 1.0
                    if alerts is None:
                        alerts = {}
                    alerts[f"{scope} - {label}"] = f"Unknown value: '{value}'"
                    if alert_once:
                        seen.add(value)
            if training or alerts is None:
                outs.append(None)
                continue
            try:
                outs.append(self._make_alert_pb(msg, score, alerts))
            except Exception:
                # one poisoned message must cost one message, never the chunk;
                # counted separately from decode errors — this is a
                # post-decode alert-construction failure, and mislabeling it
                # "undecodable" would send the operator chasing the wire
                build_errors += 1
                logging.getLogger(__name__).exception(
                    "alert construction failed for decodable message")
                outs.append(None)
        if decode_errors:
            self.count_processing_errors(decode_errors,
                                         "undecodable ParserSchema message(s)")
        if build_errors:
            self.count_processing_errors(build_errors,
                                         "alert-construction failure(s)")
        return outs

    def _make_alert_pb(self, msg, score: float, alerts: Dict[str, str]) -> bytes:
        """Alert built straight on pb2 — field-for-field what make_output +
        detect's mutations produce on the wrapper path."""
        import time as _time

        from ...schemas import SCHEMA_VERSION, schemas_pb2 as _pb

        now = int(_time.time())
        out = _pb.DetectorSchema()
        setattr(out, "__version__", SCHEMA_VERSION)
        out.detectorID = self.name
        out.detectorType = self.config.method_type
        out.alertID = str(next(self._alert_ids))
        out.detectionTimestamp = now
        out.receivedTimestamp = now
        if msg.logID:
            out.logIDs.append(msg.logID)
        ts = now
        lfv = msg.logFormatVariables
        for key in ("Time", "time", "timestamp"):
            value = lfv.get(key) if lfv else None
            if value:
                try:
                    ts = int(float(value))
                except (ValueError, OverflowError):
                    # OverflowError: attacker-controllable '1e400'/'inf' must
                    # degrade to now, not escape and sink the whole batch
                    ts = now
                break
        else:
            if msg.HasField("receivedTimestamp") and msg.receivedTimestamp:
                ts = int(msg.receivedTimestamp)
        out.extractedTimestamps.append(ts)
        out.description = self.description
        out.score = score
        for k, v in alerts.items():
            out.alertsObtain[k] = v
        return out.SerializeToString()

    # -- state checkpointing (TPU-build addition, closes SURVEY §5.4) ----
    def state_dict(self) -> Dict[str, Any]:
        return {
            "trained": self._trained,
            "seen": {"|".join(k): sorted(v) for k, v in self._seen.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._trained = int(state.get("trained", 0))
        self._seen = {
            tuple(k.split("|", 2)): set(v) for k, v in state.get("seen", {}).items()
        }
        self._drop_scan_kernel()  # restored state REPLACES the seen sets


class NewValueComboDetectorConfig(CoreDetectorConfig):
    method_type: str = "new_value_combo_detector"
    alert_once: bool = False


class NewValueComboDetector(CoreDetector):
    """Flags unseen *combinations* of the watched fields per instance."""

    config_class = NewValueComboDetectorConfig
    description = "NewValueComboDetector detects combinations of values not encountered in training as anomalies."

    def __init__(self, name: Optional[str] = None, config: Any = None,
                 buffer_mode: BufferMode = BufferMode.NO_BUF) -> None:
        super().__init__(name=name or "NewValueComboDetector", buffer_mode=buffer_mode,
                         config=config)
        self.config: NewValueComboDetectorConfig
        self._seen: Dict[Tuple[str, str], Set[Tuple]] = {}

    def _combos(self, input_: ParserSchema):
        for scope, inst_name, inst in self.iter_scopes(input_):
            combo = tuple(
                self.field_value(input_, var) for var in inst.get_all().values()
            )
            if combo and any(v is not None for v in combo):
                yield (scope, inst_name), scope, inst_name, combo

    def train(self, input_: ParserSchema) -> None:
        for key, _scope, _inst, combo in self._combos(input_):
            self._seen.setdefault(key, set()).add(combo)

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        score = 0.0
        alerts: Dict[str, str] = {}
        for key, scope, inst_name, combo in self._combos(input_):
            seen = self._seen.setdefault(key, set())
            if combo not in seen:
                score += 1.0
                alerts[f"{scope} - {inst_name}"] = f"Unknown combination: {combo!r}"
                if self.config.alert_once:
                    seen.add(combo)
        if score > 0:
            output_["score"] = score
            output_["alertsObtain"].update(alerts)
            return True
        return False

    def state_dict(self) -> Dict[str, Any]:
        return {
            "trained": self._trained,
            "seen": {"|".join(k): sorted(map(list, v)) for k, v in self._seen.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._trained = int(state.get("trained", 0))
        self._seen = {
            tuple(k.split("|", 1)): {tuple(c) for c in v}
            for k, v in state.get("seen", {}).items()
        }
