"""RandomDetector: the reference's example detector.

Parity with the example in the reference docs (docs/interfaces.md:152-204,
examples/service_settings.yaml:1-3): flags anomalies independent of the input
by drawing a uniform sample per watched variable and alerting when it exceeds
the variable's ``threshold`` param.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ...schemas import DetectorSchema, ParserSchema
from ..common.detector import BufferMode, CoreDetector, CoreDetectorConfig


class RandomDetectorConfig(CoreDetectorConfig):
    method_type: str = "random_detector"


class RandomDetector(CoreDetector):
    """Detects anomalies randomly in logs, independent of input data."""

    config_class = RandomDetectorConfig
    description = "RandomDetector flags anomalies at random for testing."

    def __init__(self, name: str = "RandomDetector", config: Any = None,
                 buffer_mode: BufferMode = BufferMode.NO_BUF) -> None:
        super().__init__(name=name, buffer_mode=buffer_mode, config=config)
        self.config: RandomDetectorConfig
        self._rng = np.random.default_rng()

    def train(self, input_: ParserSchema) -> None:
        return

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        overall = 0.0
        alerts: Dict[str, str] = {}
        for scope, _inst_name, inst in self.iter_scopes(input_):
            for label, var in inst.get_all().items():
                threshold = float(var.params.get("threshold", 1.0))
                if self._rng.random() > threshold:
                    overall += 1.0
                    alerts[f"{scope} - {label}"] = "1.0"
        if overall > 0:
            output_["score"] = overall
            output_["alertsObtain"].update(alerts)
            return True
        return False
