"""JaxScorerDetector: TPU-batched neural anomaly scoring.

This is the component the BASELINE.json north star describes: the engine
micro-batches incoming messages and dispatches them to a jax.jit-compiled
anomaly scorer instead of the per-message callback; params live in device HBM
from ``setup_io`` on. The reference has no accelerator path at all (SURVEY.md
§0 "no training, no GPU/accelerator code") — this detector is the TPU-native
capability the rebuild adds, wrapped in the same CoreDetector contract
(train-then-detect, alert-or-None per message).

Phases:
1. **train** — the first ``data_use_training`` messages are tokenized and
   buffered (filtered from the output, like every detector's training phase),
2. **fit** — at the phase boundary the scorer trains for ``train_epochs``
   over the buffer on-device, then calibrates the alert threshold as
   ``mean + threshold_sigma * std`` of the training scores,
3. **detect** — batches are tokenized on CPU, padded to a power-of-two bucket
   (few compiled shapes → no recompile storms, SURVEY.md §7 hard part #2), and
   scored in one jit call; scores above threshold become DetectorSchema alerts.
"""
from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...schemas import DetectorSchema, ParserSchema, SchemaError
from ..common.core import LibraryError
from ..common.detector import BufferMode, CoreDetector, CoreDetectorConfig


class JaxScorerDetectorConfig(CoreDetectorConfig):
    method_type: str = "jax_scorer"
    model: str = "mlp"                # "mlp" | "gru" | "logbert"
    vocab_size: int = 32768
    seq_len: int = 32
    dim: int = 128
    depth: int = 2                    # logbert/gru layers
    heads: int = 4                    # logbert only
    score_topk: int = 0               # logbert/gru: 0=mean NLL, k>0=top-k mean
    # logbert/gru: candidate-vocab approximate scoring NLL. 0 = exact
    # full-vocab head; 0 < C < vocab_size estimates the logsumexp over a
    # fixed seeded C-subset (+ log(V/C) correction, target logit exact) —
    # ~V/C fewer head FLOPs, which is the sequence families' device
    # bottleneck (logbert 66k → 262k lines/s at C=2048 on one v5e chip).
    # Threshold units change with the approximation, so it is fit-frozen.
    score_vocab: int = 0
    # logbert attention path: "auto" (flash kernel on TPU for long
    # sequences, fused einsum otherwise) | "einsum" | "flash" | "blockwise"
    # | "ring" (sequence-parallel over the mesh_shape 'seq' axis)
    attn_impl: str = "auto"
    # candidate scoring-head path (gru/logbert with score_vocab > 0):
    # "auto"/"einsum" = S-chunked einsum + low-precision logsumexp;
    # "pallas" = fused online-logsumexp kernel (ops/scorehead.py) that
    # never materializes the [N, C] logits in HBM
    head_impl: str = "auto"
    data_use_training: int = 256
    train_epochs: int = 3
    # small training buffers still get enough optimizer steps to converge
    min_train_steps: int = 100
    train_batch_size: int = 32
    threshold_sigma: float = 4.0
    score_threshold: Optional[float] = None  # explicit override wins
    # "none": score = sequence NLL. "position": score = max over positions of
    # (NLL - mu_pos)/sigma_pos with mu/sigma calibrated on training traffic —
    # noisy fields (pids, timestamps) self-suppress, low-entropy fields flag
    # unseen values sharply (models/logbert.py positional_z_max)
    score_norm: str = "none"
    # run the train→detect boundary fit in a background thread so the engine
    # loop keeps draining its input during training (batched path only)
    async_fit: bool = True
    max_batch: int = 1024
    # how many scored batches may be in flight before results are forced
    # back to the host; hides device→host readback latency behind the next
    # batch's CPU featurization (jax dispatch is async)
    pipeline_depth: int = 8
    # -- adaptive continuous batching (the coalescer) --------------------
    # > 0 enables deadline-aware micro-batch coalescing on the fitted
    # dispatch path: rows accumulate ACROSS process_batch/process_frames
    # calls toward the best-fitting warm compile bucket instead of
    # dispatching whatever one engine recv delivered, releasing when the
    # largest warm bucket fills to batch_target_occupancy ("full"), when
    # the oldest held row's wait approaches this budget ("deadline"), or
    # at engine idle/teardown ("flush"). The oldest-row wait is bounded by
    # batch_deadline_ms + one engine drain tick (the detector exports
    # drain_poll_ms = deadline/4 as the engine's short-poll hint). 0 = off:
    # every call dispatches what it got — the legacy behavior.
    batch_deadline_ms: float = 0.0
    # early-release threshold: dispatch as soon as the held rows fill this
    # fraction of the LARGEST active warm bucket — waiting longer cannot
    # raise occupancy (the next rows start a new batch), only latency
    batch_target_occupancy: float = 0.9
    # bucket retirement (coalescing only): every interval, active warm
    # device buckets that saw fewer than bucket_retire_min_dispatches
    # dispatches in the window are retired — their rows pad up to the next
    # warm bucket — shrinking the live compile set the XLA ledger tracks
    # (fewer shapes to keep warm across refits/param swaps). A retired
    # bucket that keeps winning best-fit anyway is resurrected via an
    # EXPECTED pre-warm compile before its first dispatch use, so
    # retirement can never page as an unexpected recompile. 0 = never
    # retire. The largest warm bucket is the pad-up backstop and is never
    # retired.
    bucket_retire_interval_s: float = 0.0
    bucket_retire_min_dispatches: int = 2
    # overlap host→device upload + jit dispatch with the engine thread's
    # featurize/drain work: >0 moves the _score_dev call for each batch onto
    # N background dispatch workers. On a tunneled TPU every device_put /
    # dispatch call pays a multi-ms RPC floor that otherwise serializes with
    # featurization on the engine thread (docs/benchmarks.md: ~4.5 ms/call +
    # ~15 ms/batch tunnel floor at 2.6-9% MFU); a worker hides it behind the
    # next batch's featurize. Output order is unaffected: the in-flight slot
    # is queued at dispatch-call time, workers only fill it in. 0 = dispatch
    # inline (the right choice on local CPU, where dispatch is ~free).
    upload_workers: int = 0
    # fused native featurization: serialized ParserSchema -> token matrix in
    # one GIL-free C call (wire-format walk + tokenize + crc32 hash), rows
    # sharded over a small pthread pool. On by default whenever the native
    # library loads; rows the kernel cannot featurize with byte-exact parity
    # (invalid UTF-8, >64 header entries, ASCII-lowering unicode) fall back
    # to the Python tokenizer per row. featurize_native_rows_total /
    # featurize_fallback_rows_total count the split. Off = always Python.
    native_featurize: bool = True
    # featurization pool width: 0 = auto (min(4, cores)); the pool is
    # process-wide (one pool in the C layer), so the widest configured
    # detector wins. See docs/configuration.md for sizing guidance.
    featurize_threads: int = 0
    # batches at or below this size score on a CPU-jitted twin of the model
    # (host-resident params) instead of the accelerator: a lone message costs
    # ~1 ms on host vs 2 host↔device round-trips on a remote/tunneled TPU
    # (~70 ms each, measured) — this is what makes the <10 ms p50 target hold
    # for sparse traffic. 0 disables the host path.
    host_score_max_batch: int = 128
    device: Optional[str] = None      # e.g. "tpu:0"; default = first device
    # multi-chip scale-out (BASELINE config #5): a mesh shape like
    # {"data": 8} shards batches over all chips via parallel.ShardedScorer
    # (DP) and params per the Megatron rules when "model" > 1 (TP); XLA
    # inserts the ICI collectives. None = single device.
    mesh_shape: Optional[Dict[str, int]] = None
    # model compute dtype: "auto" = each family's default (bfloat16 — the
    # MXU-native format); "float32" is the right choice on CPU fallback
    # hosts, where XLA:CPU emulates bf16 in software (~30% slower, measured)
    dtype: str = "auto"
    seed: int = 0


def _bucket(n: int, max_batch: int) -> int:
    """Round a ragged batch size up to a power of two (≤ max_batch)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class _InflightSlot:
    """One scored (or still-scoring) batch in the in-flight queue.

    ``done`` is set once ``scores`` (device array or host numpy) or
    ``error`` is populated. Inline dispatch fills the slot before it is
    appended; the upload worker fills it after — but the slot joins
    ``_inflight`` at dispatch-call time either way, so output order is the
    dispatch order regardless of which thread ran the jax calls.

    Telemetry fields (engine/device_obs.py batch spans): ``t_enqueue`` is
    dispatch-call time (for a coalesced release, the OLDEST held row's
    arrival — so queue-wait telemetry includes the coalescer hold),
    ``t_start`` when the scoring call actually began (worker pickup),
    ``trace_id`` the flight recorder's last completed trace at dispatch —
    the link from a device batch back to PR-1 traces — and ``release`` why
    the coalescer let the batch go (full/deadline/flush; None
    uncoalesced)."""

    __slots__ = ("scores", "raws", "real", "error", "done",
                 "t_enqueue", "t_start", "bucket", "path", "trace_id",
                 "release", "tokens")

    def __init__(self, raws, real: int, bucket: int = 0,
                 path: str = "device", trace_id: Optional[str] = None,
                 release: Optional[str] = None,
                 tokens: Optional[np.ndarray] = None):
        import threading

        self.scores = None
        self.raws = raws
        self.real = real
        # the REAL (unpadded) token rows, retained only while a rollout
        # sampler is attached: the drain path offers rows PAIRED with
        # their scores (dmdrift needs the live score distribution against
        # the rows that produced it). Memory bound: pipeline_depth slots x
        # bucket x seq_len x 4 bytes, None on untapped detectors.
        self.tokens = tokens
        self.error: Optional[Exception] = None
        self.done = threading.Event()
        self.t_enqueue = time.monotonic()
        self.t_start: Optional[float] = None
        self.bucket = bucket
        self.path = path
        self.trace_id = trace_id
        self.release = release


class _ChainRaws:
    """Lazy concatenation of per-segment raw-message sequences (lists or
    native ``SpanRaws``): a coalesced release merges rows from several
    ``process_batch``/``process_frames`` calls into one dispatch without
    materializing a bytes object per row — only the ~1% anomalous rows are
    sliced out at alert-construction time (`_drain_one`)."""

    __slots__ = ("_segs", "_len")

    def __init__(self, segs):
        self._segs = segs
        self._len = sum(len(s) for s in segs)

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i):
        if isinstance(i, slice):
            # the dispatch/chunking path slices (contiguous, step 1): keep
            # the result lazy too
            start, stop, step = i.indices(self._len)
            if step != 1:
                return [self[j] for j in range(start, stop, step)]
            out, pos = [], 0
            for seg in self._segs:
                n = len(seg)
                lo, hi = max(start - pos, 0), min(stop - pos, n)
                if lo < hi:
                    out.append(seg[lo:hi])
                pos += n
                if pos >= stop:
                    break
            return _ChainRaws(out)
        if i < 0:
            i += self._len
        for seg in self._segs:
            if i < len(seg):
                return seg[i]
            i -= len(seg)
        raise IndexError("row index out of range")


class _BatchCoalescer:
    """Deadline-aware row accumulator between the engine and the device.

    Pure host-side bookkeeping, single-owner (only the engine thread
    touches it, like the rest of the dispatch path — no lock). Rows arrive
    as (tokens, raws) segments stamped with their arrival time and the
    ingress frame's tenant; ``take`` pops ``n`` rows, preserving each
    remainder segment's original arrival stamp (the deadline is per-ROW
    age, not per-call). With one tenant (the anonymous ``None`` default)
    release order is plain FIFO — byte-identical to the pre-tenant
    behavior. With several, releases are DEFICIT ROUND-ROBIN across the
    per-tenant queues (equal quanta), so a tenant holding thousands of
    rows cannot monopolize a device batch: every active tenant lands
    ~n/T rows per release while order stays FIFO within each tenant.
    The release POLICY — target occupancy, warm-bucket choice,
    retirement — lives in the detector, which owns the warm set and the
    XLA ledger."""

    __slots__ = ("deadline_s", "target_occupancy", "releases", "rows_in",
                 "max_wait_s", "wait_sum_s", "wait_n", "retired_total",
                 "_q", "_rr", "_deficit", "_total")

    def __init__(self, deadline_s: float, target_occupancy: float) -> None:
        from collections import deque

        self.deadline_s = deadline_s
        self.target_occupancy = target_occupancy
        self.releases = {"full": 0, "deadline": 0, "flush": 0}
        self.rows_in = 0
        self.max_wait_s = 0.0
        self.wait_sum_s = 0.0
        self.wait_n = 0
        self.retired_total = 0
        # tenant -> deque of (t_arrival, tokens [k, S], raws); queues are
        # pruned when emptied so the table tracks ACTIVE tenants only
        self._q: Any = {}
        self._rr: Any = deque()      # round-robin rotation over _q keys
        self._deficit: Any = {}      # tenant -> carried DRR deficit (rows)
        self._total = 0

    def __len__(self) -> int:
        return self._total

    def add(self, tokens: np.ndarray, raws, now: float,
            tenant: Optional[str] = None) -> None:
        if not len(tokens):
            return
        q = self._q.get(tenant)
        if q is None:
            from collections import deque

            q = self._q[tenant] = deque()
            self._rr.append(tenant)
        q.append((now, tokens, raws))
        self._total += len(tokens)
        self.rows_in += len(tokens)

    def oldest_age(self, now: float) -> float:
        heads = [q[0][0] for q in self._q.values() if q]
        return 0.0 if not heads else max(0.0, now - min(heads))

    def due(self, now: float) -> bool:
        """True once the oldest row's wait APPROACHES the deadline: release
        one drain tick (deadline/4, the exported engine poll hint) early,
        so the wait lands at ~the budget instead of one tick past it."""
        if not self._total:
            return False
        return self.oldest_age(now) >= self.deadline_s * 0.75

    def held_by_tenant(self) -> Dict[str, int]:
        """Held-row depth per tenant (admin/bench visibility; the anonymous
        tenant reports as ``"default"``)."""
        return {(t if t is not None else "default"):
                sum(len(seg[1]) for seg in q)
                for t, q in self._q.items()}

    def take(self, n: int):
        """Pop ``n`` rows → (tokens [n, S], raws, t_oldest).

        The round starts at the tenant holding the globally-oldest row, so
        a deadline release always carries the row that tripped it; each
        visited tenant then serves up to quantum (+ carried deficit) rows
        before the rotation moves on. An emptied queue forfeits its
        carried deficit (classic DRR) and leaves the rotation."""
        quantum = max(1, n // max(1, len(self._rr)))
        oldest_key = min(self._q, key=lambda k: self._q[k][0][0])
        while self._rr[0] != oldest_key:
            self._rr.rotate(-1)
        parts, raw_segs, got = [], [], 0
        t_oldest = None
        while got < n:
            key = self._rr[0]
            q = self._q[key]
            deficit = self._deficit.get(key, 0) + quantum
            take_rows = min(deficit, n - got)
            served = 0
            while q and served < take_rows:
                t, tok, raws = q.popleft()
                if t_oldest is None or t < t_oldest:
                    t_oldest = t
                want = take_rows - served
                if want < len(tok):
                    parts.append(tok[:want])
                    raw_segs.append(raws[:want])
                    # the remainder keeps ITS arrival stamp — splitting a
                    # call's rows across releases must not reset their
                    # deadline clock
                    q.appendleft((t, tok[want:], raws[want:]))
                    served += want
                else:
                    parts.append(tok)
                    raw_segs.append(raws)
                    served += len(tok)
            got += served
            if q:
                self._deficit[key] = deficit - served
                self._rr.rotate(-1)
            else:
                self._rr.popleft()
                self._deficit.pop(key, None)
                del self._q[key]
        self._total -= n
        tokens = parts[0] if len(parts) == 1 else np.concatenate(parts)
        raws = raw_segs[0] if len(raw_segs) == 1 else _ChainRaws(raw_segs)
        return tokens, raws, t_oldest

    def note_release(self, reason: str, wait_s: float) -> None:
        self.releases[reason] = self.releases.get(reason, 0) + 1
        self.max_wait_s = max(self.max_wait_s, wait_s)
        self.wait_sum_s += max(0.0, wait_s)
        self.wait_n += 1


class JaxScorerDetector(CoreDetector):
    config_class = JaxScorerDetectorConfig
    description = "JaxScorerDetector flags log lines the TPU scorer finds improbable."

    def __init__(self, name: Optional[str] = None, config: Any = None,
                 buffer_mode: BufferMode = BufferMode.MICRO_BATCH) -> None:
        super().__init__(name=name or "JaxScorerDetector", buffer_mode=buffer_mode,
                         config=config)
        self.config: JaxScorerDetectorConfig
        from ...models.tokenizer import HashTokenizer

        self._validate_static_config()
        self._tokenizer = HashTokenizer(
            vocab_size=self.config.vocab_size, seq_len=self.config.seq_len
        )
        self._scorer = None
        self._sharded = None  # parallel.ShardedScorer when mesh_shape is set
        self._params = None
        self._opt_state = None
        self._rng = None
        self._device = None
        self._threshold: Optional[float] = self.config.score_threshold
        # (mean, std) of the calibration scores, kept so a runtime
        # threshold_sigma reconfigure can recompute the threshold refit-free
        self._calib_stats: Optional[tuple] = None
        self._train_buffer: List[np.ndarray] = []
        self._fitted = False
        self._norm_mu: Optional[np.ndarray] = None     # [S] fp32, "position" norm
        self._norm_sigma: Optional[np.ndarray] = None  # [S] fp32
        import threading

        self._fit_thread = None                        # async boundary fit
        # guards the join-and-dispatch handoff in _finish_fit: the engine
        # loop and external callers (detect/save_checkpoint/flush_final) may
        # race it, and an unguarded handoff can double-dispatch the backlog
        self._fit_lock = threading.Lock()
        self._pending: List = []                       # (tokens_row, raw) backlog
        self._host_params = None                       # CPU twin for small batches
        self._host_score = None
        self._host_normscore = None
        self._cpu_device = None
        self._host_warm: set = set()                   # compiled host buckets
        self._host_warm_thread = None
        self._ready_supported: Optional[bool] = None   # jax.Array.is_ready seen?
        self._metrics_labels = None
        self._feat_counters = None  # (native_rows, fallback_rows) label pair
        # device observability (engine/device_obs.py): the process-wide XLA
        # compile ledger (set in _ensure_scorer) plus cached label children
        # for the per-dispatch batch telemetry — occupancy, bucket
        # selection, queue-wait vs device-time (one .labels() hash per
        # (path) / (bucket, path), never per batch)
        self._ledger = None
        self._obs_backend = "unknown"
        self._batch_obs: Dict[str, tuple] = {}
        self._bucket_children: Dict[tuple, Any] = {}
        # adaptive continuous batching (batch_deadline_ms > 0): the
        # coalescer holds rows across calls; the warm/retired sets drive
        # its bucket choice (engine-thread-owned, like _inflight). Every
        # bucket enters _device_warm through an EXPECTED compile (setup_io
        # warm-up or _warm_device_bucket), so coalesced dispatch can never
        # page as an unexpected recompile.
        self._coalescer: Optional[_BatchCoalescer] = None
        # tenant of the CURRENT ingress frame (engine note_tenant seam):
        # coalescer.add segments held rows by it so releases stay
        # weighted-fair across tenants (dmshed). Engine-thread-owned.
        self._ingress_tenant: Optional[str] = None
        self._device_warm: set = set()        # pre-warmed device buckets
        self._retired_buckets: set = set()    # retired from the active set
        self._retired_hits: Dict[int, int] = {}   # best-fit pressure window
        self._bucket_usage: Dict[int, int] = {}   # dispatches since sweep
        self._retire_last_sweep: Optional[float] = None
        self._coalesce_gauge = None
        self._release_children: Dict[str, Any] = {}
        self._occ_stats = (0, 0.0)            # (dispatches, occupancy sum)
        if self.config.featurize_threads > 0:
            kern = self._matchkern()
            if kern is not None:
                kern.set_featurize_threads(self.config.featurize_threads)
        # in-flight scored batches (_InflightSlot), oldest first
        from collections import deque

        self._inflight = deque()
        self._upload_queue = None                      # upload_workers > 0
        self._upload_threads: List = []
        # self-diagnosis (engine/health.py): the hosting Service sets
        # health_monitor; drained_total is the progress counter behind the
        # device_inflight_stuck watchdog check, the dispatch heartbeat is
        # stamped by the upload workers (age gauge only — an idle worker
        # parked on queue.get is healthy, so no age-based check applies)
        self.health_monitor = None
        self._drained_total = 0
        self._dispatch_hb = None
        # dmroll (rollout/): the Service-owned RolloutManager attaches a
        # traffic sampler here; the dispatch path offers every dispatched
        # token batch to it, and install_candidate is the
        # pre-warm-then-hot-swap seam promoted candidates cut over through
        self._rollout_sampler = None
        # dmdrift (obs/capacity.py): per-batch (rows, device-seconds)
        # callback feeding the capacity model; None costs one branch
        self._capacity_tap = None
        self._model_version = 0
        # dmwarm (PR 17): AOT-compiled executables for the warm bucket set,
        # keyed (kind, bucket). setup_io lowers+compiles them so the first
        # dispatch EXECUTES without ever entering the jit tracing/compile
        # path (jax's .lower().compile() does not seed the jit's own
        # dispatch cache — the executable must be kept and called).
        self._aot_exec: Dict[tuple, Any] = {}
        # weight-only int8 serving (dtype: int8w — models/quant.py):
        # quantized tree + its jitted score paths; live only after the
        # differential-parity gate passes (zero alert-decision flips on the
        # parity corpus), else the float path keeps serving
        self._int8w = False
        self._qparams = None
        self._qscore = None
        self._qnormscore = None
        self._parity_corpus = None
        self._int8_report: Optional[Dict[str, Any]] = None

    def _validate_static_config(self) -> None:
        """Reject bad enum-ish config at CONSTRUCTION (no jax import needed):
        ops/attention's router silently falls through to einsum for unknown
        strings, so a typo ('rign') would quietly run the wrong
        implementation while the operator believes sequence-parallel
        attention is active. Re-checked in _ensure_scorer for reconfigure."""
        cfg = self.config
        if cfg.score_norm not in ("none", "position"):
            raise LibraryError(
                f"unknown score_norm {cfg.score_norm!r}; expected 'none' or 'position'")
        if cfg.attn_impl not in ("auto", "einsum", "flash", "blockwise", "ring"):
            raise LibraryError(
                f"unknown attn_impl {cfg.attn_impl!r}; expected 'auto', "
                "'einsum', 'flash', 'blockwise', or 'ring'")
        if cfg.model not in ("mlp", "gru", "logbert"):
            raise LibraryError(f"unknown scorer model {cfg.model!r}")
        if cfg.dtype not in ("auto", "bfloat16", "float32", "float16",
                             "int8w"):
            raise LibraryError(
                f"unknown dtype {cfg.dtype!r}; expected 'auto', 'bfloat16', "
                "'float32', 'float16', or 'int8w'")
        if cfg.head_impl not in ("auto", "einsum", "pallas"):
            raise LibraryError(
                f"unknown head_impl {cfg.head_impl!r}; expected 'auto', "
                "'einsum', or 'pallas'")
        if cfg.batch_deadline_ms < 0:
            raise LibraryError(
                f"batch_deadline_ms must be >= 0 (got {cfg.batch_deadline_ms})")
        if not 0.0 < cfg.batch_target_occupancy <= 1.0:
            raise LibraryError(
                "batch_target_occupancy must be in (0, 1] "
                f"(got {cfg.batch_target_occupancy})")
        if cfg.bucket_retire_interval_s < 0:
            raise LibraryError(
                "bucket_retire_interval_s must be >= 0 "
                f"(got {cfg.bucket_retire_interval_s})")

    # -- lifecycle ------------------------------------------------------
    def setup_io(self) -> None:
        """Build the model, init params, pin them on the device, and
        AOT-compile (``lower(...).compile()``) the warm bucket set
        (reference hook role: core.py:209-211 'load models here').

        dmwarm (PR 17): the compiled executables are KEPT in ``_aot_exec``
        and dispatched directly — jax's AOT compile does not seed the jit's
        own cache, so warming-by-discarding would recompile on first
        dispatch. Warm-up wall time is split into the three phases
        ``scorer_warmup_seconds{phase=device_put|aot|cache_load}``, and the
        ``scorer_warmup_pending`` deep-health check registered here keeps
        the replica supervisor from promoting this process to ACTIVE while
        the warm set is still compiling."""
        import time as _time

        t0 = _time.monotonic()
        self._ensure_scorer()

        from ...engine.device_obs import WarmupPendingCheck

        # boot→ACTIVE gate: register BEFORE the first compile so a deep
        # health probe racing the warm-up sees UNHEALTHY (the router treats
        # "degraded" as dispatchable — only unhealthy refuses traffic)
        monitor = getattr(self._ledger, "monitor", None)
        if monitor is not None:
            try:
                monitor.remove_check(WarmupPendingCheck.name)
                monitor.add_check(WarmupPendingCheck(self._ledger, monitor))
            # dmlint: ignore[DM-R001] a bare-bones test monitor without the
            except Exception:  # noqa: BLE001 — check API must not fail boot
                pass
        # device_put phase: model build + param init + device placement all
        # happened inside _ensure_scorer
        t_warm = _time.monotonic()
        self._ledger.record_warmup_phase("device_put", t_warm - t0)
        cache_load0 = self._ledger.cache_load_seconds()

        # warm only the kernels this mode's detect path will run — every
        # extra warmed kernel costs a full XLA compile at startup (the
        # shared persistent compilation cache — compile_cache_dir —
        # amortizes restarts, not first boot)
        position = self.config.score_norm == "position" and self._norm_mu is None
        dummy_stats = np.ones(self.config.seq_len, np.float32)
        # small buckets are only ever scored on-device when the host path is
        # off; with it on, warming them would waste two accelerator compiles
        # (the host twin warms its own buckets at fit time)
        host_path = self._cpu_device is not None
        small = () if host_path else (1, 8)
        # compiles in here are the expected warm-up set; after
        # mark_warmup_complete a dispatch-path compile of a bucket in
        # _device_warm is an unexpected recompile (engine/device_obs.py —
        # the RecompileStorm signal: the cache for a shape we believed
        # compiled was invalidated). First touch of a bucket OUTSIDE the
        # warm set is planned growth and pre-warms expected instead
        # (_warm_device_bucket) on both the adaptive and legacy paths.
        with self._ledger.context(where="warmup", backend=self._obs_backend,
                                  expected=True):
            for b in (*small, self.config.train_batch_size, self.config.max_batch):
                bucket = _bucket(b, self.config.max_batch)
                tokens = np.zeros((bucket, self.config.seq_len), np.int32)
                self._device_warm.add(bucket)  # the coalescer's seed warm set
                with self._ledger.context(bucket=bucket):
                    self._aot_warm_bucket(bucket, tokens, position,
                                          dummy_stats)
            if position:
                # fit's calibration pass runs token_nlls at the train bucket
                bucket = _bucket(self.config.train_batch_size,
                                 self.config.max_batch)
                tokens = np.zeros((bucket, self.config.seq_len), np.int32)
                with self._ledger.context(bucket=bucket):
                    self._aot_warm_kind("token_nlls", bucket, tokens)
        self._ledger.mark_warmup_complete()
        # the cache_load share of the warm-up is the persistent-cache
        # deserialization time jax reported; the rest of the wall is real
        # lowering + backend compile
        cache_load = max(0.0, self._ledger.cache_load_seconds() - cache_load0)
        wall = _time.monotonic() - t_warm
        self._ledger.record_warmup_phase("cache_load", cache_load)
        self._ledger.record_warmup_phase("aot", max(0.0, wall - cache_load))

    def _aot_warm_bucket(self, bucket: int, tokens: np.ndarray,
                         position: bool, dummy_stats: np.ndarray) -> None:
        """AOT-compile the serving kernel for one bucket (score when raw
        NLL serves, normscore when position normalization will)."""
        if position:
            mu, sigma = np.zeros_like(dummy_stats), dummy_stats
            self._aot_warm_kind("normscore", bucket, tokens, mu, sigma)
        else:
            self._aot_warm_kind("score", bucket, tokens)

    def _aot_warm_kind(self, kind: str, bucket: int, tokens: np.ndarray,
                       *extra) -> None:
        """Lower+compile one (kind, bucket) executable into ``_aot_exec``
        (mesh mode delegates to the sharded scorer's own AOT map)."""
        if self._sharded is not None:
            self._sharded.aot_compile_bucket(kind, tokens, *extra)
            return
        jit_fn = {"score": self._scorer._score,
                  "normscore": self._scorer._normscore,
                  "token_nlls": self._scorer._token_nlls}[kind]
        # dmlint: ignore[DM-L001] init/warm-up phase; params are live
        args = (self._params, self._put(tokens), *extra)
        self._aot_exec[(kind, bucket)] = jit_fn.lower(*args).compile()

    def warm_set_spec(self) -> Dict[str, Any]:
        """The AOT warm bucket set as a persistable spec. The rollout
        store writes it into the checkpoint manifest, so a promote on a
        RESTARTED process pre-warms what the original boot warmed — not
        whatever buckets the current process happens to have touched."""
        return {"buckets": sorted(int(b) for b in self._device_warm),
                "seq_len": int(self.config.seq_len),
                "dtype": str(self.config.dtype),
                "score_norm": str(self.config.score_norm)}

    def _ensure_scorer(self) -> None:
        if self._scorer is not None:
            return
        from ...utils.backend import apply_platform_pin

        apply_platform_pin()
        import jax

        from ...utils.profiling import enable_compilation_cache

        enable_compilation_cache()
        # XLA compile ledger: the jax.monitoring listener installs once per
        # process; this detector's jit call sites wrap themselves in ledger
        # contexts so every compile attributes to a (bucket, trigger) pair
        from ...engine import device_obs

        self._ledger = device_obs.get_ledger()
        device_obs.install_listener()
        # GET /admin/xla reports the live warm/retired bucket sets next to
        # the compile history they explain (bucket retirement shrinks the
        # compile set the ledger tracks — make that observable)
        self._ledger.set_bucket_state_provider(self._bucket_state)
        cfg = self.config
        self._validate_static_config()
        import jax.numpy as jnp

        if cfg.head_impl == "pallas":
            # fail at boot, not per batch: without this, a pallas-less jax
            # would start "running" while every detect batch errored out
            from ...ops.scorehead import _PALLAS_OK

            if not _PALLAS_OK:
                raise LibraryError(
                    "head_impl 'pallas' needs jax.experimental.pallas, "
                    "which this jax install does not provide")
        dtype_kw = {}
        self._int8w = cfg.dtype == "int8w"
        if self._int8w:
            # weight-only int8 (models/quant.py): weights live as int8 +
            # per-channel scales and dequantize INSIDE the jitted impls;
            # activations use the platform's fast float — bf16 on
            # accelerators, f32 on CPU-sim (XLA:CPU runs bf16 GEMMs at f32
            # speed, so the int8 win there is pure weight streaming)
            dtype_kw["dtype"] = (jnp.float32
                                 if jax.default_backend() == "cpu"
                                 else jnp.bfloat16)
        elif cfg.dtype and cfg.dtype != "auto":
            dtype_kw["dtype"] = jnp.dtype(cfg.dtype).type
        if cfg.model == "logbert":
            from ...models.logbert import LogBERTConfig, LogBERTScorer

            self._scorer = LogBERTScorer(LogBERTConfig(
                vocab_size=cfg.vocab_size, dim=cfg.dim, depth=cfg.depth,
                heads=cfg.heads, seq_len=cfg.seq_len, score_topk=cfg.score_topk,
                attn_impl=cfg.attn_impl, score_vocab=cfg.score_vocab,
                head_impl=cfg.head_impl, **dtype_kw,
            ))
        elif cfg.model == "gru":
            from ...models.gru import GRUScorer, GRUScorerConfig

            self._scorer = GRUScorer(GRUScorerConfig(
                vocab_size=cfg.vocab_size, dim=cfg.dim, depth=cfg.depth,
                seq_len=cfg.seq_len, score_topk=cfg.score_topk,
                score_vocab=cfg.score_vocab, head_impl=cfg.head_impl,
                **dtype_kw,
            ))
        elif cfg.model == "mlp":
            from ...models.mlp import MLPScorer, MLPScorerConfig

            self._scorer = MLPScorer(MLPScorerConfig(
                vocab_size=cfg.vocab_size, dim=cfg.dim, seq_len=cfg.seq_len,
                head_impl=cfg.head_impl, **dtype_kw,
            ))
        else:
            raise LibraryError(f"unknown scorer model {cfg.model!r}")
        self._rng = jax.random.PRNGKey(cfg.seed)
        if cfg.mesh_shape:
            # multi-chip: batches shard over the mesh's data axis, params per
            # the model rules; ShardedScorer owns the (sharded) params
            from ...parallel.mesh import make_mesh
            from ...parallel.sharded import ShardedScorer

            mesh = make_mesh(dict(cfg.mesh_shape))
            self._sharded = ShardedScorer(self._scorer, mesh=mesh, rng=self._rng)
            self._device = f"mesh({','.join(f'{k}={v}' for k, v in mesh.shape.items())})"
            self._obs_backend = "mesh"
            device_obs.export_hbm_gauges(self._obs_labels())
            return
        devices = jax.devices()
        self._device = devices[0]
        if cfg.device:
            for d in devices:
                if str(d).lower().startswith(cfg.device.lower()):
                    self._device = d
                    break
        self._obs_backend = getattr(self._device, "platform", "unknown")
        device_obs.export_hbm_gauges(self._obs_labels())
        params, opt_state = self._scorer.init(self._rng)
        # params pinned in device memory once (HBM residency; north-star
        # item); construction-time, before any other thread can exist:
        # dmlint: ignore[DM-L001] init-only write
        self._params = jax.device_put(params, self._device)
        # dmlint: ignore[DM-L001] init-only write
        self._opt_state = jax.device_put(opt_state, self._device)
        if cfg.host_score_max_batch > 0 and self._host_scoring_possible():
            try:
                self._cpu_device = jax.devices("cpu")[0]
                # the twin shares PARAMS with the device scorer but not the
                # head implementation: head_impl=pallas on the host would
                # run the kernel in interpret mode per lone message —
                # exactly the latency path the twin exists to make fast —
                # so the twin always scores through the einsum formulation
                host_scorer = self._scorer
                if cfg.head_impl == "pallas":
                    import dataclasses as _dc

                    host_scorer = type(self._scorer)(
                        _dc.replace(host_scorer.config, head_impl="einsum"))
                # the twin must share the candidate subset too: a restored
                # checkpoint may install persisted ids on self._scorer that
                # differ from this numpy's regenerated stream
                self._host_twin_scorer = host_scorer
                self._host_score = jax.jit(host_scorer._score_impl,
                                           device=self._cpu_device)
                self._host_normscore = jax.jit(host_scorer._normscore_impl,
                                               device=self._cpu_device)
            except Exception:
                self._cpu_device = None  # no CPU backend: accelerator-only

    def _host_scoring_possible(self) -> bool:
        """Whether the model can run on the host CPU twin at all: the pallas
        flash kernel is TPU-only (jitting it for the CPU backend fails at
        trace time) and ring attention is bound to the accelerator mesh, so
        those attention configs are device-only and small batches ride the
        device path instead."""
        cfg = self.config
        if cfg.model != "logbert":
            return True
        if cfg.attn_impl in ("flash", "ring"):
            return False
        if cfg.attn_impl == "auto":
            # auto picks flash on TPU for long sequences — and the decision
            # is made while tracing for the CPU device too (it checks the
            # platform of jax.devices(), not the jit target)
            from ...ops.attention import FLASH_MIN_SEQ

            return cfg.seq_len < FLASH_MIN_SEQ
        return True

    def _sync_host_params(self) -> None:
        """Mirror the current params onto the host CPU backend (one transfer,
        after fit / checkpoint load) so small batches can score locally."""
        # callers (fit, checkpoint load, candidate install) serialize:
        # dmlint: ignore[DM-L001] ref-atomic reads
        if self._cpu_device is None or self._params is None:
            return
        import jax
        import threading

        try:
            # dmlint: ignore[DM-L001] ref-atomic mirror write
            self._host_params = jax.device_put(self._params, self._cpu_device)
        except Exception:
            self._host_params = None
            return
        # warm the lone-message bucket inline (it IS the sparse-traffic
        # latency path), then the remaining power-of-two buckets on a
        # background thread — until a bucket is warm its batches ride the
        # device path, so the engine loop never blocks on a host compile
        cap = self.config.host_score_max_batch
        try:
            with self._ledger.context(bucket=1, backend="cpu",
                                      where="host_warm", expected=True):
                jax.block_until_ready(self._score_host(
                    np.zeros((1, self.config.seq_len), np.int32)))
            self._host_warm.add(1)
        except Exception:
            self._host_params = None
            return

        def _warm_rest():
            sizes, b = [], 2
            while b <= cap:
                sizes.append(b)
                b *= 2
            if cap not in sizes:  # non-power-of-two cap is its own bucket
                sizes.append(cap)
            for size in sizes:
                try:
                    # own thread → own context stack; these compiles are the
                    # planned host-bucket warm set, never recompile storms
                    with self._ledger.context(bucket=size, backend="cpu",
                                              where="host_warm",
                                              expected=True):
                        jax.block_until_ready(self._score_host(
                            np.zeros((size, self.config.seq_len), np.int32)))
                    self._host_warm.add(size)
                except Exception:
                    return

        # non-daemon on purpose: a daemon thread killed mid-XLA-compile at
        # interpreter exit aborts the process from C++ ("FATAL: exception
        # not rethrown"); the thread is short-lived (a handful of small CPU
        # compiles), so joining at exit is cheap and clean
        self._host_warm_thread = threading.Thread(
            target=_warm_rest, daemon=False, name="HostBucketWarm")
        self._host_warm_thread.start()

    def _put(self, array: np.ndarray):
        """Upload a token batch in the narrow wire format (halving upload
        bytes halves the dominant hot-path cost — models.tokenizer
        narrow_tokens has the rule; the jitted impls cast back on device)."""
        import jax

        from ...models.tokenizer import narrow_tokens

        return jax.device_put(narrow_tokens(array, self.config.vocab_size),
                              self._device)

    def _score_dev(self, tokens: np.ndarray):
        """Dispatch scoring for [n, S] tokens; returns the device array
        without forcing readback (single device or sharded mesh). Applies
        per-position normalization once calibrated (fit). Routing order:
        the int8 quantized path when live (parity-gated), then the bucket's
        AOT executable, then the jit (which compiles — the ledger sees it,
        and after warm-up that IS the unexpected-recompile signal)."""
        if self._norm_mu is not None:
            if self._sharded is not None:
                return self._sharded.normscore_device(
                    tokens, self._norm_mu, self._norm_sigma)
            # dmlint: ignore[DM-L001] ref-atomic q-tree swap
            if self._qparams is not None:
                return self._qnormscore(self._qparams, self._put(tokens),
                                        self._norm_mu, self._norm_sigma)
            comp = self._aot_exec.get(("normscore", len(tokens)))
            if comp is not None:
                try:
                    # dmlint: ignore[DM-L001] ref-atomic param swap
                    return comp(self._params, self._put(tokens),
                                self._norm_mu, self._norm_sigma)
                # dmlint: ignore[DM-R001] aval drift falls back to the
                except Exception:  # noqa: BLE001 — traced jit below
                    pass
            return self._scorer._normscore(
                self._params, self._put(tokens), self._norm_mu, self._norm_sigma)
        if self._sharded is not None:
            return self._sharded.score_device(tokens)
        # dmlint: ignore[DM-L001] ref-atomic q-tree swap
        if self._qparams is not None:
            return self._qscore(self._qparams, self._put(tokens))
        comp = self._aot_exec.get(("score", len(tokens)))
        if comp is not None:
            try:
                # dmlint: ignore[DM-L001] ref-atomic param swap
                return comp(self._params, self._put(tokens))
            # dmlint: ignore[DM-R001] aval drift falls back to the
            except Exception:  # noqa: BLE001 — traced jit below
                pass
        # dmlint: ignore[DM-L001] ref-atomic param swap; either generation
        return self._scorer.score(self._params, self._put(tokens))

    def _token_nlls_dev(self, tokens: np.ndarray):
        if self._sharded is not None:
            return self._sharded.token_nlls_device(tokens)
        comp = self._aot_exec.get(("token_nlls", len(tokens)))
        if comp is not None:
            try:
                # dmlint: ignore[DM-L001] ref-atomic param swap
                return comp(self._params, self._put(tokens))
            # dmlint: ignore[DM-R001] aval drift falls back to the
            except Exception:  # noqa: BLE001 — traced jit below
                pass
        # dmlint: ignore[DM-L001] ref-atomic param swap; either generation
        return self._scorer._token_nlls(self._params, self._put(tokens))

    # -- weight-only int8 serving (dtype: int8w — models/quant.py) -------
    def _build_qjits(self) -> None:
        """Jit the quantized-serving twins once: the same model impls over
        ``dequantize_tree`` — XLA fuses the int8→float dequant into the
        weight read, so the GEMMs stream 4× fewer weight bytes."""
        if self._qscore is not None:
            return
        import jax

        from ...models.quant import dequantize_tree

        scorer = self._scorer
        compute_dtype = scorer.config.dtype

        def _qscore_impl(qparams, tokens):
            return scorer._score_impl(
                dequantize_tree(qparams, compute_dtype), tokens)

        def _qnormscore_impl(qparams, tokens, mu, sigma):
            return scorer._normscore_impl(
                dequantize_tree(qparams, compute_dtype), tokens, mu, sigma)

        self._qscore = jax.jit(_qscore_impl)
        self._qnormscore = jax.jit(_qnormscore_impl)

    def _parity_scores(self, tokens: np.ndarray) -> np.ndarray:
        """Served-path scores for the parity corpus, chunked on the (warm)
        train bucket so the differential run never grows the compile set."""
        cfg = self.config
        bucket = _bucket(cfg.train_batch_size, cfg.max_batch)
        out = np.empty(len(tokens), np.float32)
        for start in range(0, len(tokens), bucket):
            chunk = tokens[start:start + bucket]
            real = len(chunk)
            if real < bucket:
                chunk = np.concatenate([chunk, np.zeros(
                    (bucket - real, tokens.shape[1]), np.int32)])
            with self._ledger.context(bucket=bucket):
                out[start:start + real] = np.asarray(
                    self._score_dev(chunk))[:real]
        return out

    def _activate_int8(self, where: str = "fit") -> Dict[str, Any]:
        """Quantize the live weights (per-channel int8 scales computed at
        INSTALL time) and cut the serving path over — gated on differential
        parity: the quantized path must flip ZERO alert decisions on the
        parity corpus vs the float path, or the float path stays live."""
        import jax

        from ...models import quant

        cfg = self.config
        report: Dict[str, Any] = {"activated": False, "where": where,
                                  "rows": 0, "flips": 0, "flip_ratio": 0.0}
        threshold = (float(self._threshold)
                     if self._threshold is not None else float("inf"))
        corpus = self._parity_corpus
        with self._ledger.context(where="int8_install",
                                  backend=self._obs_backend, expected=True):
            # install paths serialize: the fit thread is joined before an
            # install and the manager thread owns every promote
            params = (self._sharded.params if self._sharded is not None
                      # dmlint: ignore[DM-L001] install-path serialized read
                      else self._params)
            qparams = quant.quantize_tree(params)
            float_scores = None
            if corpus is not None and len(corpus):
                float_scores = self._parity_scores(
                    np.asarray(corpus, np.int32))
            # tentative install, then judge the q path on the same corpus
            if self._sharded is not None:
                self._sharded.install_quantized(qparams)
            else:
                self._build_qjits()
                # dmlint: ignore[DM-L001] ref-atomic q-tree swap
                self._qparams = jax.device_put(qparams, self._device)
            ok = True
            if float_scores is not None:
                q_scores = self._parity_scores(np.asarray(corpus, np.int32))
                flips = int(np.sum((float_scores > threshold)
                                   != (q_scores > threshold)))
                report.update(
                    rows=int(len(float_scores)), flips=flips,
                    flip_ratio=float(flips) / max(1, len(float_scores)))
                ok = flips == 0
            if not ok:
                # parity broke: the quantized tree never serves
                if self._sharded is not None:
                    self._sharded.clear_quantized()
                else:
                    self._qparams = None
            else:
                # parity held (or no corpus yet — a restored process before
                # its first fit): warm every warm bucket through the q path
                # so the dispatch path stays compile-free
                for b in sorted(self._device_warm):
                    tokens = np.zeros((b, cfg.seq_len), np.int32)
                    with self._ledger.context(bucket=b):
                        jax.block_until_ready(self._score_dev(tokens))
                report["activated"] = True
                report["gated"] = float_scores is not None
                report["bytes"] = quant.quant_stats(qparams)
        self._int8_report = report
        return report

    def _calibrate_position_norm(self, data: np.ndarray, bs: int) -> np.ndarray:
        """Masked per-position mean/std of training NLLs → mu/sigma [S].

        Returns the calibration split's z-max scores (computed host-side from
        the same NLLs — no second forward pass) for threshold calibration."""
        from ...models.tokenizer import PAD_ID

        # pad every chunk to the warmed compile bucket — a ragged tail shape
        # would force a fresh XLA compile right at the phase boundary
        bucket = _bucket(max(bs, self.config.train_batch_size),
                         self.config.max_batch)
        chunks = []
        for i in range(0, len(data), bucket):
            chunk = data[i:i + bucket]
            real = len(chunk)
            if real < bucket:
                chunk = np.concatenate(
                    [chunk, np.zeros((bucket - real,) + chunk.shape[1:], chunk.dtype)])
            chunks.append(np.asarray(self._token_nlls_dev(chunk))[:real])
        nlls = np.concatenate(chunks)[: len(data)]
        mask = (data != PAD_ID).astype(np.float32)
        cnt = np.maximum(mask.sum(0), 1.0)
        mu = (nlls * mask).sum(0) / cnt
        var = ((nlls - mu) ** 2 * mask).sum(0) / cnt
        # sigma floor: a near-constant position stays sensitive to unseen
        # values without the z-score exploding on float jitter
        sigma = np.maximum(np.sqrt(var), 0.05)
        self._norm_mu = mu.astype(np.float32)
        self._norm_sigma = sigma.astype(np.float32)
        z = (nlls - mu) / sigma
        z = np.where(mask > 0, z, -np.inf)
        zmax = z.max(-1)
        # match positional_z_max: only all-PAD (-inf) rows become 0
        return np.where(np.isneginf(zmax), 0.0, zmax).astype(np.float32)

    def _train_step(self, step_rng, batch: np.ndarray) -> float:
        if self._sharded is not None:
            return self._sharded.train_step(step_rng, batch)
        # the boundary fit owns these trees until _finish_fit hands off;
        # install_candidate joins the fit before swapping:
        # dmlint: ignore[DM-L001] single-writer fit phase
        self._params, self._opt_state, loss_arr = self._scorer.train_step(
            self._params, self._opt_state, step_rng, self._put(batch)
        )
        return float(loss_arr)

    # -- featurization (CPU side) ---------------------------------------
    def featurize(self, input_: ParserSchema) -> np.ndarray:
        return self._tokenizer.encode_parsed(
            input_.get("template") or "",
            list(input_["variables"]),
            dict(input_["logFormatVariables"]),
        )

    # -- training -------------------------------------------------------
    def train(self, input_: ParserSchema) -> None:
        """Single-message training path (engine_batch_size=1 parity mode):
        buffer the tokenized row so the phase-boundary ``fit`` has data —
        ``process_batch`` buffers directly and never calls this."""
        self._train_buffer.append(self.featurize(input_))

    def fit(self) -> Dict[str, float]:
        """Train on the buffered normal traffic, calibrate the threshold."""
        self._ensure_scorer()
        # the boundary fit legitimately compiles (train step, calibration
        # buckets) after warm-up — attributed here so it never counts as an
        # unexpected recompile
        with self._ledger.context(where="fit", backend=self._obs_backend,
                                  expected=True):
            return self._fit_impl()

    def _fit_impl(self) -> Dict[str, float]:
        import jax

        cfg = self.config
        if not self._train_buffer:
            self._fitted = True
            if self._threshold is None:
                self._threshold = float("inf")
            return {"loss": float("nan"), "threshold": self._threshold}
        data = np.stack(self._train_buffer)
        self._train_buffer = []
        if self._int8w:
            # training updates the FLOAT tree; the previous generation's
            # quantized tree must not serve (or calibrate) stale scores
            # mid-fit — _activate_int8 re-quantizes at the end
            # dmlint: ignore[DM-L001] ref-atomic q-tree clear
            self._qparams = None
            if self._sharded is not None:
                self._sharded.clear_quantized()
        bs = min(cfg.train_batch_size, len(data))
        loss = float("nan")
        rng = np.random.default_rng(cfg.seed)
        # "position" norm calibrates on a held-out split: statistics computed
        # on data the model memorized underestimate the NLL of *fresh* values
        # in high-entropy fields (pids, timestamps), which then all z-spike
        if cfg.score_norm == "position" and len(data) >= 64:
            n_cal = max(16, len(data) // 5)
            calib, train_data = data[-n_cal:], data[:-n_cal]
            bs = min(bs, len(train_data))  # keep the train loop non-empty
        else:
            calib, train_data = data, data
        steps_per_epoch = max(1, len(train_data) // bs)
        epochs = max(cfg.train_epochs,
                     -(-cfg.min_train_steps // steps_per_epoch))  # ceil division
        for _ in range(epochs):
            order = rng.permutation(len(train_data))
            for start in range(0, len(train_data) - bs + 1, bs):
                batch = train_data[order[start:start + bs]]
                self._rng, step_rng = jax.random.split(self._rng)
                loss = self._train_step(step_rng, batch)
        if cfg.score_norm == "position":
            # calibrate BEFORE thresholding so the threshold is in z units;
            # the returned z-max scores reuse the same forward pass
            scores = self._calibrate_position_norm(calib, bs)
            self._calib_stats = (float(scores.mean()), float(scores.std()))
            if self._threshold is None:
                self._threshold = float(
                    scores.mean() + cfg.threshold_sigma * scores.std())
        else:
            bucket = _bucket(max(bs, cfg.train_batch_size), cfg.max_batch)
            parts = []
            for i in range(0, len(calib), bucket):
                chunk = calib[i:i + bucket]
                real = len(chunk)
                if real < bucket:  # stay on the warmed compile bucket
                    chunk = np.concatenate([chunk, np.zeros(
                        (bucket - real,) + chunk.shape[1:], chunk.dtype)])
                parts.append(np.asarray(self._score_dev(chunk))[:real])
            scores = np.concatenate(parts)[: len(calib)]
            self._calib_stats = (float(scores.mean()), float(scores.std()))
            if self._threshold is None:
                self._threshold = float(
                    scores.mean() + cfg.threshold_sigma * scores.std())
        if self._int8w:
            # the calibration split is the parity corpus: the scores the
            # threshold was calibrated on ARE the decisions int8 must keep
            self._parity_corpus = np.asarray(calib[:512], np.int32)
            self._activate_int8(where="fit")
        self._fitted = True
        self._sync_host_params()
        return {"loss": loss, "threshold": self._threshold}

    # -- scoring --------------------------------------------------------
    def score_tokens(self, tokens: np.ndarray) -> np.ndarray:
        """[N, S] → [N] fp32 scores, padded up to a compile bucket."""
        self._ensure_scorer()
        n = len(tokens)
        bucket = _bucket(n, self.config.max_batch)
        out = np.empty((n,), np.float32)
        for start in range(0, n, bucket):
            chunk = tokens[start:start + bucket]
            if len(chunk) < bucket:
                pad = np.zeros((bucket - len(chunk), tokens.shape[1]), np.int32)
                chunk = np.concatenate([chunk, pad])
            # single-message parity path: compiles attribute to "detect" and
            # stay expected — the storm detector watches the batched
            # dispatch path, not per-message scoring
            with self._ledger.context(bucket=bucket, where="detect",
                                      backend=self._obs_backend,
                                      expected=True):
                scores = np.asarray(self._score_dev(chunk))
            out[start:start + min(bucket, n - start)] = scores[: min(bucket, n - start)]
        return out

    # -- engine contract ------------------------------------------------
    def _featurize_pb_into(self, msg, out_row: np.ndarray) -> None:
        """Featurize a decoded pb2 ParserSchema into a zeroed token row.

        Hot-path twin of ``featurize`` that skips the wrapper layer (dict
        copies of map fields dominated the profile)."""
        parts = [msg.template]
        parts.extend(msg.variables)
        lfv = msg.logFormatVariables
        if lfv:
            parts.extend(f"{k}={lfv[k]}" for k in sorted(lfv))
        self._tokenizer.encode_into(" ".join(parts), out_row)

    def _matchkern(self):
        """The native featurize module, or None (knob off / not built)."""
        if not self.config.native_featurize:
            return None
        try:
            from ...utils import matchkern

            return matchkern
        except ImportError:
            return None

    def _count_featurize_rows(self, native: int, fallback: int) -> None:
        """featurize_native_rows_total / featurize_fallback_rows_total —
        which path tokenized how many rows (label children cached: this
        runs once per micro-batch on the hot path)."""
        if not native and not fallback:
            return
        if self._feat_counters is None:
            from ...engine import metrics as m

            labels = dict(component_type=self.config.method_type,
                          component_id=self.name)
            self._feat_counters = (
                m.FEATURIZE_NATIVE_ROWS().labels(**labels),
                m.FEATURIZE_FALLBACK_ROWS().labels(**labels))
        if native:
            self._feat_counters[0].inc(native)
        if fallback:
            self._feat_counters[1].inc(fallback)

    def _featurize_raw_batch(self, batch: List[bytes]):
        """Serialized ParserSchema bytes → ([N, S] int32 tokens, [N] ok bool).

        Native kernel when built and ``native_featurize`` is on (protobuf
        wire parse + tokenize + hash in C, GIL-free and row-parallel);
        Python fallback otherwise — both produce identical rows (pinned by
        tests/test_native_kernels.py)."""
        kern = self._matchkern()
        if kern is not None:
            tokens, ok = kern.featurize_batch(
                batch, self.config.seq_len, self.config.vocab_size
            )
            if ok.all():
                self._count_featurize_rows(len(batch), 0)
            else:
                # the native kernel refuses rows it cannot featurize with
                # exact parity (e.g. >64 header-map entries); retry those in
                # Python so only genuinely corrupt messages stay failed
                flagged = np.flatnonzero(~ok)
                self._featurize_python_rows(batch, tokens, ok, flagged)
                self._count_featurize_rows(len(batch) - len(flagged),
                                           len(flagged))
            return tokens, ok
        tokens = np.zeros((len(batch), self.config.seq_len), np.int32)
        ok = np.zeros(len(batch), dtype=bool)
        self._featurize_python_rows(batch, tokens, ok, range(len(batch)))
        self._count_featurize_rows(0, len(batch))
        return tokens, ok

    def _featurize_python_rows(self, batch: List[bytes], tokens: np.ndarray,
                               ok: np.ndarray, indices) -> None:
        from ...schemas import schemas_pb2 as _pb

        for i in indices:
            msg = _pb.ParserSchema()
            try:
                msg.ParseFromString(batch[i])
            except Exception:
                continue
            tokens[i] = 0  # the native pass may have partially filled the row
            self._featurize_pb_into(msg, tokens[i])
            ok[i] = True

    def note_tenant(self, tenant: Optional[str]) -> None:
        """Engine seam (dmshed): the tenant the CURRENT ingress frame was
        attributed to — rows added to the coalescer until the next call are
        segmented under it, which is what makes releases weighted-fair.
        ``None`` clears the attribution (anonymous frame). Called on the
        engine thread, per frame, before the frame's messages arrive."""
        self._ingress_tenant = tenant

    def process_batch(self, batch: List[bytes]) -> List[Optional[bytes]]:
        """Batched hot path: one featurize kernel + one jit call per
        micro-batch, preserving the per-message in-order None-filtering
        contract. Raw bytes are decoded into schema objects only for the
        (rare) anomalous messages, at alert-construction time.

        The train→detect boundary fit runs in a background thread
        (``async_fit``): the engine loop keeps draining its input — messages
        that arrive mid-fit buffer in-process (ordered) instead of piling
        into socket buffers and dropping — and the pending backlog dispatches
        on the first call after the fit completes."""
        # dmlint: ignore[DM-L001] racy pre-check; _finish_fit re-checks under lock
        fit_thread = self._fit_thread  # local read: another thread may None it
        if fit_thread is not None and not fit_thread.is_alive():
            self._finish_fit()
        tokens, ok = self._featurize_raw_batch(batch)

        # split the batch across the train/detect phase boundary
        detect_idx: List[int] = []
        for i in range(len(batch)):
            if not ok[i]:
                continue
            if self._trained < self.config.data_use_training:
                self._train_buffer.append(tokens[i])
                self._trained += 1
                if self._trained == self.config.data_use_training:
                    self._start_fit()
            elif self._fit_thread is not None:
                # fit still running: keep order by buffering the message.
                # The append happens under _fit_lock so _finish_fit's
                # backlog handoff (stack + clear) can never interleave
                # with it and drop/mis-pair a message.
                with self._fit_lock:
                    if self._fit_thread is not None:
                        self._pending.append((tokens[i], batch[i]))
                        continue
                # fit finished and its backlog was already dispatched by
                # another thread between the check and the lock: this
                # message scores normally (order is preserved — backlog
                # dispatch happened first, detect_idx dispatches below)
                if not self._fitted:
                    self.fit()
                detect_idx.append(i)
            else:
                if not self._fitted:
                    self.fit()
                detect_idx.append(i)
        ready: List[Optional[bytes]] = []  # outputs from drained older batches
        if detect_idx:
            n = len(detect_idx)
            det_tokens = tokens[detect_idx]
            det_raws = [batch[i] for i in detect_idx]
            coalescer = self._get_coalescer()
            if coalescer is not None:
                # continuous batching: hold the rows toward a warm bucket;
                # _coalesce_pump below decides what (if anything) dispatches
                coalescer.add(det_tokens, det_raws, time.monotonic(),
                              tenant=self._ingress_tenant)
            else:
                self._dispatch(det_tokens, det_raws)
            self._count_device_lines(n)
        self._coalesce_pump()
        # event-driven drain: anything whose readback already landed goes out
        # NOW (bounded latency even under a steady stream that never lulls);
        # the depth gate stays as the backstop that also bounds memory
        while self._inflight and self._head_ready():
            ready.extend(self._drain_one())
        while len(self._inflight) > self.config.pipeline_depth:
            ready.extend(self._drain_one())
        # training/filtered messages of THIS batch produced no output; the
        # drained outputs (older batches) are already in order
        return ready

    def process_frames(self, frames: List[bytes]):
        """Fused wire-frame hot path (engine contract, opt-in): takes RAW
        wire frames — packed batch frames (engine/framing.py) or single
        messages — and returns ``(ready_outputs, n_messages, n_lines)``
        where ``n_lines`` follows the engine's newline line-count rule so
        read/written metrics stay in one unit.

        Frame expansion + featurization happen in ONE native call
        (dm_featurize_frames): no per-message bytes objects, list appends,
        or Python loop iterations exist on the steady-state path — the
        per-message Python floor (~6 µs/msg measured through the zmq
        service loop, VERDICT r2 weak #3) drops to the C kernel's ~0.4 µs.
        Raw bytes are sliced lazily from the frame blob only for the ~1%
        anomalous messages at alert-construction time (SpanRaws).

        During the training phase or a running boundary fit the burst is
        materialized and delegated to ``process_batch`` (same semantics,
        per-message bookkeeping) — only the fitted steady state takes the
        vectorized path, which is exactly when throughput matters."""
        matchkern = self._matchkern()
        if matchkern is None:
            msgs: List[bytes] = []
            n_corrupt = 0
            for frame in frames:
                expanded = self._expand_frame_python(frame)
                if expanded is None:
                    n_corrupt += 1
                else:
                    msgs.extend(expanded)
            if n_corrupt:
                self.count_processing_errors(n_corrupt,
                                             "corrupt batch frame(s)")
            n_lines = sum(
                max(1, d.count(b"\n") + (0 if d.endswith(b"\n") else 1))
                for d in msgs)
            return self.process_batch(msgs), len(msgs), n_lines

        # dmlint: ignore[DM-L001] racy pre-check; _finish_fit re-checks under lock
        fit_thread = self._fit_thread  # local read: another thread may None it
        if fit_thread is not None and not fit_thread.is_alive():
            self._finish_fit()

        fb = matchkern.featurize_frames(frames, self.config.seq_len,
                                        self.config.vocab_size)
        if fb.n_corrupt_frames:
            self.count_processing_errors(fb.n_corrupt_frames,
                                         "corrupt batch frame(s)")
        n = len(fb)
        steady = (self._fitted and self._fit_thread is None
                  and self._trained >= self.config.data_use_training)
        if not steady:
            # phase boundary: per-message semantics via the classic path
            raws = [fb.raw(i) for i in range(n)]
            return self.process_batch(raws), n, fb.n_lines
        if fb.ok.all():
            self._count_featurize_rows(n, 0)
        else:
            # native kernel refused rows (e.g. >64 header-map entries):
            # retry them in Python for exact parity, like the batch path
            flagged = np.flatnonzero(~fb.ok)
            self._featurize_python_rows(
                matchkern.SpanRaws(fb.blob, fb.spans), fb.tokens, fb.ok,
                flagged)
            self._count_featurize_rows(n - len(flagged), len(flagged))
        ready: List[Optional[bytes]] = []
        if fb.ok.all():
            tokens, raws = fb.tokens, matchkern.SpanRaws(fb.blob, fb.spans)
            n_ok = n
        else:
            idx = np.flatnonzero(fb.ok)
            tokens = fb.tokens[idx]
            raws = matchkern.SpanRaws(fb.blob, fb.spans[idx])
            n_ok = len(idx)
        if n_ok:
            coalescer = self._get_coalescer()
            if coalescer is not None:
                # SpanRaws segments stay lazy inside the coalescer — no
                # per-message bytes objects until alert construction
                coalescer.add(tokens, raws, time.monotonic(),
                              tenant=self._ingress_tenant)
            else:
                self._dispatch(tokens, raws)
            self._count_device_lines(n_ok)
        self._coalesce_pump()
        while self._inflight and self._head_ready():
            ready.extend(self._drain_one())
        while len(self._inflight) > self.config.pipeline_depth:
            ready.extend(self._drain_one())
        return ready, n, fb.n_lines

    @staticmethod
    def _expand_frame_python(frame: bytes) -> Optional[List[bytes]]:
        """Pure-Python frame expansion for the no-native fallback; None
        signals a corrupt batch frame (caller counts it — silent loss of a
        whole frame must be observable, matching the native branch)."""
        from ...engine.framing import FramingError, unpack_batch

        try:
            msgs = unpack_batch(frame)
        except FramingError:
            return None
        if msgs is None:
            return [frame] if frame else []
        return [m for m in msgs if m]

    def _head_ready(self) -> bool:
        """True when the oldest in-flight batch's scores are host-readable
        without blocking (host-path numpy results always are)."""
        slot = self._inflight[0]
        if not slot.done.is_set():
            return False  # a worker still owns the dispatch call
        if slot.error is not None or isinstance(slot.scores, np.ndarray):
            return True
        is_ready = getattr(slot.scores, "is_ready", None)
        if callable(is_ready):
            self._ready_supported = True
            try:
                return bool(is_ready())
            except Exception:
                return False
        self._ready_supported = False
        return False  # cannot tell: leave it to the depth gate / flush

    def pending_count(self) -> int:
        """In-flight scored batches not yet drained, plus one while the
        coalescer holds rows (engine poll hint: while results are pending —
        or a held row's deadline is ticking — the engine shortens its recv
        timeout so a drain/release happens within one tick of readiness,
        not at the 100 ms lull)."""
        held = self._coalescer is not None and len(self._coalescer) > 0
        return len(self._inflight) + (1 if held else 0)

    @property
    def drain_poll_ms(self) -> Optional[int]:
        """Engine short-poll hint (engine.py): while the coalescer may hold
        rows, the engine must tick often enough to honor batch_deadline_ms.
        A quarter of the budget bounds the oldest-row overshoot to one tick
        (the coalescer also releases one tick EARLY — _BatchCoalescer.due),
        without hard-coding 5 ms polling onto second-scale budgets."""
        if self.config.batch_deadline_ms <= 0:
            return None
        return max(1, int(self.config.batch_deadline_ms / 4))

    def drained_total(self) -> int:
        """Monotonic count of drained in-flight batches — the progress
        counter the health watchdog pairs with ``pending_count`` to detect a
        stuck device queue (pending > 0 and this number frozen)."""
        return self._drained_total

    def drain_ready(self) -> List[Optional[bytes]]:
        """Engine short-poll tick: pop only batches whose readback already
        landed — never blocks the loop on an in-flight device batch. When the
        array type cannot report readiness at all, fall back to the blocking
        flush (otherwise nothing would ever drain on short ticks)."""
        out: List[Optional[bytes]] = []
        self._finish_fit(wait=False)
        self._coalesce_pump()  # deadline releases ride the short-poll tick
        while self._inflight and self._head_ready():
            out.extend(self._drain_one())
        if self._inflight and self._ready_supported is False:
            out.extend(self.flush())
        return out

    # -- async fit at the phase boundary --------------------------------
    def _start_fit(self) -> None:
        if not self.config.async_fit:
            self.fit()
            return
        import threading

        def _fit_safe():
            try:
                self.fit()
            except Exception:
                import logging

                logging.getLogger(__name__).exception("background fit failed")
                self._fitted = True  # fail open: detect with inf threshold
                if self._threshold is None:
                    self._threshold = float("inf")

        # publish AND start under the lock: _finish_fit's join-and-dispatch
        # handoff clears the handle under _fit_lock, so an unguarded write
        # here could lose that clear — and joining a published-but-unstarted
        # thread raises RuntimeError, so start() must happen before any
        # other thread can observe the handle (start is microseconds; the
        # fit itself runs on the new thread, not under the lock)
        with self._fit_lock:
            self._fit_thread = threading.Thread(target=_fit_safe, daemon=True,
                                                name="ScorerFit")
            self._fit_thread.start()

    def _finish_fit(self, wait: bool = False) -> None:
        """Join a finished (or, with ``wait``, still-running) fit thread and
        dispatch the ordered backlog that accumulated during the fit.

        Lock-guarded: the engine loop and external callers (detect /
        save_checkpoint / flush_final — mixed usage the class supports) may
        call this concurrently; without the lock both could observe a
        non-empty backlog and double-dispatch it."""
        # dmlint: ignore[DM-L001] racy pre-check; the read repeats under the lock
        pre = self._fit_thread  # local read: another thread may None it
        if pre is not None and pre.is_alive() and not wait:
            return  # cheap pre-check without the lock
        with self._fit_lock:
            thread = self._fit_thread
            if thread is None:
                return
            if thread.is_alive() and not wait:
                return
            # the fit thread never takes _fit_lock, so no deadlock here:
            # dmlint: ignore[DM-L002] _fit_lock IS the handoff serializer
            thread.join()
            self._fit_thread = None
            if self._pending:
                tokens = np.stack([t for t, _ in self._pending])
                raws = [r for _, r in self._pending]
                self._pending = []
                coalescer = self._get_coalescer()
                if coalescer is not None:
                    # the backlog's size is whatever the fit's duration made
                    # it — bucketing it through the coalescer (released by
                    # the caller's pump) keeps it on warm compile shapes
                    coalescer.add(tokens, raws, time.monotonic())
                else:
                    self._dispatch(tokens, raws)
                self._count_device_lines(len(raws))

    def _dispatch(self, tokens: np.ndarray, msgs: List[Any],
                  t_enqueue: Optional[float] = None,
                  release: Optional[str] = None) -> None:
        """Asynchronously score [n, S] tokens, padded to a compile bucket.

        Small batches (≤ ``host_score_max_batch``) score synchronously on the
        CPU twin instead: on a remote/tunneled accelerator a lone message
        would otherwise pay two ~70 ms transfer round-trips for ~µs of MXU
        work. The host result enters the same in-flight queue (as a ready
        numpy array) so ordering with accelerator batches is preserved.

        A coalesced release (``release`` set) backdates ``t_enqueue`` to the
        oldest held row's arrival — queue-wait telemetry then includes the
        coalescer hold — and buckets against the ACTIVE warm set
        (``_pick_device_bucket``) instead of the raw power-of-two rule, so
        every coalesced dispatch rides a pre-warmed compile shape."""
        self._ensure_scorer()
        n = len(tokens)
        # retain real token rows on the slot only while a rollout sampler
        # is attached: the drain path offers rows PAIRED with their scores
        # (dmdrift reads the live score distribution off the reservoir)
        keep_tokens = self._rollout_sampler is not None
        cap = self.config.host_score_max_batch
        # dmlint: ignore[DM-L001] ref-atomic mirror swap (see _score_host)
        if 0 < n <= cap and self._host_params is not None:
            # power-of-two host buckets keep the padding compute proportional
            # to the batch (padding everything to the cap costs ~60 ms for
            # 128 rows on a small CPU — measured, it broke the p50 target);
            # buckets compile in a background warm thread, and a batch whose
            # bucket is not warm yet rides the device path instead of
            # stalling the engine loop on a synchronous XLA compile
            bucket = _bucket(n, cap)
            if bucket in self._host_warm:
                chunk = tokens
                if n < bucket:
                    chunk = np.concatenate(
                        [tokens, np.zeros((bucket - n, tokens.shape[1]), np.int32)])
                slot = _InflightSlot(list(msgs), n, bucket=bucket,
                                     path="host",
                                     trace_id=self._current_trace_id(),
                                     release=release,
                                     tokens=tokens if keep_tokens else None)
                if t_enqueue is not None:
                    slot.t_enqueue = t_enqueue
                slot.t_start = time.monotonic()
                # only warmed host buckets reach here, so a compile in this
                # context IS an unexpected recompile (a warm-set bug)
                with self._ledger.context(bucket=bucket, backend="cpu",
                                          where="host", expected=False):
                    slot.scores = np.asarray(self._score_host(chunk))[:n]
                slot.done.set()
                # synchronous path: scores are host-readable now — record
                # the span/occupancy here, not at drain
                self._observe_batch(slot, time.monotonic() - slot.t_start)
                self._inflight.append(slot)
                return
        if release is not None:
            bucket = self._pick_device_bucket(n)
            self._bucket_usage[bucket] = self._bucket_usage.get(bucket, 0) + 1
        else:
            bucket = _bucket(n, self.config.max_batch)
            if bucket not in self._device_warm:
                # legacy (non-coalescer) path: a bucket outside the warm
                # set — traffic whose natural batch size the setup warm-up
                # never saw, e.g. a replica tier halving each scorer's
                # burst — gets the same EXPECTED on-demand pre-warm the
                # adaptive path does, instead of paging the first dispatch
                # as an unexpected recompile
                self._warm_device_bucket(bucket)
        use_workers = self.config.upload_workers > 0
        if use_workers:
            self._ensure_upload_workers()
        for start in range(0, n, bucket):
            chunk = tokens[start:start + bucket]
            real = len(chunk)
            if real < bucket:
                chunk = np.concatenate(
                    [chunk, np.zeros((bucket - real, tokens.shape[1]), np.int32)]
                )
            slot = _InflightSlot(msgs[start:start + real], real,
                                 bucket=bucket, path="device",
                                 trace_id=self._current_trace_id(),
                                 release=release,
                                 tokens=(tokens[start:start + real]
                                         if keep_tokens else None))
            if t_enqueue is not None:
                slot.t_enqueue = t_enqueue
            self._inflight.append(slot)
            if use_workers:
                self._upload_queue.put((slot, chunk))
            else:
                # inline: fill before returning; dispatch errors propagate
                # to the caller exactly as before
                slot.t_start = time.monotonic()
                with self._ledger.context(bucket=bucket,
                                          backend=self._obs_backend,
                                          where="dispatch", expected=False):
                    slot.scores = self._score_dev(chunk)
                    try:
                        slot.scores.copy_to_host_async()
                    except AttributeError:
                        pass
                slot.done.set()

    # -- adaptive continuous batching (the coalescer) --------------------
    def _get_coalescer(self) -> Optional["_BatchCoalescer"]:
        if self.config.batch_deadline_ms <= 0:
            return None
        if self._coalescer is None:
            self._coalescer = _BatchCoalescer(
                self.config.batch_deadline_ms / 1000.0,
                self.config.batch_target_occupancy)
        return self._coalescer

    def _coalesce_pump(self, force: bool = False) -> None:
        """Release due coalesced batches. Three reasons, in priority order:

        * ``full`` — the held rows fill the largest active warm bucket to
          ``batch_target_occupancy``; waiting longer cannot raise occupancy;
        * ``deadline`` — the oldest held row's wait approaches
          ``batch_deadline_ms`` (everything held goes, smaller buckets);
        * ``flush`` — the engine's idle/teardown drain (``force``), or the
          knob was turned off at runtime with rows still held.

        Single-owner like the rest of the dispatch path: only the engine
        thread pumps."""
        co = self._coalescer
        if co is None:
            return
        if not len(co):
            self._observe_coalesce_depth(0)
            return
        if self.config.batch_deadline_ms <= 0:
            force = True  # disabled at runtime with rows still held
        now = time.monotonic()
        largest = self._largest_active_bucket()
        target = max(1, math.ceil(co.target_occupancy * largest))
        while len(co) >= target:
            self._release_coalesced(min(len(co), largest), "full", now)
        if force:
            while len(co):
                self._release_coalesced(min(len(co), largest), "flush", now)
        elif co.due(now):
            while len(co):
                self._release_coalesced(min(len(co), largest), "deadline",
                                        now)
        self._maybe_retire_buckets(now)
        self._observe_coalesce_depth(len(co))

    def _release_coalesced(self, n: int, reason: str, now: float) -> None:
        tokens, raws, t_oldest = self._coalescer.take(n)
        self._coalescer.note_release(reason, now - t_oldest)
        self._count_release(reason)
        self._dispatch(tokens, raws, t_enqueue=t_oldest, release=reason)

    def _active_buckets(self) -> List[int]:
        """The warm set minus retirements, sorted ascending."""
        return sorted(self._device_warm - self._retired_buckets)

    def _largest_active_bucket(self) -> int:
        active = self._active_buckets()
        return active[-1] if active else _bucket(self.config.max_batch,
                                                 self.config.max_batch)

    def _pick_device_bucket(self, n: int) -> int:
        """Warm-set bucket choice for a coalesced release: the natural
        power-of-two bucket when active (pre-warming it — an expected
        compile — on first use), the next active bucket up when the natural
        one is retired (padding is cheaper than resurrecting a shape the
        usage window judged underused), resurrection once the retired
        bucket keeps winning best-fit anyway (persistent pressure means the
        traffic shape changed back)."""
        cap = self.config.max_batch
        natural = _bucket(n, cap)
        if natural in self._device_warm and natural not in self._retired_buckets:
            return natural
        if natural in self._retired_buckets:
            hits = self._retired_hits.get(natural, 0) + 1
            self._retired_hits[natural] = hits
            if hits <= max(1, self.config.bucket_retire_min_dispatches):
                # pad up: the largest bucket is never retired, so an active
                # bucket >= natural always exists
                for b in self._active_buckets():
                    if b >= natural:
                        return b
            self._retired_buckets.discard(natural)
        self._warm_device_bucket(natural)
        return natural

    def _warm_device_bucket(self, bucket: int) -> None:
        """Compile a device bucket BEFORE the dispatch path uses it — an
        EXPECTED compile (where="bucket_warm"): neither adaptive warm-set
        growth nor post-retirement resurrection may page as a recompile
        storm. The compile stalls this one release (like any planned warm),
        and every later dispatch on the bucket is cache-hot."""
        self._ensure_scorer()
        import jax

        tokens = np.zeros((bucket, self.config.seq_len), np.int32)
        with self._ledger.context(bucket=bucket, backend=self._obs_backend,
                                  where="bucket_warm", expected=True):
            if self._sharded is not None:
                self._sharded.warm_bucket(tokens)
            else:
                jax.block_until_ready(self._score_dev(tokens))
        self._device_warm.add(bucket)

    def _maybe_retire_buckets(self, now: float) -> None:
        interval = self.config.bucket_retire_interval_s
        if interval <= 0 or self._coalescer is None:
            return
        if self._retire_last_sweep is None:
            self._retire_last_sweep = now
            return
        if now - self._retire_last_sweep >= interval:
            self._retire_sweep(now)

    def _retire_sweep(self, now: float) -> None:
        """One retirement pass over the usage window: active buckets that
        saw fewer than ``bucket_retire_min_dispatches`` dispatches since
        the last sweep leave the active set (their future rows pad up),
        shrinking the compile set the XLA ledger tracks. The largest bucket
        is the pad-up backstop and always stays."""
        floor = max(1, self.config.bucket_retire_min_dispatches)
        active = self._active_buckets()
        largest = active[-1] if active else 0
        retired = [b for b in active
                   if b != largest and self._bucket_usage.get(b, 0) < floor]
        for b in retired:
            self._retired_buckets.add(b)
        if retired:
            self._coalescer.retired_total += len(retired)
            import logging

            logging.getLogger(__name__).info(
                "batch coalescer retired underused bucket(s) %s "
                "(< %d dispatches in %.1fs); active warm set now %s",
                retired, floor, self.config.bucket_retire_interval_s,
                self._active_buckets())
        self._bucket_usage.clear()
        self._retired_hits.clear()
        self._retire_last_sweep = now

    def _bucket_state(self) -> Dict[str, Any]:
        """The ledger's bucket-state provider (GET /admin/xla)."""
        return {
            "coalescing": self.config.batch_deadline_ms > 0,
            "warm": self._active_buckets(),
            "retired": sorted(self._retired_buckets),
        }

    def batching_stats(self) -> Dict[str, Any]:
        """Scheduler counters for the bench / smoke harnesses: releases by
        reason, achieved occupancy, held depth, release waits, and the
        warm/retired bucket sets (also on ``GET /admin/xla`` via the
        ledger's bucket state)."""
        co = self._coalescer
        occ_n, occ_sum = self._occ_stats
        return {
            "enabled": self.config.batch_deadline_ms > 0,
            "held_rows": 0 if co is None else len(co),
            "rows_coalesced": 0 if co is None else co.rows_in,
            "releases": dict(co.releases) if co is not None else {},
            "max_wait_s": 0.0 if co is None else round(co.max_wait_s, 6),
            "mean_wait_s": (round(co.wait_sum_s / co.wait_n, 6)
                            if co is not None and co.wait_n else 0.0),
            "buckets_retired_total": 0 if co is None else co.retired_total,
            "held_by_tenant": {} if co is None else co.held_by_tenant(),
            "dispatches": occ_n,
            "occupancy_sum": round(occ_sum, 4),
            "occupancy_mean": round(occ_sum / occ_n, 4) if occ_n else None,
            "warm_buckets": self._active_buckets(),
            "retired_buckets": sorted(self._retired_buckets),
        }

    def _observe_coalesce_depth(self, depth: int) -> None:
        if self._coalesce_gauge is None:
            from ...engine import metrics as m

            self._coalesce_gauge = m.COALESCE_DEPTH().labels(
                **self._obs_labels())
        self._coalesce_gauge.set(depth)

    def _count_release(self, reason: str) -> None:
        child = self._release_children.get(reason)
        if child is None:
            from ...engine import metrics as m

            child = m.DEADLINE_RELEASES().labels(reason=reason,
                                                 **self._obs_labels())
            self._release_children[reason] = child
        child.inc()

    def _ensure_upload_workers(self) -> None:
        if self._upload_threads and all(t.is_alive() for t in self._upload_threads):
            return
        import queue as _queue
        import threading

        if self._upload_queue is None:
            self._upload_queue = _queue.Queue()
        if self._dispatch_hb is None and self.health_monitor is not None:
            self._dispatch_hb = self.health_monitor.register_heartbeat(
                "scorer_dispatch")
        self._upload_threads = [t for t in self._upload_threads if t.is_alive()]
        for i in range(len(self._upload_threads), self.config.upload_workers):
            t = threading.Thread(target=self._upload_loop, daemon=True,
                                 name=f"ScorerDispatch-{i}")
            self._upload_threads.append(t)
            t.start()

    def _upload_loop(self) -> None:
        """Dispatch worker: runs the device upload + jit call for queued
        slots. jax dispatch is thread-safe; a failure is stored on the slot
        (surfaced and counted at drain) so a poisoned batch can never leave
        the engine thread waiting on a slot that nobody will complete."""
        # dmlint: hot-loop
        while True:
            item = self._upload_queue.get()
            if item is None:
                return
            if self._dispatch_hb is not None:
                self._dispatch_hb.beat()
            slot, chunk = item
            slot.t_start = time.monotonic()  # queue wait ends here
            try:
                with self._ledger.context(bucket=slot.bucket,
                                          backend=self._obs_backend,
                                          where="dispatch", expected=False):
                    scores = self._score_dev(chunk)
                    try:
                        scores.copy_to_host_async()
                    except AttributeError:
                        pass
                slot.scores = scores
            except Exception as exc:  # noqa: BLE001 — containment boundary
                slot.error = exc
            finally:
                slot.done.set()

    def _score_host(self, tokens: np.ndarray):
        """Score a small batch on the CPU backend with the mirrored params."""
        if self._norm_mu is not None:
            # dmlint: ignore[DM-L001] ref-atomic mirror swap; engine
            # thread reads whichever params generation is current
            return self._host_normscore(self._host_params, tokens,
                                        self._norm_mu, self._norm_sigma)
        # dmlint: ignore[DM-L001] ref-atomic mirror swap (see above)
        return self._host_score(self._host_params, tokens)

    def _drain_one(self) -> List[Optional[bytes]]:
        slot = self._inflight.popleft()
        slot.done.wait()
        self._drained_total += 1
        if slot.error is not None:
            # worker-path dispatch failure: same containment rule as the
            # engine's per-message processing — count EVERY lost message
            # (error-rate dashboards must see the real magnitude), emit
            # nothing, live on
            self.count_processing_errors(
                slot.real, f"batch dispatch failed: {slot.error}")
            return []
        raws, real = slot.raws, slot.real
        scores = np.asarray(slot.scores)[:real]
        if self._rollout_sampler is not None and slot.tokens is not None:
            # drain-time tap (dmdrift): rows enter the reservoir PAIRED
            # with the scores this batch produced — the drift monitor's
            # live distribution is exactly what the dispatch path scored
            self._rollout_sampler.offer_rows(slot.tokens[:real], scores)
        if slot.path != "host":
            # np.asarray above forced the readback: scoring-call start →
            # now is the batch's device compute + readback time (the host
            # path recorded its synchronous span at dispatch)
            start = slot.t_start if slot.t_start is not None else slot.t_enqueue
            self._observe_batch(slot, time.monotonic() - start)
        threshold = self._threshold if self._threshold is not None else float("inf")
        out: List[Optional[bytes]] = []
        hits = np.flatnonzero(scores > threshold)
        if hits.size == 0:
            return out
        from ...schemas import schemas_pb2 as _pb

        for i in hits:  # touch only the anomalous rows (~1% of the batch)
            msg = _pb.ParserSchema()
            msg.ParseFromString(raws[i])
            out.append(self._make_alert_pb(msg, float(scores[i])))
        return out

    def flush(self) -> List[Optional[bytes]]:
        """Idle-time drain (engine calls on every input lull): NON-blocking —
        a 100 ms lull does not mean the input stays idle, so waiting out a
        running boundary fit here would stall the engine loop and drop
        messages at the socket HWM (the failure async_fit exists to prevent).
        A finished fit's backlog is dispatched; a running fit is left alone.
        Coalesced rows release unconditionally (reason "flush"): an idle
        lull or teardown must never strand held rows."""
        self._finish_fit(wait=False)
        self._coalesce_pump(force=True)
        out: List[Optional[bytes]] = []
        while self._inflight:
            out.extend(self._drain_one())
        return out

    def flush_final(self) -> List[Optional[bytes]]:
        """Stop-time drain: waits for a running boundary fit so its pending
        backlog is scored and emitted before sockets close (and for the host
        bucket warmer, so post-restore usage sees a deterministic state).
        Upload workers are stopped after the drain — a detector that keeps
        processing afterwards (tests do) just respawns them on next
        dispatch; a torn-down one leaks no thread pinning it alive."""
        self._finish_fit(wait=True)
        warm = self._host_warm_thread
        if warm is not None and warm.is_alive():
            warm.join()
        out = self.flush()
        self._stop_upload_workers()
        return out

    def _stop_upload_workers(self) -> None:
        if self._upload_queue is None:
            return
        for t in self._upload_threads:
            if t.is_alive():
                self._upload_queue.put(None)   # one sentinel per live worker
        for t in self._upload_threads:
            t.join(timeout=5)
        self._upload_threads = []

    def _make_alert_pb(self, msg, score: float) -> bytes:
        """Alert construction straight on the generated pb2 classes — at a
        1% anomaly rate over 250k+ lines/s this runs thousands of times per
        second, and the dict-style wrapper layers (field-descriptor lookups,
        map copies) measurably cap drain throughput. Field semantics match
        CoreDetector.make_output exactly — pinned field-by-field by
        test_batch_alert_full_field_parity_with_make_output."""
        from ...schemas import SCHEMA_VERSION, schemas_pb2 as _pb

        now = int(time.time())
        out = _pb.DetectorSchema()
        setattr(out, "__version__", SCHEMA_VERSION)
        out.detectorID = self.name
        out.detectorType = self.config.method_type
        out.alertID = str(next(self._alert_ids))
        out.detectionTimestamp = now
        out.receivedTimestamp = now
        if msg.logID:
            out.logIDs.append(msg.logID)
        ts = now
        lfv = msg.logFormatVariables
        for key in ("Time", "time", "timestamp"):
            value = lfv.get(key) if lfv else None
            if value:
                try:
                    ts = int(float(value))
                except ValueError:
                    pass
                break
        else:
            if msg.receivedTimestamp:
                ts = int(msg.receivedTimestamp)
        out.extractedTimestamps.append(ts)
        out.description = self.description
        out.score = score
        out.alertsObtain[f"{self.name} - score"] = (
            f"anomaly score {score:.4f} > {self._threshold:.4f}")
        return out.SerializeToString()

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        """Single-message path (parity mode / tests): batch of one."""
        self._finish_fit(wait=True)  # mixed usage: boundary fit may be running
        if not self._fitted:
            self.fit()
        score = float(self.score_tokens(self.featurize(input_)[None])[0])
        if score > self._threshold:
            output_["score"] = score
            output_["alertsObtain"].update(
                {f"{self.name} - score": f"anomaly score {score:.4f} > {self._threshold:.4f}"}
            )
            self._count_device_lines(1)
            return True
        self._count_device_lines(1)
        return False

    def _count_device_lines(self, n: int) -> None:
        from ...engine import metrics as m

        if self._metrics_labels is None:
            self._metrics_labels = dict(
                component_type=self.config.method_type,
                component_id=self.name,
                device=str(self._device),
            )
        m.DEVICE_LINES().labels(**self._metrics_labels).inc(n)
        m.DEVICE_BATCHES().labels(**self._metrics_labels).inc()

    # -- device observability (engine/device_obs.py) ---------------------
    def _obs_labels(self) -> Dict[str, str]:
        return dict(component_type=self.config.method_type,
                    component_id=self.name)

    def _current_trace_id(self) -> Optional[str]:
        """Flight recorder's last completed trace id (the PR-1 link a
        device-batch span carries), or None off a traced pipeline."""
        monitor = self.health_monitor
        recorder = (getattr(monitor, "trace_recorder", None)
                    if monitor is not None else None)
        return (getattr(recorder, "last_trace_id", None)
                if recorder is not None else None)

    def _observe_batch(self, slot: "_InflightSlot",
                       device_s: float) -> None:
        """Per-dispatch batch telemetry, recorded when a batch's scores
        become host-readable: occupancy (real/bucket — 1 minus padding
        waste), bucket selection, and the queue-wait vs device-time split,
        attributed to the host or device path; plus a span in the compile
        ledger carrying the dispatch-time trace id."""
        from ...engine import metrics as m

        bucket, path = slot.bucket, slot.path
        if bucket <= 0:
            return
        t_start = slot.t_start if slot.t_start is not None else slot.t_enqueue
        queue_wait_s = max(0.0, t_start - slot.t_enqueue)
        children = self._batch_obs.get(path)
        if children is None:
            labels = dict(self._obs_labels(), path=path)
            children = (m.BATCH_OCCUPANCY().labels(**labels),
                        m.BATCH_QUEUE_WAIT().labels(**labels),
                        m.BATCH_DEVICE_SECONDS().labels(**labels))
            self._batch_obs[path] = children
        occ_h, wait_h, dev_h = children
        occ_h.observe(slot.real / bucket)
        # dmtel: link the queue-wait sample to the trace that was in flight
        # at dispatch time so a scrape with ?format=openmetrics carries an
        # exemplar pointing straight at an assembled trace in the collector.
        if slot.trace_id:
            wait_h.observe(queue_wait_s, {"trace_id": slot.trace_id})
        else:
            wait_h.observe(queue_wait_s)
        dev_h.observe(max(0.0, device_s))
        # running (dispatches, occupancy-sum) pair: the bench/smoke
        # harnesses read deltas of it per load phase (batching_stats)
        occ_n, occ_sum = self._occ_stats
        self._occ_stats = (occ_n + 1, occ_sum + slot.real / bucket)
        bucket_child = self._bucket_children.get((bucket, path))
        if bucket_child is None:
            bucket_child = m.BUCKET_SELECTED().labels(
                bucket=str(bucket), path=path, **self._obs_labels())
            self._bucket_children[(bucket, path)] = bucket_child
        bucket_child.inc()
        if self._ledger is not None:
            self._ledger.record_span(bucket, slot.real, path, queue_wait_s,
                                     max(0.0, device_s), slot.trace_id,
                                     release=slot.release)
        tap = self._capacity_tap
        if tap is not None:
            # dmdrift capacity arithmetic: real rows + the device-time this
            # batch cost, from the one site every scored batch reports to
            tap(slot.real, max(0.0, device_s))

    # -- model rollout (rollout/manager.py seams) ------------------------
    def set_rollout_sampler(self, sampler) -> None:
        """Attach the dispatch-path traffic tap (rollout/sampler.py). One
        ``offer_rows`` call per DRAINED micro-batch — rows enter paired
        with the scores they produced (dmdrift) — and the sampler bounds
        its own memory and does its own thinning."""
        self._rollout_sampler = sampler

    def set_capacity_tap(self, tap) -> None:
        """Attach the dmdrift capacity tap (obs/capacity.py): called as
        ``tap(n_rows, device_seconds)`` per observed batch, any dispatch
        path. None detaches."""
        self._capacity_tap = tap

    def model_version(self) -> int:
        """The installed checkpoint version (0 = the boot-time fit)."""
        # dmlint: ignore[DM-L001] int read; swap publishes ref-atomically
        return self._model_version

    def live_threshold(self) -> float:
        return float(self._threshold) if self._threshold is not None \
            else float("inf")

    def rollout_ready(self) -> bool:
        """Whether the continuous fine-tune/shadow cycle can run: a fitted,
        single-device scorer with live params. Mesh (sharded) mode serves
        hot-swaps of externally-built checkpoints (install_candidate /
        load_params_checkpoint) but not in-process fine-tuning — the train
        path donates the sharded trees in place."""
        # dmlint: ignore[DM-L001] racy pre-check; install paths re-sync
        return (self._fitted and self._fit_thread is None
                and self._sharded is None
                # dmlint: ignore[DM-L001] presence probe; cycle re-reads
                and self._params is not None)

    def rollout_fine_tune(self, rows: np.ndarray, epochs: int = 1,
                          seed: int = 0):
        """Fine-tune a CANDIDATE param tree off the live params on sampled
        rows; the live tree is never touched (train_step is functional).
        Every jit call rides the train-bucket shape the boundary fit
        compiled, and anything new attributes to an expected
        ``rollout_fit`` ledger context — the dispatch path keeps its
        zero-unexpected-recompile contract while training runs on the
        manager thread."""
        self._ensure_scorer()
        # dmlint: ignore[DM-L001] presence probe
        if self._sharded is None and self._params is None:
            raise LibraryError("scorer has no live params to fine-tune from")
        if self._sharded is not None:
            raise LibraryError(
                "continuous fine-tuning is not supported in mesh (sharded) "
                "mode; deploy externally-trained checkpoints instead")
        import jax

        cfg = self.config
        rows = np.asarray(rows, np.int32)
        if not len(rows):
            raise LibraryError("no sampled rows to fine-tune on")
        bs = min(cfg.train_batch_size, len(rows))
        # a concurrent swap just means the candidate forks from the
        # pre-swap generation; the shadow gate judges it against whatever
        # is live at promote time:
        # dmlint: ignore[DM-L001] snapshot read
        params, opt_state = self._params, self._opt_state
        rng = jax.random.PRNGKey(cfg.seed + 1 + seed)
        order_rng = np.random.default_rng(cfg.seed + seed)
        loss, steps = float("nan"), 0
        with self._ledger.context(where="rollout_fit",
                                  backend=self._obs_backend, expected=True):
            for _ in range(max(1, epochs)):
                order = order_rng.permutation(len(rows))
                for start in range(0, len(rows) - bs + 1, bs):
                    batch = rows[order[start:start + bs]]
                    rng, step_rng = jax.random.split(rng)
                    params, opt_state, loss_arr = self._scorer.train_step(
                        params, opt_state, step_rng, self._put(batch))
                    loss = float(loss_arr)
                    steps += 1
        return params, opt_state, {"steps": steps, "loss": loss,
                                   "batch_size": bs}

    def _score_with_params(self, params, tokens: np.ndarray):
        """Score a padded chunk with an explicit param tree (None = live);
        applies the live position-norm calibration either way so live and
        candidate scores stay in one unit."""
        if params is None:
            return self._score_dev(tokens)
        if self._norm_mu is not None:
            return self._scorer._normscore(params, self._put(tokens),
                                           self._norm_mu, self._norm_sigma)
        return self._scorer.score(params, self._put(tokens))

    def rollout_scores(self, params, tokens: np.ndarray) -> np.ndarray:
        """Shadow-scoring path: [n, S] tokens → [n] fp32 scores under the
        given params (None = live). Chunks ride the train-bucket compile
        shape (guaranteed warm since the boundary fit) under an expected
        ``shadow`` ledger context."""
        self._ensure_scorer()
        if self._sharded is not None and params is not None:
            raise LibraryError(
                "shadow scoring with explicit params is not supported in "
                "mesh (sharded) mode")
        tokens = np.asarray(tokens, np.int32)
        n = len(tokens)
        if n == 0:
            return np.zeros(0, np.float32)
        bucket = _bucket(self.config.train_batch_size, self.config.max_batch)
        out = np.empty(n, np.float32)
        with self._ledger.context(bucket=bucket, where="shadow",
                                  backend=self._obs_backend, expected=True):
            for start in range(0, n, bucket):
                chunk = tokens[start:start + bucket]
                real = len(chunk)
                if real < bucket:
                    chunk = np.concatenate([chunk, np.zeros(
                        (bucket - real, tokens.shape[1]), np.int32)])
                scores = np.asarray(self._score_with_params(params, chunk))
                out[start:start + real] = scores[:real]
        return out

    def _resolve_warm_set(self, warm_set) -> List[int]:
        """Buckets to pre-warm at install: the live warm set UNIONED with a
        persisted warm-set spec (rollout manifest — see warm_set_spec), so
        a promote on a restarted process warms what the recording boot
        warmed. A spec for a different sequence length is stale config and
        is ignored."""
        cfg = self.config
        warmed = set(self._device_warm)
        if warm_set:
            try:
                if int(warm_set.get("seq_len", cfg.seq_len)) == cfg.seq_len:
                    warmed.update(
                        b for b in (int(x) for x in warm_set.get("buckets", ()))
                        if 0 < b <= cfg.max_batch)
            except (TypeError, ValueError, AttributeError):
                pass  # malformed spec: warm the live set only
        return sorted(warmed)

    def install_candidate(self, params, opt_state, version: int = 0,
                          warm_set=None) -> Dict[str, Any]:
        """Zero-downtime hot-swap: pre-warm the candidate against EVERY
        warm device bucket (plus the persisted ``warm_set`` spec from the
        rollout manifest) under an expected ``model_swap`` ledger context
        *before* cutover, then swap the dispatch path's param refs under
        the ``_fit_lock`` handoff. The coalescer keeps draining while the
        warm runs on the caller's (manager) thread; because the candidate's
        avals match the live tree every warm call is an XLA cache hit, and
        any surprise compile is attributed expected here rather than
        paging as a recompile storm. The host CPU twin's mirror is computed
        pre-swap too, so small batches never score a stale model. Under
        ``dtype: int8w`` the candidate is re-quantized after the swap and
        the parity gate re-judged — a candidate that flips decisions under
        quantization serves float."""
        self._ensure_scorer()
        import jax

        # land a running boundary fit first: its completion would overwrite
        # the freshly-installed params with the pre-swap training result
        self._finish_fit(wait=True)
        cfg = self.config
        warmed = self._resolve_warm_set(warm_set)
        with self._ledger.context(where="model_swap",
                                  backend=self._obs_backend, expected=True):
            if self._sharded is not None:
                # serve float while the swap + requant are in flight
                self._sharded.clear_quantized()
                self._sharded.install_params(params, opt_state)
                for b in warmed:
                    self._device_warm.add(b)
                    self._sharded.warm_bucket(
                        np.zeros((b, cfg.seq_len), np.int32))
                with self._fit_lock:
                    self._model_version = int(version)
                result = {"swapped": True, "version": int(version),
                          "prewarmed_buckets": warmed, "backend": "mesh"}
                if self._int8w:
                    result["int8"] = self._activate_int8(where="install")
                return result
            dev_params = jax.device_put(params, self._device)
            dev_opt = jax.device_put(opt_state, self._device)
            for b in warmed:
                tokens = np.zeros((b, cfg.seq_len), np.int32)
                self._device_warm.add(b)
                with self._ledger.context(bucket=b):
                    jax.block_until_ready(
                        self._score_with_params(dev_params, tokens))
            host_params = None
            # the mirror itself is recomputed from the candidate and
            # swapped under the lock:
            # dmlint: ignore[DM-L001] presence probe
            if self._host_params is not None:
                try:
                    host_params = jax.device_put(params, self._cpu_device)
                except Exception:
                    host_params = None
            with self._fit_lock:
                self._params = dev_params
                self._opt_state = dev_opt
                # the old generation's quantized tree must not outlive its
                # float source; requantized below from the candidate
                self._qparams = None
                if host_params is not None:
                    self._host_params = host_params
                self._model_version = int(version)
        result = {"swapped": True, "version": int(version),
                  "prewarmed_buckets": warmed,
                  "backend": self._obs_backend}
        if self._int8w:
            result["int8"] = self._activate_int8(where="install")
        return result

    def save_params_checkpoint(self, directory: str, params,
                               opt_state) -> None:
        """Persist an EXPLICIT param tree (a rollout candidate) with this
        detector's state metadata — the versioned-store twin of
        ``save_checkpoint``, which persists the live tree."""
        from ...utils.checkpoint import MODEL_TREE_VERSIONS, save_scorer_state

        save_scorer_state(directory, params, opt_state, self.state_dict(),
                          tree_version=MODEL_TREE_VERSIONS.get(
                              self.config.model, 1))

    def load_params_checkpoint(self, directory: str):
        """Load a stored version's trees against the live templates WITHOUT
        installing them (promote-by-version / rollback load through here,
        then ``install_candidate``)."""
        from ...utils.checkpoint import (COMPATIBLE_TREE_VERSIONS,
                                         load_scorer_state)

        self._ensure_scorer()
        accepted = COMPATIBLE_TREE_VERSIONS.get(self.config.model, {1})
        if self._sharded is not None:
            return load_scorer_state(
                directory, self._sharded.params, self._sharded.opt_state,
                accepted_tree_versions=accepted)
        # any live generation's tree structure restores identically:
        # dmlint: ignore[DM-L001] template read
        return load_scorer_state(directory, self._params, self._opt_state,
                                 accepted_tree_versions=accepted)

    # -- runtime reconfigure (POST /admin/reconfigure end-to-end) --------
    def validate_reconfigure(self, new_config) -> None:
        """Veto changes that would require rebuilding the compiled model or
        re-calibrating in different units — those need a restart/refit, and
        silently accepting them would mis-calibrate detection."""
        super().validate_reconfigure(new_config)
        frozen = ("model", "vocab_size", "seq_len", "dim", "depth", "heads",
                  "score_topk", "score_vocab", "score_norm", "mesh_shape",
                  "attn_impl", "dtype", "head_impl")
        for field in frozen:
            if getattr(new_config, field) != getattr(self.config, field):
                raise LibraryError(
                    f"{field!r} cannot change at runtime (old="
                    f"{getattr(self.config, field)!r} new="
                    f"{getattr(new_config, field)!r}); restart the service")

    def apply_config(self) -> None:
        """React to a live config swap: threshold semantics re-derive
        immediately (explicit score_threshold wins; a new threshold_sigma
        recomputes from the stored calibration stats; pre-fit, a withdrawn
        override clears so the upcoming fit calibrates instead of keeping
        the stale value forever)."""
        super().apply_config()
        if self.config.featurize_threads > 0:
            kern = self._matchkern()
            if kern is not None:
                kern.set_featurize_threads(self.config.featurize_threads)
        # batching knobs apply live: an existing coalescer re-reads the
        # budget/target (held rows keep their original arrival stamps); a
        # deadline turned off drains on the next pump (reason "flush")
        if self._coalescer is not None and self.config.batch_deadline_ms > 0:
            self._coalescer.deadline_s = self.config.batch_deadline_ms / 1000.0
            self._coalescer.target_occupancy = self.config.batch_target_occupancy
        if self.config.score_threshold is not None:
            self._threshold = float(self.config.score_threshold)
        elif self._calib_stats is not None:
            mean, std = self._calib_stats
            self._threshold = float(mean + self.config.threshold_sigma * std)
        elif not self._fitted:
            self._threshold = None  # the upcoming fit calibrates fresh
        else:
            # fitted but no stored calibration (e.g. a pre-calib-stats
            # checkpoint): nothing to recompute from — keep the live value
            # and say so rather than silently honoring half the request
            import logging

            logging.getLogger(__name__).warning(
                "reconfigure: no stored calibration stats; threshold stays %r",
                self._threshold)

    # -- state checkpointing (orbax; closes SURVEY §5.4) -----------------
    def state_dict(self) -> Dict[str, Any]:
        state = {
            "trained": self._trained,
            "threshold": self._threshold,
            "fitted": self._fitted,
            "calib_stats": (None if self._calib_stats is None
                            else list(self._calib_stats)),
            "norm_mu": None if self._norm_mu is None else self._norm_mu.tolist(),
            "norm_sigma": (None if self._norm_sigma is None
                           else self._norm_sigma.tolist()),
        }
        # candidate-vocab subset: numpy's Generator bit-stream is not
        # guaranteed stable across numpy versions, so "same seed" does not
        # guarantee the same subset after a restore under a different numpy —
        # which would silently shift the score_vocab approximation out from
        # under the fit-frozen threshold. Persist the ids and reuse them.
        cand = getattr(self._scorer, "_cand_cache", None)
        if cand is not None:
            state["cand_key"] = list(cand[0])
            state["cand_ids"] = cand[1].tolist()
        return state

    def save_checkpoint(self, directory: str) -> None:
        from ...utils.checkpoint import MODEL_TREE_VERSIONS, save_scorer_state

        # a boundary fit mutates params/threshold concurrently — land it
        # first so the checkpoint is a consistent post-fit snapshot
        self._finish_fit(wait=True)

        version = MODEL_TREE_VERSIONS.get(self.config.model, 1)
        if self._sharded is not None:
            save_scorer_state(directory, self._sharded.params,
                              self._sharded.opt_state, self.state_dict(),
                              tree_version=version)
        else:
            # _finish_fit(wait=True) above ended the only racing writer:
            # dmlint: ignore[DM-L001] post-join read
            save_scorer_state(directory, self._params, self._opt_state,
                              self.state_dict(), tree_version=version)

    def load_checkpoint(self, directory: str) -> None:
        from ...utils.checkpoint import (COMPATIBLE_TREE_VERSIONS,
                                         load_scorer_state)

        self._ensure_scorer()
        accepted = COMPATIBLE_TREE_VERSIONS.get(self.config.model, {1})
        if self._sharded is not None:
            # restore against the sharded targets so each leaf comes back
            # with its mesh placement intact
            params, opt_state, meta = load_scorer_state(
                directory, self._sharded.params, self._sharded.opt_state,
                accepted_tree_versions=accepted,
            )
            self._sharded.params, self._sharded.opt_state = params, opt_state
        else:
            params, opt_state, meta = load_scorer_state(
                # dmlint: ignore[DM-L001] template read (tree structure only)
                directory, self._params, self._opt_state,
                accepted_tree_versions=accepted,
            )
            self._params, self._opt_state = params, opt_state
        self._trained = int(meta.get("trained", 0))
        self._fitted = bool(meta.get("fitted", False))
        cand_key, cand_ids = meta.get("cand_key"), meta.get("cand_ids")
        if cand_key is not None and cand_ids is not None:
            # reuse the checkpointed subset verbatim — regenerating from the
            # seed under a different numpy could shift the approximation and
            # decalibrate the restored threshold
            cache = (tuple(cand_key), np.asarray(cand_ids, np.int32))
            self._scorer._cand_cache = cache
            twin = getattr(self, "_host_twin_scorer", None)
            if twin is not None and twin is not self._scorer:
                twin._cand_cache = cache
        stats = meta.get("calib_stats")
        self._calib_stats = None if stats is None else (float(stats[0]),
                                                        float(stats[1]))
        mu, sigma = meta.get("norm_mu"), meta.get("norm_sigma")
        # norm-mode mismatch: the checkpointed threshold is in the units the
        # checkpoint was calibrated under (z-scores with norm stats, raw NLL
        # without); applying it across a mode change silently mis-calibrates
        # detection, so it is discarded (fail open) unless config overrides
        norm_mismatch = (mu is not None) != (self.config.score_norm == "position")
        if self.config.score_norm == "position":
            self._norm_mu = None if mu is None else np.asarray(mu, np.float32)
            self._norm_sigma = (None if sigma is None
                                else np.asarray(sigma, np.float32))
        else:
            # a config that turned normalization off outranks checkpointed
            # calibration — otherwise scores and threshold disagree on units
            self._norm_mu = self._norm_sigma = None
        if self.config.score_threshold is not None:
            # explicit config override outranks the checkpointed calibration
            self._threshold = self.config.score_threshold
        else:
            thr = meta.get("threshold")
            if thr is not None and norm_mismatch:
                import logging

                logging.getLogger(__name__).warning(
                    "checkpoint norm calibration (%s) does not match config "
                    "score_norm=%r: discarding the checkpointed threshold "
                    "(alerts disabled until reconfigured or refitted)",
                    "present" if mu is not None else "absent",
                    self.config.score_norm)
                self._threshold = float("inf")
            elif thr is not None:
                self._threshold = float(thr)
            elif self._fitted:
                self._threshold = float("inf")
            else:
                # unfitted checkpoint: drop any stale in-memory calibration so
                # the next fit() recalibrates for the restored run
                self._threshold = None
        if self._int8w and self._fitted:
            # re-quantize from the restored float tree (the checkpoint
            # stores float weights — int8 is a serving-time representation).
            # Without a parity corpus in this process the activation is
            # ungated and the report records gated=False.
            self._activate_int8(where="restore")
        self._sync_host_params()
