"""LLMEscalationDetector: second-opinion triage of anomalies via an LLM.

Capability-ceiling parity: the reference library's dependency set includes
``openai`` + ``tiktoken`` (SURVEY §2.9, reference uv.lock:277-294 — the
library does LLM-assisted detection). This is that capability rebuilt for the
TPU-first pipeline, with the economics the reference's design implies:

* the CHEAP detector (any in-tree detector — typically the TPU-batched
  ``JaxScorerDetector``) screens every message at full line rate,
* only its alerts — rare by construction — escalate to the EXPENSIVE
  assessor, an LLM asked to judge the flagged log line in context,
* the assessor sits behind a pluggable ``LLMClient`` interface; the default
  offline implementation is deterministic (no network exists in this
  environment, and CI must not depend on one), and an OpenAI-compatible
  HTTP client can be dropped in via config (``client: "openai"``) where
  egress exists.

The LLM verdict either enriches the alert (``alertsObtain["llm - verdict"]``,
confidence into ``score``) or suppresses it (verdict "benign" with
``suppress_benign``) — turning the scorer's statistical alarm into a
triaged one.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Protocol, runtime_checkable

from ...schemas import DetectorSchema, SchemaError
from ..common.core import CoreComponent, CoreConfig, LibraryError


@runtime_checkable
class LLMClient(Protocol):
    """Pluggable assessor: one call per escalated alert."""

    def assess(self, prompt: str) -> Dict[str, Any]:
        """Return {"verdict": "malicious"|"suspicious"|"benign",
        "confidence": float 0..1, "reason": str}."""
        ...


class RuleStubLLMClient:
    """Deterministic offline assessor (the default — this environment has no
    egress, and tests need reproducible verdicts). Scores by indicator terms
    the way a prompted model reports its judgment; the interface is the
    contract, this implementation is the stand-in."""

    # NOTE: detector phrasing ("unknown value", "anomaly score") must NOT be
    # an indicator — every escalated alert contains it by construction, which
    # would make the assessor's "benign" verdict unreachable
    MALICIOUS = ("xmrig", "miner", "nc -e", "reverse shell", "/dev/shm",
                 "shellcode", "base64 -d", "curl | sh", "wget http")
    SUSPICIOUS = ("/tmp/.", "chmod 777", "segfault")

    def assess(self, prompt: str) -> Dict[str, Any]:
        text = prompt.lower()
        for term in self.MALICIOUS:
            if term in text:
                return {"verdict": "malicious", "confidence": 0.95,
                        "reason": f"indicator {term!r} present"}
        for term in self.SUSPICIOUS:
            if term in text:
                return {"verdict": "suspicious", "confidence": 0.7,
                        "reason": f"indicator {term!r} present"}
        return {"verdict": "benign", "confidence": 0.6,
                "reason": "no known indicator in flagged line"}


class OpenAICompatClient:
    """OpenAI-compatible chat-completions client over stdlib urllib (role of
    the reference library's openai dependency). Constructed lazily and only
    when configured — importless, so the offline default never touches it."""

    def __init__(self, base_url: str, model: str, api_key: str = "",
                 timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.api_key = api_key
        self.timeout_s = timeout_s

    def assess(self, prompt: str) -> Dict[str, Any]:
        import urllib.request

        body = json.dumps({
            "model": self.model,
            "messages": [
                {"role": "system", "content":
                 "You are a security analyst. Reply with a single JSON "
                 "object: {\"verdict\": \"malicious|suspicious|benign\", "
                 "\"confidence\": 0..1, \"reason\": \"...\"}."},
                {"role": "user", "content": prompt},
            ],
            "temperature": 0,
        }).encode()
        req = urllib.request.Request(
            self.base_url + "/chat/completions", data=body, method="POST",
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {self.api_key}"}
                        if self.api_key else {})})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            payload = json.loads(resp.read())
        content = payload["choices"][0]["message"]["content"]
        return json.loads(content)


class LLMEscalationDetectorConfig(CoreConfig):
    method_type: str = "llm_escalation"
    client: str = "stub"              # "stub" | "openai"
    base_url: str = "http://127.0.0.1:8000/v1"
    model: str = "gpt-4o-mini"
    api_key_env: str = "DETECTMATE_LLM_API_KEY"
    timeout_s: float = 10.0
    # drop alerts the assessor judges benign below this confidence bar
    suppress_benign: bool = False
    suppress_confidence: float = 0.8
    # cap on assessor calls per process lifetime (cost guard); beyond it
    # alerts pass through unassessed, annotated as such
    max_assessments: int = 10000


class LLMEscalationDetector(CoreComponent):
    """Pipeline stage placed AFTER a detector: consumes DetectorSchema
    alerts, escalates each to the LLM client, enriches or suppresses."""

    config_class = LLMEscalationDetectorConfig
    category = "detectors"
    description = "LLMEscalationDetector triages detector alerts through an LLM assessor."

    def __init__(self, name: Optional[str] = None, config: Any = None,
                 client: Optional[LLMClient] = None) -> None:
        super().__init__(name=name or "LLMEscalationDetector", config=config)
        self.config: LLMEscalationDetectorConfig
        self._client = client  # injected (tests) or built from config
        self.assessed = 0
        self.suppressed = 0

    # -- client wiring ---------------------------------------------------
    def _get_client(self) -> LLMClient:
        if self._client is None:
            self._client = self._build_client()
        return self._client

    def _build_client(self) -> LLMClient:
        cfg = self.config
        if cfg.client == "stub":
            return RuleStubLLMClient()
        if cfg.client == "openai":
            import os

            return OpenAICompatClient(cfg.base_url, cfg.model,
                                      os.environ.get(cfg.api_key_env, ""),
                                      cfg.timeout_s)
        raise LibraryError(f"unknown LLM client {self.config.client!r}")

    def apply_config(self) -> None:
        self._client = None  # rebuilt lazily from the new config

    # -- engine contract -------------------------------------------------
    def process(self, data: bytes) -> Optional[bytes]:
        try:
            alert = DetectorSchema.from_bytes(data)
        except SchemaError:
            return None
        cfg = self.config
        if self.assessed >= cfg.max_assessments:
            alert["alertsObtain"].update({"llm - verdict": "unassessed (budget)"})
            return alert.serialize()
        self.assessed += 1
        try:
            result = self._get_client().assess(self._prompt(alert))
        except Exception as exc:  # assessor down: never lose the alert
            alert["alertsObtain"].update(
                {"llm - verdict": f"unassessed (error: {exc})"})
            return alert.serialize()
        verdict = str(result.get("verdict", "suspicious"))
        confidence = float(result.get("confidence", 0.0))
        if (cfg.suppress_benign and verdict == "benign"
                and confidence >= cfg.suppress_confidence):
            self.suppressed += 1
            return None  # triaged away: no output at all
        alert["alertsObtain"].update({
            "llm - verdict": verdict,
            "llm - confidence": f"{confidence:.2f}",
            "llm - reason": str(result.get("reason", ""))[:500],
        })
        return alert.serialize()

    def _prompt(self, alert: DetectorSchema) -> str:
        return (
            "A log anomaly detector flagged the following event.\n"
            f"detector: {alert.detectorType} ({alert.detectorID})\n"
            f"score: {alert.score}\n"
            f"log ids: {list(alert.logIDs)}\n"
            f"findings: {json.dumps(dict(alert.alertsObtain), sort_keys=True)}\n"
            f"description: {alert.description}\n"
            "Is this malicious, suspicious, or benign?"
        )
