"""File reader: raw text lines → LogSchema messages.

Parity with the reference library's ``readers`` category
(reference: src/service/features/config_manager.py:15, config_loader.py:23
name the ``readers.log_file.LogFileConfig`` shape). Two modes:

* as a pipeline component, ``process`` wraps incoming raw text (one or more
  newline-separated lines) into LogSchema bytes — the ingress adapter role
  fluentd plays in the reference demo stack,
* ``read()`` iterates a configured file and yields LogSchema messages, the
  in-process equivalent of the file-tailing reader.
"""
from __future__ import annotations

import socket
import uuid
from pathlib import Path
from typing import Any, Iterator, Optional

from ...schemas import LogSchema
from ..common.core import CoreComponent, CoreConfig, LibraryError


class LogFileConfig(CoreConfig):
    method_type: str = "log_file"
    path: Optional[str] = None
    log_source: Optional[str] = None


class LogFileReader(CoreComponent):
    config_class = LogFileConfig
    category = "readers"

    def __init__(self, name: Optional[str] = None, config: Any = None) -> None:
        super().__init__(name=name, config=config)
        self.config: LogFileConfig
        self._hostname = socket.gethostname()

    def make_log(self, line: str) -> LogSchema:
        return LogSchema(
            logID=str(uuid.uuid4()),
            log=line,
            logSource=self.config.log_source or self.config.path or self.name,
            hostname=self._hostname,
        )

    def process(self, data: bytes) -> Optional[bytes]:
        """Wrap raw text into a LogSchema (first non-empty line)."""
        try:
            text = data.decode("utf-8", errors="replace")
        except Exception as exc:  # pragma: no cover - decode never raises here
            raise LibraryError(f"{self.name}: cannot decode input: {exc}") from exc
        for line in text.splitlines():
            if line.strip():
                return self.make_log(line).serialize()
        return None

    def read(self, path: Optional[str] = None) -> Iterator[LogSchema]:
        """Yield a LogSchema per non-empty line of the file."""
        target = path or self.config.path
        if not target:
            raise LibraryError(f"{self.name}: no file path configured")
        try:
            with open(Path(target), "r", encoding="utf-8", errors="replace") as fh:
                for line in fh:
                    line = line.rstrip("\n")
                    if line.strip():
                        yield self.make_log(line)
        except OSError as exc:
            raise LibraryError(f"{self.name}: cannot read {target}: {exc}") from exc
