from .log_file import LogFileReader, LogFileConfig

__all__ = ["LogFileReader", "LogFileConfig"]
