"""Deterministic detector double for pipeline tests.

Role of the reference's published test double
``detectmatelibrary_tests.test_detectors.dummy_detector.DummyDetector``
(usage: tests/library_integration/test_detector_integration.py:25-27,92-115 —
detects in a fixed False/True/False alternation so tests can assert exactly
which messages produce alerts and which produce *no output at all*).
"""
from __future__ import annotations

from typing import Any, Optional

from ...schemas import DetectorSchema, ParserSchema
from ..common.detector import BufferMode, CoreDetector, CoreDetectorConfig


class DummyDetectorConfig(CoreDetectorConfig):
    method_type: str = "dummy_detector"
    pattern: list = [False, True, False]


class DummyDetector(CoreDetector):
    config_class = DummyDetectorConfig
    description = "DummyDetector alternates detections deterministically."

    def __init__(self, name: Optional[str] = None, config: Any = None,
                 buffer_mode: BufferMode = BufferMode.NO_BUF) -> None:
        super().__init__(name=name or "DummyDetector", buffer_mode=buffer_mode,
                         config=config)
        self.config: DummyDetectorConfig
        self._calls = 0

    def train(self, input_: ParserSchema) -> None:
        return

    def detect(self, input_: ParserSchema, output_: DetectorSchema) -> bool:
        pattern = self.config.pattern or [False]
        hit = bool(pattern[self._calls % len(pattern)])
        self._calls += 1
        if hit:
            output_["score"] = 1.0
            output_["alertsObtain"].update({"Dummy": "deterministic detection"})
        return hit
