from .dummy_parser import DummyParser, DummyParserConfig
from .dummy_detector import DummyDetector, DummyDetectorConfig

__all__ = ["DummyParser", "DummyParserConfig", "DummyDetector", "DummyDetectorConfig"]
