"""Deterministic parser double for pipeline tests.

Role of the reference's published test double
``detectmatelibrary_tests.test_parsers.dummy_parser.DummyParser`` (usage:
tests/library_integration/test_one_pipe_to_rule_them_all.py:10,35-62 — returns
a fixed template/variables for any input so tests can assert exact pipelines).
"""
from __future__ import annotations

import time
from typing import Any, Optional

from ...schemas import LogSchema, ParserSchema
from ..common.core import CoreComponent, CoreConfig


class DummyParserConfig(CoreConfig):
    method_type: str = "dummy_parser"
    template: str = "User <*> logged in from <*>"
    variables: list = ["john", "192.168.1.100"]
    event_id: int = 1


class DummyParser(CoreComponent):
    config_class = DummyParserConfig
    category = "parsers"

    def __init__(self, name: Optional[str] = None, config: Any = None) -> None:
        super().__init__(name=name or "DummyParser", config=config)
        self.config: DummyParserConfig

    def process(self, data: bytes) -> Optional[bytes]:
        input_ = LogSchema.from_bytes(data)
        now = int(time.time())
        out = ParserSchema(
            parserType=self.config.method_type,
            parserID=self.name,
            EventID=self.config.event_id,
            template=self.config.template,
            variables=list(self.config.variables),
            logID=input_.get("logID") or "",
            log=input_.get("log") or "",
            receivedTimestamp=now,
            parsedTimestamp=now,
        )
        return out.serialize()
