"""Device-paced detector double: models a DEVICE-BOUND scorer.

``scripts/replica_bench.py`` needs to measure the replica-router tier's
scale-out — N replicas sustaining ~N× one replica's goodput — but on a
host with fewer cores than replicas a CPU-bound scorer cannot scale by
construction (the cores are the ceiling, not the router). The regime the
paper targets is the opposite: the TPU does the scoring while the host
only orchestrates, so replica throughput is bounded by *device* time that
overlaps freely across replica processes.

:class:`PacedDetector` models exactly that regime: each ``process_batch``
call "occupies the device" for ``service_ms`` of wall time (a sleep — no
host CPU consumed, like a dispatch waiting on device compute + readback)
and then passes every message through unchanged. One batch at a time per
replica, like a scorer with ``pipeline_depth`` 0. The bench's ``jax``
mode swaps this for the real ``JaxScorerDetector`` on hosts that can
exercise it.
"""
from __future__ import annotations

import time
from typing import Any, List, Optional

from ..common.core import CoreComponent, CoreConfig


class PacedDetectorConfig(CoreConfig):
    method_type: str = "paced_detector"
    # wall milliseconds one batch "occupies the device"
    service_ms: float = 50.0


class PacedDetector(CoreComponent):
    config_class = PacedDetectorConfig
    category = "detectors"
    description = ("PacedDetector passes messages through after a fixed "
                   "per-batch device-time wait (replica-bench double).")

    def __init__(self, name: Optional[str] = None, config: Any = None) -> None:
        super().__init__(name=name or "PacedDetector", config=config)
        self.config: PacedDetectorConfig

    def _occupy_device(self) -> None:
        wait_s = max(0.0, float(self.config.service_ms)) / 1000.0
        if wait_s:
            time.sleep(wait_s)

    def process(self, data: bytes) -> Optional[bytes]:
        self._occupy_device()
        return data

    def process_batch(self, batch: List[bytes]) -> List[Optional[bytes]]:
        """One device occupancy per BATCH — the whole point: a bigger
        micro-batch amortizes the device wait exactly like a real
        accelerator dispatch."""
        self._occupy_device()
        return list(batch)
