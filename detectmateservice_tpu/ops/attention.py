"""Attention ops for the scorer models.

TPU-first: batched, bfloat16-friendly einsum attention the MXU tiles well,
with a numerically stable blockwise variant that is the building block for
ring attention (parallel/ring.py), and the fused pallas kernel (ops/flash.py)
for long sequences. ``attention()`` routes between them: below
``FLASH_MIN_SEQ`` the whole score matrix fits one MXU tile and XLA's fused
einsum is already optimal (measured: the kernel only wins from ~512 tokens),
above it the pallas kernel avoids materializing the [S, T] logits in HBM.
"""
from __future__ import annotations

import contextlib
import contextvars
from typing import Optional

import jax
import jax.numpy as jnp

# measured on TPU v5e (scripts/bench_flash.py): flash ~parity with the fused
# einsum at S=1024-4096 and 2.4-2.7x faster at S=8192 (where einsum's [S,S]
# fp32 logits are also 1 GB/batch-head and OOM first); below this the einsum
# path stays — one MXU tile, nothing for a kernel to save
FLASH_MIN_SEQ = 2048

# (mesh, batch_axis, seq_axis) for impl="ring" — set by the execution layer
# (parallel.ShardedScorer) around tracing so the *model* stays mesh-agnostic:
# the same LogBERT module scores single-device, dp×tp, or sequence-parallel
# purely by who wraps the call
_RING_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "dm_ring_attention_ctx", default=None)


@contextlib.contextmanager
def ring_context(mesh, batch_axis: Optional[str] = None, axis_name: str = "seq"):
    """Make ``impl="ring"`` resolvable inside model code traced under this
    scope. Tracing-time only — compiled executables keep the mesh baked in."""
    token = _RING_CTX.set((mesh, batch_axis, axis_name))
    try:
        yield
    finally:
        _RING_CTX.reset(token)


def attention(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,  # [B, H, T, D]
    v: jax.Array,  # [B, H, T, D]
    key_mask: Optional[jax.Array] = None,  # [B, T] bool; True = attend
    impl: str = "auto",
) -> jax.Array:
    """Route to the right attention implementation.

    ``impl``: "auto" (flash on TPU for long sequences, einsum otherwise),
    "einsum", "flash", "blockwise", or "ring" (sequence-parallel exact
    attention over the mesh provided via ``ring_context``). The mask here is
    the scorer's PAD-key form ([B, T]); einsum/blockwise broadcast it, ring
    uses it as per-shard key validity."""
    t = k.shape[2]
    if impl == "auto":
        on_tpu = any(d.platform == "tpu" for d in jax.devices())
        impl = "flash" if (on_tpu and t >= FLASH_MIN_SEQ) else "einsum"
    if impl == "ring":
        ctx = _RING_CTX.get()
        if ctx is None:
            raise ValueError(
                "attention impl='ring' needs a sequence mesh: run the model "
                "through parallel.ShardedScorer with a 'seq' mesh axis (or "
                "wrap the call in ops.attention.ring_context)")
        mesh, batch_axis, axis_name = ctx
        from ..parallel.ring import ring_attention

        return ring_attention(q, k, v, mesh, kv_valid=key_mask,
                              axis_name=axis_name, batch_axis=batch_axis)
    if impl == "flash":
        from .flash import flash_attention

        # interpret mode keeps a forced flash config runnable (and its
        # numerics testable) on CPU hosts — slow, but not a crash
        on_tpu = any(d.platform == "tpu" for d in jax.devices())
        return flash_attention(q, k, v, key_mask, interpret=not on_tpu)
    mask = None if key_mask is None else key_mask[:, None, None, :]
    if impl == "blockwise":
        return blockwise_attention(q, k, v, mask=mask)
    return dot_product_attention(q, k, v, mask)


def dot_product_attention(
    q: jax.Array,  # [B, H, S, D]
    k: jax.Array,  # [B, H, T, D]
    v: jax.Array,  # [B, H, T, D]
    mask: Optional[jax.Array] = None,  # broadcastable to [B, H, S, T]; True = attend
) -> jax.Array:
    """Standard softmax attention; accumulates in fp32 regardless of input dtype."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)


def blockwise_attention_step(
    q: jax.Array,            # [B, H, S, D]
    k_block: jax.Array,      # [B, H, Tb, D]
    v_block: jax.Array,      # [B, H, Tb, D]
    acc: jax.Array,          # [B, H, S, D] fp32 running numerator
    row_max: jax.Array,      # [B, H, S] fp32 running max
    row_sum: jax.Array,      # [B, H, S] fp32 running denominator
    mask_block: Optional[jax.Array] = None,  # [B, H, S, Tb]
):
    """One streaming-softmax update against a block of keys/values.

    The online-softmax recurrence (flash-attention style): callers scan this
    over key/value blocks — locally for long sequences, or over ppermute'd
    shards for ring attention — and finish with ``acc / row_sum``.
    """
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k_block,
                        preferred_element_type=jnp.float32) * scale
    if mask_block is not None:
        logits = jnp.where(mask_block, logits, jnp.finfo(jnp.float32).min)
    block_max = jnp.max(logits, axis=-1)                      # [B,H,S]
    new_max = jnp.maximum(row_max, block_max)
    correction = jnp.exp(row_max - new_max)
    probs = jnp.exp(logits - new_max[..., None])              # [B,H,S,Tb]
    new_sum = row_sum * correction + probs.sum(axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum(
        "bhst,bhtd->bhsd", probs, v_block.astype(jnp.float32)
    )
    return new_acc, new_max, new_sum


def blockwise_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    block_size: int = 128,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Full attention computed in key blocks via ``lax.scan`` — O(S·Tb) memory.

    Matches ``dot_product_attention`` numerically (fp32 accumulation); used for
    long-context scoring where the [S, T] logits matrix would blow VMEM/HBM.
    """
    b, h, s, d = q.shape
    t = k.shape[2]
    if t % block_size != 0:
        raise ValueError(f"key length {t} not divisible by block size {block_size}")
    n_blocks = t // block_size
    k_blocks = k.reshape(b, h, n_blocks, block_size, d).transpose(2, 0, 1, 3, 4)
    v_blocks = v.reshape(b, h, n_blocks, block_size, d).transpose(2, 0, 1, 3, 4)
    if mask is not None:
        mask = jnp.broadcast_to(mask, (b, h, s, t))
        mask_blocks = mask.reshape(b, h, s, n_blocks, block_size).transpose(3, 0, 1, 2, 4)
    else:
        mask_blocks = jnp.ones((n_blocks, b, h, s, block_size), dtype=bool)

    init = (
        jnp.zeros((b, h, s, d), jnp.float32),
        jnp.full((b, h, s), jnp.finfo(jnp.float32).min, jnp.float32),
        jnp.zeros((b, h, s), jnp.float32),
    )

    def step(carry, blocks):
        k_b, v_b, m_b = blocks
        acc, row_max, row_sum = carry
        return blockwise_attention_step(q, k_b, v_b, acc, row_max, row_sum, m_b), None

    (acc, _, row_sum), _ = jax.lax.scan(step, init, (k_blocks, v_blocks, mask_blocks))
    # defensive guard matching ring.py; row_sum stays ≥ 1 even for fully
    # masked rows (masked logits are finfo.min, not -inf, so probs = 1)
    return (acc / jnp.maximum(row_sum[..., None], 1e-30)).astype(q.dtype)
