"""Fused flash attention as a Pallas TPU kernel.

The promise at ops/attention.py:5 made real: a single fused kernel computing
softmax(QK^T/sqrt(d)) V with the online-softmax recurrence, so the [S, T]
logits matrix never materializes in HBM — the working set per grid step is
one (block_q x d) query tile, one (block_k x d) key/value tile, and the
(block_q x d) fp32 accumulator in VMEM.

When it matters: long-context scoring (SURVEY §5.7 analog — multi-line log
windows, stack traces, transaction sessions tokenized to thousands of
tokens). At the flagship scorer's default seq_len=32 the whole attention fits
in one MXU tile and XLA's fused einsum is already optimal — so
``attention()`` in ops/attention.py routes: seq < FLASH_MIN_SEQ stays on the
einsum path, longer sequences take this kernel. Measured on TPU v5e
(scripts/bench_flash.py, median-of-15 blocking calls): parity at
S=1024-4096, **2.4-2.7x at S=8192** (einsum 180 ms vs flash 67-75 ms,
B1 H4 D64) — and the einsum path's [B,H,S,S] fp32 logits (1 GB per
batch-head at S=8192) OOM long before the kernel's O(S·block_k) VMEM
working set does.

Training-grade: the backward is two more fused kernels (dq; dk+dv) that
recompute probability tiles from (q, k, saved per-row logsumexp) — the
FlashAttention backward recurrence — so gradients also never materialize
the [S, T] logits, and long-context *training* keeps the same memory
profile as scoring. The scoring path skips the lse output entirely (no
extra HBM write when no grad is pending).

Layout choices, TPU-first:
* grid = (B*H, S/block_q, T/block_k) with the k dimension innermost and
  "arbitrary" semantics (sequential accumulation), q/batch dims parallel;
* fp32 accumulator + running (max, sum) live in VMEM scratch across the
  k-steps; the output tile is written once, on the last k-step;
* PAD-key masking arrives as an additive fp32 bias [B, T] (0 or -1e30) so
  the kernel needs no boolean plumbing and padding to block multiples is
  masking-correct by construction;
* blocks default to 128x128 — the MXU tile — with fp32 accumulation via
  ``preferred_element_type`` on both matmuls.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 256  # best of the swept (bq, bk) grids on v5e at S>=4096
DEFAULT_BLOCK_K = 512
_NEG_BIG = -1e30

try:  # pallas import kept lazy-tolerant: CPU-only deployments skip the kernel
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # jax renamed TPUCompilerParams -> CompilerParams (~0.5); support both so
    # the kernels run on this image's 0.4.x AND current jax
    _COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None)
    _PALLAS_OK = _COMPILER_PARAMS is not None
except Exception:  # pragma: no cover - environment without pallas
    _PALLAS_OK = False


def _flash_kernel(bias_ref, q_ref, k_ref, v_ref, o_ref, *rest,
                  scale: float, want_lse: bool):
    """One (batch*head, q-block, k-block) grid step of online softmax.
    ``want_lse`` (backward pass pending) adds a second output carrying the
    per-row logsumexp; the scoring path skips the write entirely."""
    if want_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        (acc_ref, m_ref, l_ref), lse_ref = rest, None
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0]                                   # [bq, d]
    k = k_ref[0]                                   # [bk, d]
    v = v_ref[0]                                   # [bk, d]
    s = jax.lax.dot_general(                       # [bq, bk] fp32
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    s = s + bias_ref[0]                            # [1, bk]: PAD keys -> -1e30

    m_prev = m_ref[:, :1]                          # [bq, 1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                         # [bq, bk]
    l_new = l_prev * correction + p.sum(axis=-1, keepdims=True)
    # p casts down to the value dtype (bf16 on the hot path) so BOTH matmuls
    # run the MXU at native width; accumulation stays fp32 throughout
    acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finalize():
        # l >= 1 always: every row has at least the -1e30-biased exp terms
        # summed with max subtracted, so a fully-masked row divides by the
        # number of keys, producing ~0 output rather than NaN
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row logsumexp L = m + log(l): the backward's softmax
            # denominator — saving it is what lets the bwd kernels
            # recompute p = exp(s - L) in one pass, no online recurrence
            lse_ref[0] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l_safe),
                                          lse_ref.shape[1:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(
    q: jax.Array,                     # [B, H, S, D]
    k: jax.Array,                     # [B, H, T, D]
    v: jax.Array,                     # [B, H, T, D]
    key_mask: Optional[jax.Array] = None,   # [B, T] bool; True = attend
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    """Fused attention; numerically matches ``dot_product_attention`` with a
    broadcast key mask (the scorer's use). S/T pad up to block multiples
    internally; D must be an MXU-friendly multiple of 8 (it is 64 for every
    shipped config).

    Differentiable end to end in the fused regime: the forward saves the
    per-row logsumexp, and the backward (``custom_vjp``) runs two more
    Pallas kernels (dq; dk+dv) that recompute the probability tiles from
    (q, k, lse) — so neither direction ever materializes the [S, T]
    logits in HBM and long-context *training* keeps the O(S·block)
    memory profile. Gradients match the einsum formulation's (pinned in
    tests/test_flash.py)."""
    out, _ = _flash_forward(q, k, v, key_mask, block_q, block_k, interpret,
                            want_lse=False)
    return out


def _pad_inputs(q, k, v, key_mask, block_q, block_k):
    """Shared fwd/bwd padding: S/T up to block multiples, PAD keys as an
    additive fp32 bias. Returns the padded operands + the shapes."""
    b, h, s, d = q.shape
    t = k.shape[2]
    block_q = min(block_q, max(s, 8))
    block_k = min(block_k, max(t, 8))
    s_pad = -(-s // block_q) * block_q
    t_pad = -(-t // block_k) * block_k
    if key_mask is None:
        key_mask = jnp.ones((b, t), dtype=bool)
    if t_pad != t:
        key_mask = jnp.pad(key_mask, ((0, 0), (0, t_pad - t)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))
    if s_pad != s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    # [B, 1, Tp]: the singleton middle dim satisfies the TPU block-shape rule
    # (last two block dims must divide (8, 128) or equal the array dims)
    bias = jnp.where(key_mask, 0.0, _NEG_BIG).astype(jnp.float32)[:, None, :]
    return q, k, v, bias, block_q, block_k, s_pad, t_pad


def _flash_forward(q, k, v, key_mask, block_q, block_k, interpret,
                   want_lse: bool):
    """Run the fused forward; returns (out [B,H,S,D], lse [BH,Sp,128] or
    None). The lse output exists only when a backward is pending — the
    scoring path skips its HBM write."""
    if not _PALLAS_OK:
        raise RuntimeError("pallas is unavailable in this jax install")
    b, h, s, d = q.shape
    q, k, v, bias, block_q, block_k, s_pad, t_pad = _pad_inputs(
        q, k, v, key_mask, block_q, block_k)
    qr = q.reshape(b * h, s_pad, d)
    kr = k.reshape(b * h, t_pad, d)
    vr = v.reshape(b * h, t_pad, d)
    grid = (b * h, s_pad // block_q, t_pad // block_k)

    out_specs = [pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * h, s_pad, d), q.dtype)]
    if want_lse:
        out_specs.append(
            pl.BlockSpec((1, block_q, 128), lambda bh, qi, ki: (bh, qi, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, s_pad, 128), jnp.float32))

    result = pl.pallas_call(
        functools.partial(_flash_kernel, scale=d ** -0.5, want_lse=want_lse),
        grid=grid,
        in_specs=[
            # bias indexes by batch (= bh // h), broadcast over heads/q
            pl.BlockSpec((1, 1, block_k), lambda bh, qi, ki: (bh // h, 0, ki)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # accumulator
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(bias, qr, kr, vr)
    out, lse = (result if want_lse else (result[0], None))

    out = out.reshape(b, h, s_pad, d)
    return (out[:, :, :s] if s_pad != s else out), lse


def _dq_kernel(bias_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, scale: float):
    """dQ: grid (BH, S/bq, T/bk), k innermost; dq accumulates across k."""
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = s + bias_ref[0]
    p = jnp.exp(s - lse_ref[0][:, :1])             # [bq, bk] via saved L
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, :1])            # [bq, bk]
    dq_acc[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(bias_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float):
    """dK/dV: grid (BH, T/bk, S/bq), q innermost; both accumulate across q."""
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = s + bias_ref[0]
    p = jnp.exp(s - lse_ref[0][:, :1])             # [bq, bk]
    dv_acc[:] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)        # pᵀ · dO  → [bk, d]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, :1])
    dk_acc[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # dsᵀ · Q → [bk, d]

    @pl.when(qb == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _reference_attention(q, k, v, key_mask):
    """The einsum formulation the kernel matches — the fwd/grad parity
    oracle in tests. (No pallas ⇒ flash_attention raises up front; there
    is deliberately no silent einsum fallback inside this module — the
    route decision lives in ops/attention.py.)"""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * (d ** -0.5)
    if key_mask is not None:
        s = s + jnp.where(key_mask, 0.0, _NEG_BIG)[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _flash_fwd(q, k, v, key_mask, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, key_mask, block_q, block_k, interpret,
                              want_lse=True)
    return out, (q, k, v, key_mask, out, lse)


def _flash_bwd(block_q, block_k, interpret, residuals, g):
    q, k, v, key_mask, out, lse = residuals
    b, h, s, d = q.shape
    t = k.shape[2]
    # delta_i = Σ_d dO·O per row — the softmax-jacobian rowsum, computed
    # once outside the kernels (an [S, D] elementwise + reduce, cheap)
    delta = jnp.einsum("bhsd,bhsd->bhs", g.astype(jnp.float32),
                       out.astype(jnp.float32))
    qp, kp, vp, bias, bq, bk, s_pad, t_pad = _pad_inputs(
        q, k, v, key_mask, block_q, block_k)
    dop = jnp.pad(g, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    deltap = jnp.pad(delta, ((0, 0), (0, 0), (0, s_pad - s)))
    deltar = jnp.broadcast_to(
        deltap.reshape(b * h, s_pad, 1), (b * h, s_pad, 128))
    qr = qp.reshape(b * h, s_pad, d)
    kr = kp.reshape(b * h, t_pad, d)
    vr = vp.reshape(b * h, t_pad, d)
    dor = dop.reshape(b * h, s_pad, d)
    scale = d ** -0.5

    q_spec = pl.BlockSpec((1, bq, d), lambda bh_, i, j: (bh_, i, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda bh_, i, j: (bh_, j, 0))
    row_spec = pl.BlockSpec((1, bq, 128), lambda bh_, i, j: (bh_, i, 0))
    bias_spec = pl.BlockSpec((1, 1, bk), lambda bh_, i, j: (bh_ // h, 0, j))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale),
        grid=(b * h, s_pad // bq, t_pad // bk),
        in_specs=[bias_spec, q_spec, k_spec, k_spec, q_spec, row_spec,
                  row_spec],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh_, i, j: (bh_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(bias, qr, kr, vr, dor, lse, deltar)

    # dkv grid swaps the outer block dim to k; index maps flip accordingly
    q_spec2 = pl.BlockSpec((1, bq, d), lambda bh_, i, j: (bh_, j, 0))
    k_spec2 = pl.BlockSpec((1, bk, d), lambda bh_, i, j: (bh_, i, 0))
    row_spec2 = pl.BlockSpec((1, bq, 128), lambda bh_, i, j: (bh_, j, 0))
    bias_spec2 = pl.BlockSpec((1, 1, bk), lambda bh_, i, j: (bh_ // h, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale),
        grid=(b * h, t_pad // bk, s_pad // bq),
        in_specs=[bias_spec2, q_spec2, k_spec2, k_spec2, q_spec2, row_spec2,
                  row_spec2],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh_, i, j: (bh_, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh_, i, j: (bh_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_pad, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(bias, qr, kr, vr, dor, lse, deltar)

    dq = dq.reshape(b, h, s_pad, d)[:, :, :s]
    dk = dk.reshape(b, h, t_pad, d)[:, :, :t]
    dv = dv.reshape(b, h, t_pad, d)[:, :, :t]
    return dq, dk, dv, None


flash_attention.defvjp(_flash_fwd, _flash_bwd)
