"""Fused candidate-vocab scoring head as a Pallas TPU kernel.

The sequence families' detect-path bottleneck is the scoring head: for
every token position, logits against the candidate subset ``emb_c`` and a
logsumexp over them (models/base.py ``_token_nlls_candidate``; the r3
roofline measured logbert-candidate at 5.6% MFU, VPU-softmax-bound). On
the XLA path the ``[N, C]`` logits tensor materializes between the matmul
and the reduce — at N = B·S = 512k, C = 2048 that is 2 GB of HBM traffic
written and read back per batch.

This kernel fuses both: grid (N/block_n, C/block_c) with the C dimension
innermost and "arbitrary" (sequential) semantics, an online (max, sum)
recurrence in VMEM scratch — the same shape as ops/flash.py's softmax
recurrence, minus the value matmul. The logits tile lives only in VMEM;
HBM sees the ``[N, D]`` hidden states once (the hidden block index does
not change across the inner C steps, so Pallas keeps the tile resident),
the ``[C, D]`` candidate embeddings once per N block, and a ``[N]``-sized
output.

Correctness is pinned against the jnp reference in interpret mode on CPU
(tests/test_scorehead.py); routing lives behind the scorer's
``head_impl`` knob. Measured on the live v5e (round 4,
scripts/bench_scorehead.py slope protocol): at the candidate hot shape
(N=512k, C=2048, D=256) the XLA einsum+bf16-lse route is 1.8× FASTER
than this kernel (6.7 vs 12.1 ms/op — XLA's bf16 exp runs at twice this
kernel's fp32 lane width and its own fusion already keeps the C=2048
logits tile cheap), so ``head_impl: auto`` keeps einsum for the
candidate head. The kernel earns its keep on the EXACT full-vocab head,
where it deletes the [rows, V] chunk materialization (the HBM
high-water of the exact path) at parity speed (within ~10%).
"""
from __future__ import annotations


import jax
import jax.numpy as jnp

DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_C = 512
_NEG_BIG = -1e30

try:  # pallas import kept lazy-tolerant: CPU-only deployments skip the kernel
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # jax renamed TPUCompilerParams -> CompilerParams (~0.5); support both so
    # the kernels run on this image's 0.4.x AND current jax
    _COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None)
    _PALLAS_OK = _COMPILER_PARAMS is not None
except Exception:  # pragma: no cover - environment without pallas
    _PALLAS_OK = False


def _lse_kernel(bias_ref, h_ref, e_ref, o_ref, m_ref, l_ref):
    """One (n-block, c-block) grid step of the online logsumexp."""
    cb = pl.program_id(1)

    @pl.when(cb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[:] = jnp.zeros_like(l_ref)

    h = h_ref[:]                                   # [bn, d]
    e = e_ref[:]                                   # [bc, d]
    s = jax.lax.dot_general(                       # [bn, bc] fp32
        h, e, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )
    s = s + bias_ref[:]                            # [1, bc]: C-pad rows → -inf
    m_prev = m_ref[:, :1]                          # [bn, 1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    l_new = (l_prev * jnp.exp(m_prev - m_new)
             + jnp.exp(s - m_new).sum(axis=-1, keepdims=True))
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(cb == pl.num_programs(1) - 1)
    def _finalize():
        # l >= 1 whenever at least one candidate exists (max subtracted),
        # so the log is finite for every real row
        o_ref[:] = jnp.broadcast_to(
            jnp.log(jnp.maximum(l_ref[:, :1], 1e-30)) + m_ref[:, :1],
            o_ref.shape)


def candidate_lse(hidden: jax.Array, emb_c: jax.Array,
                  block_n: int = DEFAULT_BLOCK_N,
                  block_c: int = DEFAULT_BLOCK_C,
                  interpret: bool = False) -> jax.Array:
    """``logsumexp(hidden @ emb_c.T, axis=-1)`` without materializing the
    ``[N, C]`` logits in HBM.

    ``hidden``: [N, D] (any float dtype; the matmul accumulates fp32),
    ``emb_c``: [C, D]. Returns fp32 [N]. Both N and C pad internally to
    block multiples — padded C rows are masked out with an additive -inf
    bias (the flash-kernel pattern), so arbitrary vocab/candidate sizes
    keep full-width blocks instead of degrading to divisor-sized ones.
    """
    if not _PALLAS_OK:
        raise RuntimeError("pallas is unavailable in this jax install")
    n, d = hidden.shape
    c = emb_c.shape[0]
    block_n = min(block_n, max(n, 8))
    block_c = min(block_c, max(c, 128))
    n_pad = -(-n // block_n) * block_n
    c_pad = -(-c // block_c) * block_c
    if n_pad != n:
        hidden = jnp.pad(hidden, ((0, n_pad - n), (0, 0)))
    if c_pad != c:
        emb_c = jnp.pad(emb_c, ((0, c_pad - c), (0, 0)))
    bias = jnp.where(jnp.arange(c_pad) < c, 0.0, _NEG_BIG
                     ).astype(jnp.float32)[None, :]

    grid = (n_pad // block_n, c_pad // block_c)
    out = pl.pallas_call(
        _lse_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_c), lambda ni, ci: (0, ci)),
            pl.BlockSpec((block_n, d), lambda ni, ci: (ni, 0)),
            pl.BlockSpec((block_c, d), lambda ni, ci: (ci, 0)),
        ],
        # [bn, 128] lane-width tile; column 0 carries the result
        out_specs=pl.BlockSpec((block_n, 128), lambda ni, ci: (ni, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((block_n, 128), jnp.float32),  # running max
            pltpu.VMEM((block_n, 128), jnp.float32),  # running sum
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(bias, hidden, emb_c)
    return out[:n, 0]
