"""``detectmate`` CLI: run one service process.

Parity with the reference CLI (reference: src/service/cli.py:12-65): root
logging splits records below ERROR to stdout and ERROR+ to stderr (pinned in
the reference by tests/test_cli_logging_setup.py:21-44); ``--settings`` is
required, ``--config`` optional; the service runs until Ctrl-C.
"""
from __future__ import annotations

import argparse
import logging
import sys
from typing import List, Optional

from .core import Service
from .settings import ServiceSettings


class _MaxLevelFilter(logging.Filter):
    def __init__(self, max_level: int):
        super().__init__()
        self.max_level = max_level

    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno < self.max_level


def setup_logging(level: str = "INFO", log_format: str = "plain") -> None:
    """stdout for < ERROR, stderr for >= ERROR (reference: cli.py:12-32).
    ``log_format="json"`` emits one JSON object per record (settings
    ``log_format: json`` — the structured-event log, engine/health.py)."""
    root = logging.getLogger()
    root.setLevel(level.upper())
    for handler in list(root.handlers):
        root.removeHandler(handler)
    if log_format == "json":
        from .engine.health import JsonLogFormatter

        fmt: logging.Formatter = JsonLogFormatter()
    else:
        fmt = logging.Formatter("[%(asctime)s] %(levelname)s %(name)s: %(message)s")
    out_handler = logging.StreamHandler(sys.stdout)
    out_handler.addFilter(_MaxLevelFilter(logging.ERROR))
    out_handler.setFormatter(fmt)
    err_handler = logging.StreamHandler(sys.stderr)
    err_handler.setLevel(logging.ERROR)
    err_handler.setFormatter(fmt)
    root.addHandler(out_handler)
    root.addHandler(err_handler)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="detectmate", description="Run one DetectMate TPU service process"
    )
    parser.add_argument("--settings", required=True, help="service settings YAML")
    parser.add_argument("--config", default=None, help="component config YAML")
    args = parser.parse_args(argv)

    settings = ServiceSettings.from_yaml(args.settings)
    if args.config and not settings.config_file:
        settings.config_file = args.config
    setup_logging(settings.log_level, settings.log_format)

    service = Service(settings)
    try:
        with service:
            service.run()
    except KeyboardInterrupt:
        service.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
