"""dmroll: the model-lifecycle orchestrator behind ``/admin/model``.

One manager per service wraps the detector's rollout hooks
(library/detectors/jax_scorer.py) into the continuous loop ROADMAP item 4
asks for:

1. **sample** — a :class:`~..rollout.sampler.TrafficSampler` taps the
   dispatch path (the detector offers every dispatched token batch);
2. **fine-tune** — every ``rollout_interval_s`` the manager clones the live
   params and fine-tunes a CANDIDATE on the sampled reservoir (the live
   dispatch path never blocks: training runs on the manager thread against
   its own param tree, and every jit call rides the shapes the boundary
   fit already compiled);
3. **checkpoint** — the candidate lands in the versioned
   :class:`~..rollout.store.CheckpointStore` (crash-atomic save + manifest
   commit, keep-N rotation) BEFORE it shadows, so a crashed or held-back
   canary is still inspectable and a fleet deploy has an artifact;
4. **shadow** — sampled rows score through live AND candidate params; the
   :class:`~..rollout.shadow.ShadowEvaluator` gates promotion on score
   deltas + alert-decision flips, exported as ``model_shadow_divergence``;
5. **swap** — a promoted candidate is pre-warmed against every warm device
   bucket under an expected ``model_swap`` ledger context and then swapped
   reference-atomically on the dispatch path (zero
   ``scorer_xla_recompiles_unexpected_total`` — CI-gated); a diverging one
   becomes a structured ``model_canary_holdback`` event instead.

Admin verbs (web/router.py ``/admin/model``, client.py ``model``):
``promote`` (force the current canary, or install a stored version),
``rollback`` (previous live version), ``pin``/``unpin`` (freeze the served
version; cycles suspend while pinned), ``cycle`` (run one
sample→fine-tune→shadow cycle now). ``client.py model deploy`` composes
these with the PR-9 replica admin plane into a rolling fleet rollout.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .sampler import TrafficSampler
from .shadow import ShadowEvaluator
from .store import CheckpointStore, StoreError


class RolloutError(RuntimeError):
    pass


class _ShadowRun:
    """One candidate under shadow: params + evaluator + bookkeeping."""

    def __init__(self, version: int, params: Any, opt_state: Any,
                 evaluator: ShadowEvaluator, started: float,
                 source: str, timeout_s: float) -> None:
        self.version = version
        self.params = params
        self.opt_state = opt_state
        self.evaluator = evaluator
        self.started = started
        self.source = source      # "fine_tune" | "injected"
        self.timeout_s = timeout_s


class RolloutManager:
    def __init__(self, detector: Any, settings: Any,
                 labels: Dict[str, str], monitor: Any = None,
                 logger: Optional[logging.Logger] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time) -> None:
        self.detector = detector
        self.settings = settings
        self.labels = dict(labels)
        self.monitor = monitor
        self.logger = logger or logging.getLogger(__name__)
        self._clock = clock
        self._wall = wall_clock
        self.store = CheckpointStore(settings.rollout_dir,
                                     keep=settings.rollout_keep_checkpoints,
                                     clock=wall_clock)
        self.sampler = TrafficSampler(settings.rollout_sample_capacity,
                                      settings.rollout_sample_ratio,
                                      seed=getattr(settings, "seed", 0) or 0,
                                      clock=clock)
        detector.set_rollout_sampler(self.sampler)
        # _lock guards the cheap state below; _op_lock serializes the
        # heavyweight verbs (cycle / shadow tick / promote / rollback) so
        # an admin POST and the manager thread can never interleave a swap
        # with a fine-tune. jax work happens under _op_lock only — never
        # under _lock, which admin GETs take.
        self._lock = threading.Lock()
        self._op_lock = threading.Lock()
        self._shadow: Optional[_ShadowRun] = None
        self._history: List[Dict[str, Any]] = []
        self._last_cycle_info: Optional[Dict[str, Any]] = None
        self._last_cycle_t: Optional[float] = None
        self._started_wall = wall_clock()
        self._halt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._swap_children: Dict[str, Any] = {}
        self._divergence_hist = None
        self._version_child: Optional[tuple] = None
        self._export_metrics()

    # -- metrics ----------------------------------------------------------
    def _export_metrics(self) -> None:
        from ..engine import metrics as m

        self._divergence_hist = m.MODEL_SHADOW_DIVERGENCE().labels(
            **self.labels)
        # scrape-time checkpoint age: survives a wedged manager thread, and
        # "no checkpoint yet" ages from manager start — a trainer that
        # never produces one must look stale, not fresh
        age_gauge = m.MODEL_CHECKPOINT_AGE().labels(**self.labels)
        age_gauge.set_function(
            lambda: max(0.0, self._wall() - (
                self.store.newest_created_unix() or self._started_wall)))
        self._set_version_info(self.store.live_version() or 0)

    def _set_version_info(self, version: int) -> None:
        from ..engine import metrics as m

        model = getattr(self.detector.config, "model", "unknown")
        gauge = m.MODEL_VERSION_INFO()
        new_key = (self.labels.get("component_type"),
                   self.labels.get("component_id"), str(version), model)
        old = self._version_child
        if old is not None and old != new_key:
            try:
                gauge.remove(*old)
            except KeyError:
                pass
        gauge.labels(*new_key).set(1)
        self._version_child = new_key

    def _count_swap(self, result: str) -> None:
        child = self._swap_children.get(result)
        if child is None:
            from ..engine import metrics as m

            child = m.MODEL_SWAPS().labels(result=result, **self.labels)
            self._swap_children[result] = child
        child.inc()

    # -- events / history -------------------------------------------------
    def _note(self, kind: str, level: int = logging.WARNING,
              **fields: Any) -> Dict[str, Any]:
        doc = {"kind": kind, **fields}
        with self._lock:
            self._history.append({**doc, "at_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(self._wall()))})
            del self._history[:-64]
        if self.monitor is not None:
            self.monitor.emit_event(dict(doc), level=level)
        else:
            self.logger.log(level, "rollout event %s: %s", kind, doc)
        return doc

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._halt.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ModelRollout")
        self._thread.start()

    def stop(self) -> None:
        self._halt.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=10)
        self._thread = None

    def _shadow_ref(self) -> Optional[_ShadowRun]:
        with self._lock:
            return self._shadow

    # dmlint: thread(rollout)
    def _run(self) -> None:
        interval = max(0.05, float(self.settings.rollout_interval_s))
        tick = min(1.0, interval / 4)
        while not self._halt.wait(tick):
            try:
                if self._shadow_ref() is not None:
                    self.shadow_tick()
                elif self._due():
                    self.run_cycle(reason="interval")
            except Exception:
                # containment boundary: a failed cycle must not kill the
                # lifecycle thread — the next interval retries
                self.logger.exception("rollout cycle failed")
                self._count_swap("failed")

    def _due(self) -> bool:
        with self._lock:
            last = self._last_cycle_t
        if self.store.pinned_version() is not None:
            return False
        now = self._clock()
        if last is None:
            with self._lock:
                # anchor the first interval at manager start, not epoch
                self._last_cycle_t = now
            return False
        return now - last >= float(self.settings.rollout_interval_s)

    # -- the cycle --------------------------------------------------------
    def run_cycle(self, reason: str = "manual",
                  block: bool = False) -> Dict[str, Any]:
        """One sample→fine-tune→checkpoint→shadow cycle. With ``block``,
        shadow ticks run inline until the gate resolves (the smoke/soak
        path); otherwise the manager thread ticks the shadow forward."""
        with self._op_lock:
            info = self._start_cycle_locked(reason)
        if not block or info.get("skipped") or self._shadow_ref() is None:
            return info
        deadline = self._clock() + float(self.settings.rollout_shadow_timeout_s)
        while self._shadow_ref() is not None:
            outcome = self.shadow_tick()
            if outcome is not None:
                info["outcome"] = outcome
                break
            if self._clock() > deadline:
                with self._op_lock:
                    if self._shadow_ref() is not None:
                        info["outcome"] = self._resolve_shadow(
                            "hold", "shadow timeout")
                break
            time.sleep(0.05)
        if "outcome" not in info:
            # the manager thread's own tick may have resolved the shadow
            # between our checks — its outcome is the cycle's outcome
            with self._lock:
                info["outcome"] = self._last_cycle_info
        return info

    def _start_cycle_locked(self, reason: str) -> Dict[str, Any]:
        with self._lock:
            self._last_cycle_t = self._clock()
        if self._shadow_ref() is not None:
            return {"skipped": "a candidate is already shadowing"}
        if self.store.pinned_version() is not None:
            return {"skipped": f"pinned to v{self.store.pinned_version()}"}
        if not self.detector.rollout_ready():
            return {"skipped": "detector not fitted yet"}
        rows = self.sampler.snapshot()
        if len(rows) < int(self.settings.rollout_min_fit_rows):
            return {"skipped": f"only {len(rows)} sampled rows "
                               f"(need {self.settings.rollout_min_fit_rows})"}
        version = self.store.allocate_version()
        t0 = self._clock()
        params, opt_state, fit_info = self.detector.rollout_fine_tune(
            rows, epochs=int(self.settings.rollout_train_epochs),
            seed=version)
        ckpt_dir = str(self.store.version_dir(version))
        self.detector.save_params_checkpoint(ckpt_dir, params, opt_state)
        meta = {"source": "fine_tune", "reason": reason,
                "rows": int(len(rows)),
                "model": getattr(self.detector.config, "model", "unknown"),
                **fit_info}
        # persist the AOT warm-set spec (dmwarm): a promote on a RESTARTED
        # replica pre-warms the buckets the recording boot warmed, so the
        # cutover stays compile-free even when the promoting process never
        # dispatched those shapes itself
        warm_spec = self._warm_set_spec()
        if warm_spec is not None:
            meta["warm_set"] = warm_spec
        self.store.record(version, meta, status="shadowing")
        self._begin_shadow(version, params, opt_state, source="fine_tune")
        info = {"version": version, "rows": int(len(rows)),
                "fine_tune": fit_info,
                "elapsed_s": round(self._clock() - t0, 3)}
        self._note("model_candidate_ready", level=logging.INFO,
                   version=version, **meta)
        return info

    def _begin_shadow(self, version: int, params: Any, opt_state: Any,
                      source: str, min_samples: Optional[int] = None,
                      timeout_s: Optional[float] = None) -> None:
        evaluator = ShadowEvaluator(
            threshold=self.detector.live_threshold(),
            min_samples=int(min_samples
                            if min_samples is not None
                            else self.settings.rollout_min_shadow_samples),
            max_mean_delta=float(self.settings.rollout_max_mean_delta),
            max_flip_ratio=float(self.settings.rollout_max_flip_ratio))
        with self._lock:
            self._shadow = _ShadowRun(
                version, params, opt_state, evaluator, self._clock(), source,
                timeout_s=float(timeout_s if timeout_s is not None
                                else self.settings.rollout_shadow_timeout_s))

    def inject_candidate(self, params: Any, opt_state: Any,
                         tag: str = "injected",
                         min_samples: Optional[int] = None,
                         timeout_s: Optional[float] = None) -> int:
        """Test/soak seam: shadow an externally-built candidate (e.g. a
        deliberately-broken param tree) through the real gate. The optional
        gate overrides let a harness keep the canary shadowing — and the
        divergence series flowing — for a controlled window."""
        with self._op_lock:
            if self._shadow_ref() is not None:
                raise RolloutError("a candidate is already shadowing")
            version = self.store.allocate_version()
            ckpt_dir = str(self.store.version_dir(version))
            self.detector.save_params_checkpoint(ckpt_dir, params, opt_state)
            meta: Dict[str, Any] = {"source": tag}
            warm_spec = self._warm_set_spec()
            if warm_spec is not None:
                meta["warm_set"] = warm_spec
            self.store.record(version, meta, status="shadowing")
            self._begin_shadow(version, params, opt_state, source=tag,
                               min_samples=min_samples, timeout_s=timeout_s)
            return version

    def shadow_tick(self, max_rows: int = 256) -> Optional[Dict[str, Any]]:
        """Score one sampled batch through live + candidate params and feed
        the divergence accounting; resolves the gate when it can. Returns
        the resolution dict once resolved, else None."""
        with self._op_lock:
            shadow = self._shadow_ref()
            if shadow is None:
                return None
            rows = self.sampler.snapshot()
            if len(rows) == 0:
                return None
            if len(rows) > max_rows:
                idx = np.random.default_rng(shadow.evaluator.samples).choice(
                    len(rows), size=max_rows, replace=False)
                rows = rows[idx]
            live = self.detector.rollout_scores(None, rows)       # live params
            cand = self.detector.rollout_scores(shadow.params, rows)
            delta = shadow.evaluator.observe(live, cand)
            for value in delta:
                self._divergence_hist.observe(float(value))
            verdict = shadow.evaluator.verdict()
            if verdict == "wait":
                if self._clock() - shadow.started > shadow.timeout_s:
                    return self._resolve_shadow("hold", "shadow timeout")
                return None
            if verdict == "promote" and not bool(
                    self.settings.rollout_auto_promote):
                return self._resolve_shadow(
                    "hold", "auto-promote disabled; POST "
                            "/admin/model {action: promote} to cut over")
            return self._resolve_shadow(verdict, "gate")

    def _resolve_shadow(self, verdict: str, why: str) -> Dict[str, Any]:
        """Caller holds ``_op_lock`` (or is ``run_cycle(block=True)``'s
        inline loop, which does)."""
        shadow = self._shadow_ref()
        if shadow is None:
            return {"result": "idle"}
        stats = shadow.evaluator.stats()
        if verdict == "promote":
            swap = self._install(shadow.params, shadow.opt_state,
                                 shadow.version, source=shadow.source,
                                 warm_set=self._stored_warm_set(
                                     shadow.version))
            self.store.set_live(shadow.version, divergence=stats)
            self._count_swap("promoted")
            self._set_version_info(shadow.version)
            self._note("model_promoted", level=logging.INFO,
                       version=shadow.version, divergence=stats, swap=swap)
            outcome = {"result": "promoted", "version": shadow.version,
                       "divergence": stats, "swap": swap}
        else:
            self.store.set_status(shadow.version, "holdback",
                                  divergence=stats, why=why)
            self._count_swap("holdback")
            self._note("model_canary_holdback", version=shadow.version,
                       divergence=stats, why=why)
            outcome = {"result": "holdback", "version": shadow.version,
                       "divergence": stats, "why": why}
        with self._lock:
            self._shadow = None
            self._last_cycle_info = outcome
        return outcome

    def _warm_set_spec(self) -> Optional[Dict[str, Any]]:
        """The detector's live AOT warm-set spec (None for components
        without one)."""
        spec_fn = getattr(self.detector, "warm_set_spec", None)
        if not callable(spec_fn):
            return None
        try:
            return spec_fn()
        # dmlint: ignore[DM-R001] warm-set spec is manifest metadata — it
        except Exception:  # noqa: BLE001 — must not block a rollout cycle
            return None

    def _stored_warm_set(self, version: int) -> Optional[Dict[str, Any]]:
        """The warm-set spec recorded with a stored version, if any."""
        try:
            return self.store.entry(version).get("meta", {}).get("warm_set")
        # dmlint: ignore[DM-R001] absent entry / legacy manifest — install
        except Exception:  # noqa: BLE001 — warms the live set instead
            return None

    def _install(self, params: Any, opt_state: Any, version: int,
                 source: str,
                 warm_set: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        swap = self.detector.install_candidate(params, opt_state,
                                               version=version,
                                               warm_set=warm_set)
        swap["source"] = source
        return swap

    # -- admin verbs ------------------------------------------------------
    def promote(self, version: Optional[int] = None) -> Dict[str, Any]:
        """Force-promote: the current shadow candidate (``version=None``)
        or a stored version (the fleet-deploy path — every replica promotes
        the same number off the shared store)."""
        with self._op_lock:
            if version is None:
                if self._shadow_ref() is None:
                    raise RolloutError(
                        "no candidate is shadowing; pass a version to "
                        "promote from the store")
                return self._resolve_shadow("promote", "operator promote")
            return self._install_version(version, action="promote")

    def rollback(self) -> Dict[str, Any]:
        with self._op_lock:
            target = self.store.previous_live()
            if target is None:
                raise RolloutError("no superseded version to roll back to")
            live = self.store.live_version()
            outcome = self._install_version(target, action="rollback")
            if live is not None:
                try:
                    self.store.set_status(live, "rolled_back")
                except StoreError:
                    pass
            return outcome

    def _install_version(self, version: int, action: str) -> Dict[str, Any]:
        """Load a stored version and hot-swap it in (promote-by-number and
        rollback share this path)."""
        entry = self.store.entry(version)          # StoreError → HTTP 400
        directory = str(self.store.root / entry["dir"])
        params, opt_state, meta = self.detector.load_params_checkpoint(
            directory)
        swap = self._install(params, opt_state, version, source=action,
                             warm_set=entry.get("meta", {}).get("warm_set"))
        self.store.set_live(version)
        result = "promoted" if action == "promote" else "rolled_back"
        self._count_swap(result)
        self._set_version_info(version)
        # literal kinds (not f"model_{result}") so the DM-E event-contract
        # analyzer can extract both from the AST
        self._note("model_promoted" if action == "promote"
                   else "model_rolled_back",
                   level=logging.INFO, version=version,
                   action=action, swap=swap)
        outcome = {"result": result, "version": version, "swap": swap}
        with self._lock:
            self._last_cycle_info = outcome
        return outcome

    def pin(self, version: Optional[int] = None) -> Dict[str, Any]:
        """Pin the served model: cycles suspend and auto-promote stops
        until ``unpin``. With a version, that version is installed first."""
        with self._op_lock:
            outcome: Dict[str, Any] = {"result": "pinned"}
            if version is not None and version != self.store.live_version():
                outcome["install"] = self._install_version(version,
                                                           action="promote")
            pin_version = (version if version is not None
                           else self.store.live_version())
            if pin_version is None:
                raise RolloutError("nothing live to pin; promote first")
            self.store.pin(pin_version)
            self._count_swap("pinned")
            self._note("model_pinned", level=logging.INFO,
                       version=pin_version)
            outcome["version"] = pin_version
            return outcome

    def unpin(self) -> Dict[str, Any]:
        with self._op_lock:
            self.store.pin(None)
            self._note("model_unpinned", level=logging.INFO)
            return {"result": "unpinned"}

    # -- status -----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            shadow = self._shadow
            shadow_doc = None
            if shadow is not None:
                shadow_doc = {"version": shadow.version,
                              "source": shadow.source,
                              "age_s": round(self._clock() - shadow.started,
                                             1),
                              **shadow.evaluator.stats()}
            last = self._last_cycle_info
            history = list(reversed(self._history))
        return {
            "enabled": True,
            "live_version": self.store.live_version(),
            "pinned_version": self.store.pinned_version(),
            "detector_version": self.detector.model_version(),
            "interval_s": float(self.settings.rollout_interval_s),
            "auto_promote": bool(self.settings.rollout_auto_promote),
            "shadow": shadow_doc,
            "last_outcome": last,
            "sampler": self.sampler.stats(),
            "store": {"root": str(self.store.root),
                      "keep": self.store.keep,
                      "versions": [e["version"]
                                   for e in self.store.history()]},
            "history": history,
        }

    def history(self, limit: Optional[int] = None) -> Dict[str, Any]:
        return {"checkpoints": self.store.history(limit),
                "live_version": self.store.live_version(),
                "pinned_version": self.store.pinned_version()}
