"""Reservoir sampler tapping the scorer dispatch path.

The continuous fine-tuning loop (rollout/manager.py) needs a recent,
representative slice of live traffic without holding the stream: the
detector offers every dispatched token batch here (one call per
micro-batch, engine thread), a seeded ratio filter thins it, and a classic
Algorithm-R reservoir bounds memory to ``capacity`` rows no matter how long
the service runs. Rows are stored as copies of the tokenized [S] int32
vectors — raw bytes never enter the sampler, so its memory bound is exactly
``capacity * seq_len * 4`` bytes (plus one fp32 score per row when the
offerer pairs scores with rows — the dmdrift tap).

Determinism: the RNG is seeded, and both the ratio filter and the reservoir
replacement indices are drawn from it in offer order — the same offered
sequence always yields the same reservoir (pinned by tests/test_rollout.py).
The clock is injected for the same reason: ``last_offer_age`` (the
staleness the manager reports) is testable without sleeping.

Scores ride ALONGSIDE the rows (dmdrift, obs/drift.py): the drain path
offers each scored batch together with its [n] fp32 scores, and the
reservoir keeps row i's score in the same slot — ``snapshot(with_scores=
True)`` returns both copies under ONE lock acquisition, so a drift
evaluation never reads a reservoir mid-mutation or pairs a row with
another row's score. Rows offered without scores carry NaN.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np


class TrafficSampler:
    """Bounded reservoir over dispatched token rows (thread-safe: the
    engine thread offers, the rollout manager and drift monitor
    snapshot/drain)."""

    def __init__(self, capacity: int, ratio: float, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if capacity <= 0:
            raise ValueError(f"sampler capacity must be > 0 (got {capacity})")
        if not 0.0 < ratio <= 1.0:
            raise ValueError(f"sample ratio must be in (0, 1] (got {ratio})")
        self.capacity = capacity
        self.ratio = ratio
        self._rng = np.random.default_rng(seed)
        self._clock = clock
        self._lock = threading.Lock()
        self._rows: List[np.ndarray] = []
        self._row_scores: List[float] = []   # parallel to _rows (NaN = none)
        self._seen = 0          # rows that passed the ratio filter
        self._offered = 0       # rows offered by the dispatch path
        self._last_offer: Optional[float] = None

    def offer_rows(self, tokens: np.ndarray,
                   scores: Optional[np.ndarray] = None) -> int:
        """Offer an [n, S] token batch from the dispatch path (optionally
        with its [n] scores); returns how many rows entered the reservoir.
        One RNG draw per offered batch for the ratio filter plus one per
        accepted row once the reservoir is full — cheap enough for the hot
        path's per-micro-batch cadence. The RNG draw sequence is identical
        with and without scores, so pairing scores in cannot perturb which
        rows a seeded run samples."""
        n = len(tokens)
        if n == 0:
            return 0
        if scores is not None and len(scores) != n:
            raise ValueError(
                f"scores must pair 1:1 with tokens ({len(scores)} != {n})")
        with self._lock:
            self._offered += n
            self._last_offer = self._clock()
            picked = np.flatnonzero(self._rng.random(n) < self.ratio)
            taken = 0
            for i in picked:
                self._seen += 1
                row = np.array(tokens[i], dtype=np.int32, copy=True)
                score = float(scores[i]) if scores is not None else float("nan")
                if len(self._rows) < self.capacity:
                    self._rows.append(row)
                    self._row_scores.append(score)
                    taken += 1
                else:
                    # Algorithm R: row j of the filtered stream replaces a
                    # reservoir slot with probability capacity/j
                    slot = int(self._rng.integers(0, self._seen))
                    if slot < self.capacity:
                        self._rows[slot] = row
                        self._row_scores[slot] = score
                        taken += 1
            return taken

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def snapshot(self, with_scores: bool = False
                 ) -> Union[np.ndarray, Tuple[np.ndarray, np.ndarray]]:
        """Copy of the reservoir as one [k, S] matrix (empty → [0, 0]).
        With ``with_scores``, returns ``(rows, scores)`` — the [k] fp32
        score paired with each row (NaN where the offerer had none) —
        both copied under ONE lock acquisition, so a concurrent
        ``offer_rows`` can neither tear the matrix nor skew a row against
        another row's score."""
        with self._lock:
            if not self._rows:
                rows = np.zeros((0, 0), np.int32)
                scores = np.zeros(0, np.float32)
            else:
                rows = np.stack(self._rows)
                scores = np.array(self._row_scores, np.float32)
        return (rows, scores) if with_scores else rows

    def last_offer_age(self) -> Optional[float]:
        with self._lock:
            if self._last_offer is None:
                return None
            return max(0.0, self._clock() - self._last_offer)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            scored = sum(1 for s in self._row_scores if s == s)  # non-NaN
            return {
                "capacity": self.capacity,
                "ratio": self.ratio,
                "held_rows": len(self._rows),
                "scored_rows": scored,
                "rows_offered": self._offered,
                "rows_sampled": self._seen,
                "last_offer_age_s": (
                    None if self._last_offer is None
                    else round(max(0.0, self._clock() - self._last_offer), 3)),
            }
