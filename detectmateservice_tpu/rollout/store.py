"""Versioned checkpoint store with an atomically-committed manifest.

Layout under ``rollout_dir``::

    MANIFEST.json        # the commit point (utils.checkpoint.write_json_atomic)
    v000001/             # one utils.checkpoint save_scorer_state dir each
    v000002/
    ...

The manifest is the ONLY state the rest of the subsystem trusts: which
versions exist, which one is live, which (if any) is pinned, and each
version's metadata (model family, tree version, norm-calibration stats,
shadow-divergence verdict). It is replaced atomically with an fsync'd
temp-file + ``os.replace`` — the same discipline as the checkpoint meta —
so a crash mid-rotation can never leave a manifest naming a half-written
version: ``record`` is only called AFTER ``save_scorer_state`` committed
the version directory's own meta.

Keep-N pruning removes the oldest entries beyond ``keep`` — but never the
live version, never a pinned version, and never the newest candidate — so
rollback always has a target and an operator pin survives any amount of
churn. A shared filesystem makes the store the fleet-rollout vehicle:
every replica points its ``rollout_dir`` at the same root and
``client.py model deploy`` promotes one version number everywhere.
"""
from __future__ import annotations

import shutil
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from ..utils.checkpoint import write_json_atomic

MANIFEST = "MANIFEST.json"
_SCHEMA = "dmroll-manifest-v1"


class StoreError(RuntimeError):
    pass


class CheckpointStore:
    def __init__(self, root: str, keep: int = 4,
                 clock: Callable[[], float] = time.time) -> None:
        if keep < 1:
            raise ValueError(f"keep must be >= 1 (got {keep})")
        self.root = Path(root).absolute()
        self.keep = keep
        self._clock = clock
        self._lock = threading.Lock()
        self.root.mkdir(parents=True, exist_ok=True)

    # -- manifest ---------------------------------------------------------
    def _load(self) -> Dict[str, Any]:
        path = self.root / MANIFEST
        if not path.exists():
            return {"schema": _SCHEMA, "live_version": None,
                    "pinned_version": None, "entries": []}
        import json

        doc = json.loads(path.read_text(encoding="utf-8"))
        if doc.get("schema") != _SCHEMA:
            raise StoreError(
                f"manifest {path} has schema {doc.get('schema')!r}; this "
                f"build reads {_SCHEMA!r}")
        return doc

    def _write(self, doc: Dict[str, Any]) -> None:
        write_json_atomic(self.root / MANIFEST, doc)

    # -- versions ---------------------------------------------------------
    def version_dir(self, version: int) -> Path:
        return self.root / f"v{version:06d}"

    def allocate_version(self) -> int:
        """Next unused version number: one past the max of manifest entries
        and on-disk ``v*`` dirs (orphans from a crashed save included, so a
        retried save never reuses a dirty directory)."""
        with self._lock:
            doc = self._load()
            top = max((e["version"] for e in doc["entries"]), default=0)
            for entry in self.root.glob("v[0-9]*"):
                try:
                    top = max(top, int(entry.name[1:]))
                except ValueError:
                    continue
            return top + 1

    def record(self, version: int, meta: Dict[str, Any],
               status: str = "candidate") -> Dict[str, Any]:
        """Commit a fully-saved version into the manifest (atomic), then
        apply keep-N pruning. Caller guarantees ``save_scorer_state``
        already landed in ``version_dir(version)``.

        ``meta`` may carry a ``warm_set`` spec (the detector's
        ``warm_set_spec()`` — dmwarm): install paths read it back so a
        promote on a restarted process AOT pre-warms the bucket set the
        recording boot warmed before cutover."""
        with self._lock:
            doc = self._load()
            entry = {
                "version": version,
                "dir": self.version_dir(version).name,
                "created_unix": self._clock(),
                "created_utc": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime(self._clock())),
                "status": status,
                "meta": dict(meta),
            }
            doc["entries"] = [e for e in doc["entries"]
                              if e["version"] != version] + [entry]
            doc["entries"].sort(key=lambda e: e["version"])
            self._prune_locked(doc)
            self._write(doc)
            return entry

    def set_status(self, version: int, status: str,
                   **meta_updates: Any) -> None:
        with self._lock:
            doc = self._load()
            entry = self._entry_locked(doc, version)
            entry["status"] = status
            entry["meta"].update(meta_updates)
            self._write(doc)

    def update_meta(self, version: int, **meta_updates: Any) -> None:
        """Merge metadata into a version's manifest entry without touching
        its status — the dmdrift baseline-pinning path (``drift_baseline``
        rides the live entry so a restarted monitor resumes against the
        same reference distribution)."""
        with self._lock:
            doc = self._load()
            entry = self._entry_locked(doc, version)
            entry["meta"].update(meta_updates)
            self._write(doc)

    def set_live(self, version: int, **meta_updates: Any) -> None:
        """Mark ``version`` live (the dispatch path's params); the previous
        live entry becomes ``superseded`` — the natural rollback target."""
        with self._lock:
            doc = self._load()
            entry = self._entry_locked(doc, version)
            for other in doc["entries"]:
                if other["status"] == "live" and other is not entry:
                    other["status"] = "superseded"
            entry["status"] = "live"
            entry["meta"].update(meta_updates)
            doc["live_version"] = version
            self._write(doc)

    def pin(self, version: Optional[int]) -> None:
        """Pin a version (protect from pruning, block auto-promote past it);
        ``None`` lifts the pin."""
        with self._lock:
            doc = self._load()
            if version is not None:
                self._entry_locked(doc, version)  # must exist
            doc["pinned_version"] = version
            self._write(doc)

    def _entry_locked(self, doc: Dict[str, Any],
                      version: int) -> Dict[str, Any]:
        for entry in doc["entries"]:
            if entry["version"] == version:
                return entry
        raise StoreError(
            f"no checkpoint version {version} in {self.root / MANIFEST}; "
            f"known: {[e['version'] for e in doc['entries']]}")

    def _prune_locked(self, doc: Dict[str, Any]) -> None:
        entries = doc["entries"]
        protected = {doc.get("live_version"), doc.get("pinned_version")}
        if entries:
            protected.add(entries[-1]["version"])   # the newest stays
        keep: List[Dict[str, Any]] = []
        removable = [e for e in entries if e["version"] not in protected]
        excess = len(entries) - self.keep
        for entry in entries:
            if excess > 0 and entry in removable:
                shutil.rmtree(self.root / entry["dir"], ignore_errors=True)
                excess -= 1
            else:
                keep.append(entry)
        doc["entries"] = keep

    # -- read side --------------------------------------------------------
    def manifest(self) -> Dict[str, Any]:
        with self._lock:
            return self._load()

    def entry(self, version: int) -> Dict[str, Any]:
        with self._lock:
            return self._entry_locked(self._load(), version)

    def live_version(self) -> Optional[int]:
        with self._lock:
            return self._load().get("live_version")

    def pinned_version(self) -> Optional[int]:
        with self._lock:
            return self._load().get("pinned_version")

    def previous_live(self) -> Optional[int]:
        """The newest ``superseded`` entry — what rollback targets."""
        with self._lock:
            doc = self._load()
            superseded = [e["version"] for e in doc["entries"]
                          if e["status"] == "superseded"]
            return max(superseded) if superseded else None

    def history(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            entries = list(reversed(self._load()["entries"]))
            return entries[:limit] if limit else entries

    def newest_created_unix(self) -> Optional[float]:
        with self._lock:
            doc = self._load()
            if not doc["entries"]:
                return None
            return max(e["created_unix"] for e in doc["entries"])
