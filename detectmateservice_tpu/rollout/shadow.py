"""Shadow-scoring canary: divergence accounting and the promotion gate.

While a candidate shadows, the manager scores sampled traffic through BOTH
param sets and feeds the per-row results here. Two divergence views:

* **score deltas** — ``|candidate - live|`` per row, exported as the
  ``model_shadow_divergence`` histogram (the ``ModelCanaryDiverging``
  signal) and summarized as mean/max;
* **alert-decision flips** — rows where ``score > threshold`` disagrees
  between the two models. Deltas measure drift in the score space; flips
  measure what an operator would actually see change. Both must clear
  their gate.

The promotion gate is three-valued: ``wait`` until ``min_samples`` rows
have shadowed (a candidate must not promote off a handful of lucky rows),
then ``promote`` when mean-|delta| ≤ ``max_mean_delta`` AND the flip ratio
≤ ``max_flip_ratio``, else ``hold`` — the manager turns a hold into a
structured ``model_canary_holdback`` event and keeps serving the live
params. Pure host-side math, fully deterministic (pinned by
tests/test_rollout.py).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np


class ShadowEvaluator:
    def __init__(self, threshold: float, min_samples: int,
                 max_mean_delta: float, max_flip_ratio: float,
                 track_top: int = 0) -> None:
        if min_samples < 1:
            raise ValueError(f"min_samples must be >= 1 (got {min_samples})")
        self.threshold = threshold
        self.min_samples = min_samples
        self.max_mean_delta = max_mean_delta
        self.max_flip_ratio = max_flip_ratio
        self.samples = 0
        self.delta_sum = 0.0
        self.delta_max = 0.0
        self.flips = 0
        # bounded worst-offender ledger (offline replay triage: WHICH
        # recorded rows moved the candidate — 0 keeps the live canary free)
        self.track_top = max(0, int(track_top))
        self._top: list = []        # (|delta|, row_id, live, cand) desc

    def observe(self, live_scores: np.ndarray,
                cand_scores: np.ndarray,
                row_ids: Optional[Sequence[int]] = None) -> np.ndarray:
        """Account one shadow batch; returns the per-row ``|delta|`` array
        so the caller can feed the ``model_shadow_divergence`` histogram.
        ``row_ids`` (optional, aligned) labels rows in the worst-offender
        ledger when ``track_top`` is set — the WAL replay passes record
        sequence numbers so an operator can pull the exact traffic back
        out of the spool."""
        live = np.asarray(live_scores, np.float64)
        cand = np.asarray(cand_scores, np.float64)
        if live.shape != cand.shape:
            raise ValueError(
                f"live/candidate score shapes differ: {live.shape} vs "
                f"{cand.shape}")
        delta = np.abs(cand - live)
        self.samples += len(delta)
        self.delta_sum += float(delta.sum())
        self.delta_max = max(self.delta_max, float(delta.max(initial=0.0)))
        self.flips += int(((live > self.threshold)
                           != (cand > self.threshold)).sum())
        if self.track_top and len(delta):
            for i in np.argsort(delta)[::-1][:self.track_top]:
                self._top.append((float(delta[i]),
                                  row_ids[i] if row_ids is not None else None,
                                  float(live[i]), float(cand[i])))
            self._top.sort(key=lambda t: t[0], reverse=True)
            del self._top[self.track_top:]
        return delta

    @property
    def mean_delta(self) -> float:
        return self.delta_sum / self.samples if self.samples else 0.0

    @property
    def flip_ratio(self) -> float:
        return self.flips / self.samples if self.samples else 0.0

    def verdict(self) -> str:
        """``wait`` | ``promote`` | ``hold`` (see module docstring)."""
        if self.samples < self.min_samples:
            return "wait"
        if (self.mean_delta <= self.max_mean_delta
                and self.flip_ratio <= self.max_flip_ratio):
            return "promote"
        return "hold"

    def stats(self) -> Dict[str, Any]:
        doc = {
            "samples": self.samples,
            "min_samples": self.min_samples,
            "mean_abs_delta": round(self.mean_delta, 6),
            "max_abs_delta": round(self.delta_max, 6),
            "flips": self.flips,
            "flip_ratio": round(self.flip_ratio, 6),
            "gate": {"max_mean_delta": self.max_mean_delta,
                     "max_flip_ratio": self.max_flip_ratio},
            "verdict": self.verdict(),
        }
        if self.track_top:
            doc["top_divergent"] = [
                {"abs_delta": round(d, 6), "row_id": rid,
                 "live": round(lv, 6), "candidate": round(cv, 6)}
                for d, rid, lv, cv in self._top]
        return doc
