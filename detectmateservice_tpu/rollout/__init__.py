"""dmroll — online learning + zero-downtime model rollout (ROADMAP item 4).

The served model becomes a versioned, continuously refreshed artifact:
``TrafficSampler`` taps the dispatch path, ``RolloutManager`` fine-tunes
candidates off the live params, the ``CheckpointStore`` rotates crash-atomic
versioned checkpoints, the ``ShadowEvaluator`` gates promotion on
shadow-scoring divergence, and the detector hot-swaps promoted params with
zero unexpected XLA recompiles. See docs/model_lifecycle.md.
"""
from .manager import RolloutError, RolloutManager
from .sampler import TrafficSampler
from .shadow import ShadowEvaluator
from .store import CheckpointStore, StoreError

__all__ = [
    "CheckpointStore",
    "RolloutError",
    "RolloutManager",
    "ShadowEvaluator",
    "StoreError",
    "TrafficSampler",
]
