"""ShardedScorer: DP×TP execution of a scorer over a device mesh.

Multi-chip scale-out for the detector hot path (SURVEY.md §7 step 6,
BASELINE.json config #5 "8× detector replicas across v5e-8"). Instead of the
reference's N independent processes, one process drives all chips: the batch
is sharded over the ``data`` axis, params are sharded over ``model`` per the
Megatron-style rules (parallel/mesh.py), and ``jit`` + GSPMD insert the ICI
collectives. Training steps psum gradients across ``data`` automatically
(they fall out of jit's partitioning — no hand-written NCCL/MPI analog).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..engine import device_obs
from ..models.tokenizer import narrow_tokens
from .mesh import (
    AXIS_DATA,
    AXIS_SEQ,
    LOGBERT_RULES,
    REPLICATED_RULES,
    make_mesh,
    tree_shardings,
)


class ShardedScorer:
    """Wraps a scorer (LogBERTScorer / MLPScorer surface) with mesh placement.

    ``score(tokens)`` and ``train_step(rng, tokens)`` own the params/opt-state
    internally (sharded once at construction) so callers just stream batches.
    """

    def __init__(
        self,
        scorer,
        mesh=None,
        rules: Optional[Sequence] = None,
        rng: Optional[jax.Array] = None,
    ):
        self.scorer = scorer
        self.mesh = mesh if mesh is not None else make_mesh()
        if rules is None:
            rules = LOGBERT_RULES if getattr(scorer, "name", "") == "logbert" else REPLICATED_RULES
        # sequence parallelism (long-context): a 'seq' mesh axis shards the
        # token/activation sequence dim; the model's attention runs as ring
        # attention over that axis (ops.attention impl="ring", resolved via
        # the ring_context this wrapper sets around tracing). Each 'data' row
        # runs its own independent ring.
        self._seq_axis = AXIS_SEQ if AXIS_SEQ in self.mesh.shape else None
        if self._seq_axis is not None:
            seq_size = int(self.mesh.shape[AXIS_SEQ])
            seq_len = getattr(getattr(scorer, "config", None), "seq_len", None)
            if seq_len is not None and seq_len % seq_size != 0:
                raise ValueError(
                    f"seq_len {seq_len} must divide by the seq mesh axis "
                    f"({seq_size}) for sequence-parallel scoring")
        # token batches travel in the narrow wire format (uint16 when the
        # vocab fits — models.tokenizer.narrow_tokens has the one rule); the
        # jitted impls cast back to int32 on device
        self._vocab_size = getattr(getattr(scorer, "config", None),
                                   "vocab_size", 1 << 31)
        self._data_axis = AXIS_DATA if AXIS_DATA in self.mesh.shape else None
        # init also traces the model (flax shape inference) so it needs the
        # ring context on a seq mesh — but with the batch axis REPLICATED:
        # flax init runs on a [1, S] dummy, and a batch of 1 cannot shard
        # over a data axis of 2+
        init_rng = rng if rng is not None else jax.random.PRNGKey(0)
        # construction-time tracing/compiles attribute to the mesh init —
        # always an expected phase, whatever context the caller holds
        with device_obs.get_ledger().context(where="sharded_init",
                                             backend="mesh", expected=True):
            if self._seq_axis is None:
                params, opt_state = scorer.init(init_rng)
            else:
                from ..ops.attention import ring_context

                with ring_context(self.mesh, batch_axis=None,
                                  axis_name=self._seq_axis):
                    params, opt_state = scorer.init(init_rng)
        self._param_sharding = tree_shardings(self.mesh, params, rules)
        self._opt_sharding = tree_shardings(self.mesh, opt_state, rules)
        self.params = jax.device_put(params, self._param_sharding)
        self.opt_state = jax.device_put(opt_state, self._opt_sharding)
        # tokens are [B, S]: batch over 'data' when present, sequence over
        # 'seq' when present — so activations start out seq-sharded and the
        # ring's shard_map needs no initial reshard
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._batch_sharding = NamedSharding(
            self.mesh, P(self._data_axis, self._seq_axis))

        self._score = jax.jit(
            scorer._score_impl,
            in_shardings=(self._param_sharding, self._batch_sharding),
        )
        self._token_nlls = jax.jit(
            scorer._token_nlls_impl,
            in_shardings=(self._param_sharding, self._batch_sharding),
        )
        self._normscore = jax.jit(
            scorer._normscore_impl,
            in_shardings=(self._param_sharding, self._batch_sharding, None, None),
        )
        self._train = jax.jit(
            scorer._train_impl,
            in_shardings=(self._param_sharding, self._opt_sharding, None,
                          self._batch_sharding),
            out_shardings=(self._param_sharding, self._opt_sharding, None),
            donate_argnums=(0, 1),
        )
        # dmwarm (PR 17): AOT-compiled executables keyed (kind, padded_B) —
        # the detector's setup_io lowers+compiles the warm bucket set here
        # so mesh dispatch executes without entering the jit compile path
        self._aot: Dict[Tuple[str, int], Any] = {}
        # weight-only int8 serving (models/quant.py): installed by the
        # detector after its parity gate passes; None = float path serves
        self._qparams = None
        self._qscore = None
        self._qnormscore = None

    @property
    def data_parallelism(self) -> int:
        return int(self.mesh.shape.get(AXIS_DATA, 1))

    def install_params(self, params, opt_state) -> None:
        """Hot-swap the served param/opt trees (model rollout): the new
        trees are placed with the SAME shardings the jitted executables
        were compiled against, so every cached executable keeps hitting —
        the swap itself is a reference assignment, never a recompile."""
        self.params = jax.device_put(params, self._param_sharding)
        self.opt_state = jax.device_put(opt_state, self._opt_sharding)

    # -- AOT warm-start (dmwarm) -----------------------------------------
    def aot_compile_bucket(self, kind: str, tokens: np.ndarray,
                           *extra) -> None:
        """Lower+compile one (kind, bucket) sharded executable and KEEP it
        (jax's AOT compile does not seed the jit's dispatch cache). The
        batch pads to the mesh's data-axis multiple first, so the key is
        the padded shape every later dispatch of this bucket produces."""
        jit_fn = {"score": self._score, "normscore": self._normscore,
                  "token_nlls": self._token_nlls}[kind]
        tokens, _ = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        args = (self.params, tokens, *extra)
        with device_obs.get_ledger().context(bucket=tokens.shape[0],
                                             backend="mesh",
                                             where="sharded"):
            if self._seq_axis is None:
                self._aot[(kind, tokens.shape[0])] = (
                    jit_fn.lower(*args).compile())
            else:
                from ..ops.attention import ring_context

                with ring_context(self.mesh, batch_axis=self._data_axis,
                                  axis_name=self._seq_axis):
                    self._aot[(kind, tokens.shape[0])] = (
                        jit_fn.lower(*args).compile())

    def _aot_call(self, kind: str, batch: int, *args):
        """The kept executable for (kind, batch), called directly — returns
        None when absent or on aval drift (caller falls back to the jit)."""
        comp = self._aot.get((kind, batch))
        if comp is None:
            return None
        try:
            return comp(*args)
        # dmlint: ignore[DM-R001] aval drift returns None — the caller
        except Exception:  # noqa: BLE001 — falls back to the traced jit
            return None

    # -- weight-only int8 serving (dmwarm) -------------------------------
    def install_quantized(self, qparams) -> None:
        """Install a quantized tree (models/quant.quantize_tree of the live
        params): the int8 payloads shard exactly like their float leaves,
        the per-channel scales along the leaf's last-axis placement. The
        detector's parity gate decides whether this tree ever serves."""
        from ..models.quant import dequantize_tree, quant_shardings

        qshard = quant_shardings(self.params, self._param_sharding,
                                 self.mesh)
        qparams = jax.device_put(qparams, qshard)
        if self._qscore is None:
            scorer = self.scorer
            compute_dtype = scorer.config.dtype

            def _qscore_impl(qp, tokens):
                return scorer._score_impl(
                    dequantize_tree(qp, compute_dtype), tokens)

            def _qnormscore_impl(qp, tokens, mu, sigma):
                return scorer._normscore_impl(
                    dequantize_tree(qp, compute_dtype), tokens, mu, sigma)

            self._qscore = jax.jit(
                _qscore_impl, in_shardings=(qshard, self._batch_sharding))
            self._qnormscore = jax.jit(
                _qnormscore_impl,
                in_shardings=(qshard, self._batch_sharding, None, None))
        self._qparams = qparams

    def clear_quantized(self) -> None:
        """Back to the float path (parity flip, or a fresh candidate swap
        whose requant has not been judged yet)."""
        self._qparams = None

    def _traced(self, fn, *args, bucket: Optional[int] = None):
        """Invoke a jitted fn; on a seq mesh, tracing happens inside
        ring_context so the model's ``attention(impl="ring")`` resolves to
        this mesh. Trace-time only: cached executions skip the context.

        Compiles fired here attribute to the padded batch bucket on the
        mesh backend (engine/device_obs.py); ``expected`` is inherited from
        the caller — the detector's dispatch path marks itself
        unexpected-after-warm-up, its fit/warm-up paths expected."""
        with device_obs.get_ledger().context(bucket=bucket, backend="mesh",
                                             where="sharded"):
            if self._seq_axis is None:
                return fn(*args)
            from ..ops.attention import ring_context

            with ring_context(self.mesh, batch_axis=self._data_axis,
                              axis_name=self._seq_axis):
                return fn(*args)

    def _pad_batch(self, tokens: np.ndarray) -> Tuple[np.ndarray, int]:
        """Pad the batch to a multiple of the data-axis size (and narrow to
        the wire dtype — see __init__)."""
        n = len(tokens)
        dp = self.data_parallelism
        padded = ((n + dp - 1) // dp) * dp
        if padded != n:
            pad = np.zeros((padded - n,) + tokens.shape[1:], tokens.dtype)
            tokens = np.concatenate([tokens, pad])
        return narrow_tokens(tokens, self._vocab_size), n

    def score(self, tokens: np.ndarray) -> np.ndarray:
        tokens, n = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        return np.asarray(self._traced(self._score, self.params, tokens,
                                       bucket=len(tokens)))[:n]

    def warm_bucket(self, tokens: np.ndarray) -> None:
        """Pre-compile the sharded score path for this batch shape and block
        until the executable exists. The detector's adaptive batcher warms
        buckets BEFORE their first dispatch use (adaptive warm-set growth,
        post-retirement resurrection), so the compile attributes as an
        expected ``bucket_warm`` — never an unexpected-recompile page."""
        with device_obs.get_ledger().context(bucket=len(tokens),
                                             backend="mesh",
                                             where="bucket_warm",
                                             expected=True):
            jax.block_until_ready(self.score_device(tokens))

    def score_device(self, tokens: np.ndarray) -> jax.Array:
        """Asynchronous scoring: dispatch and return the device array without
        forcing a host readback (rows beyond the caller's real batch are
        padding — the caller slices). Lets the detector's pipelined hot path
        overlap readback with the next batch's featurization. Routing: the
        int8 quantized path when live, then the bucket's AOT executable,
        then the jit (whose compile the ledger attributes)."""
        tokens, _ = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        if self._qparams is not None:
            return self._traced(self._qscore, self._qparams, tokens,
                                bucket=tokens.shape[0])
        out = self._aot_call("score", tokens.shape[0], self.params, tokens)
        if out is not None:
            return out
        return self._traced(self._score, self.params, tokens,
                            bucket=tokens.shape[0])

    def token_nlls_device(self, tokens: np.ndarray) -> jax.Array:
        """[n, S] → [n_padded, S] per-position NLLs on device."""
        tokens, _ = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        out = self._aot_call("token_nlls", tokens.shape[0],
                             self.params, tokens)
        if out is not None:
            return out
        return self._traced(self._token_nlls, self.params, tokens,
                            bucket=tokens.shape[0])

    def normscore_device(self, tokens: np.ndarray, mu, sigma) -> jax.Array:
        """Per-position-normalized scores (models.logbert.positional_z_max)."""
        tokens, _ = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        if self._qparams is not None:
            return self._traced(self._qnormscore, self._qparams, tokens,
                                mu, sigma, bucket=tokens.shape[0])
        out = self._aot_call("normscore", tokens.shape[0],
                             self.params, tokens, mu, sigma)
        if out is not None:
            return out
        return self._traced(self._normscore, self.params, tokens, mu, sigma,
                            bucket=tokens.shape[0])

    def train_step(self, rng: jax.Array, tokens: np.ndarray) -> float:
        # pad by wrapping real rows, NOT zeros: synthetic all-PAD rows would
        # enter the loss mean and train the model that empty sequences are
        # normal; duplicating real rows only slightly oversamples them
        tokens = np.asarray(tokens)
        n = len(tokens)
        dp = self.data_parallelism
        padded = ((n + dp - 1) // dp) * dp
        if padded != n:
            # modular repetition handles n < padded - n too (e.g. a 3-row
            # final batch on a data=8 mesh); a plain slice would come up
            # short and crash the sharded device_put
            tokens = tokens[np.arange(padded) % n]
        tokens = jax.device_put(narrow_tokens(tokens, self._vocab_size),
                                self._batch_sharding)
        self.params, self.opt_state, loss = self._traced(
            self._train, self.params, self.opt_state, rng, tokens,
            bucket=tokens.shape[0]
        )
        return float(loss)
