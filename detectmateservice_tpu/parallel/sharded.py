"""ShardedScorer: DP×TP execution of a scorer over a device mesh.

Multi-chip scale-out for the detector hot path (SURVEY.md §7 step 6,
BASELINE.json config #5 "8× detector replicas across v5e-8"). Instead of the
reference's N independent processes, one process drives all chips: the batch
is sharded over the ``data`` axis, params are sharded over ``model`` per the
Megatron-style rules (parallel/mesh.py), and ``jit`` + GSPMD insert the ICI
collectives. Training steps psum gradients across ``data`` automatically
(they fall out of jit's partitioning — no hand-written NCCL/MPI analog).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..models.tokenizer import narrow_tokens
from .mesh import (
    AXIS_DATA,
    LOGBERT_RULES,
    REPLICATED_RULES,
    batch_sharding,
    make_mesh,
    tree_shardings,
)


class ShardedScorer:
    """Wraps a scorer (LogBERTScorer / MLPScorer surface) with mesh placement.

    ``score(tokens)`` and ``train_step(rng, tokens)`` own the params/opt-state
    internally (sharded once at construction) so callers just stream batches.
    """

    def __init__(
        self,
        scorer,
        mesh=None,
        rules: Optional[Sequence] = None,
        rng: Optional[jax.Array] = None,
    ):
        self.scorer = scorer
        self.mesh = mesh if mesh is not None else make_mesh()
        if rules is None:
            rules = LOGBERT_RULES if getattr(scorer, "name", "") == "logbert" else REPLICATED_RULES
        # token batches travel in the narrow wire format (uint16 when the
        # vocab fits — models.tokenizer.narrow_tokens has the one rule); the
        # jitted impls cast back to int32 on device
        self._vocab_size = getattr(getattr(scorer, "config", None),
                                   "vocab_size", 1 << 31)
        params, opt_state = scorer.init(rng if rng is not None else jax.random.PRNGKey(0))
        self._param_sharding = tree_shardings(self.mesh, params, rules)
        self._opt_sharding = tree_shardings(self.mesh, opt_state, rules)
        self.params = jax.device_put(params, self._param_sharding)
        self.opt_state = jax.device_put(opt_state, self._opt_sharding)
        self._batch_sharding = batch_sharding(self.mesh, AXIS_DATA)

        self._score = jax.jit(
            scorer._score_impl,
            in_shardings=(self._param_sharding, self._batch_sharding),
        )
        self._token_nlls = jax.jit(
            scorer._token_nlls_impl,
            in_shardings=(self._param_sharding, self._batch_sharding),
        )
        self._normscore = jax.jit(
            scorer._normscore_impl,
            in_shardings=(self._param_sharding, self._batch_sharding, None, None),
        )
        self._train = jax.jit(
            scorer._train_impl,
            in_shardings=(self._param_sharding, self._opt_sharding, None,
                          self._batch_sharding),
            out_shardings=(self._param_sharding, self._opt_sharding, None),
            donate_argnums=(0, 1),
        )

    @property
    def data_parallelism(self) -> int:
        return int(self.mesh.shape.get(AXIS_DATA, 1))

    def _pad_batch(self, tokens: np.ndarray) -> Tuple[np.ndarray, int]:
        """Pad the batch to a multiple of the data-axis size (and narrow to
        the wire dtype — see __init__)."""
        n = len(tokens)
        dp = self.data_parallelism
        padded = ((n + dp - 1) // dp) * dp
        if padded != n:
            pad = np.zeros((padded - n,) + tokens.shape[1:], tokens.dtype)
            tokens = np.concatenate([tokens, pad])
        return narrow_tokens(tokens, self._vocab_size), n

    def score(self, tokens: np.ndarray) -> np.ndarray:
        tokens, n = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        return np.asarray(self._score(self.params, tokens))[:n]

    def score_device(self, tokens: np.ndarray) -> jax.Array:
        """Asynchronous scoring: dispatch and return the device array without
        forcing a host readback (rows beyond the caller's real batch are
        padding — the caller slices). Lets the detector's pipelined hot path
        overlap readback with the next batch's featurization."""
        tokens, _ = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        return self._score(self.params, tokens)

    def token_nlls_device(self, tokens: np.ndarray) -> jax.Array:
        """[n, S] → [n_padded, S] per-position NLLs on device."""
        tokens, _ = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        return self._token_nlls(self.params, tokens)

    def normscore_device(self, tokens: np.ndarray, mu, sigma) -> jax.Array:
        """Per-position-normalized scores (models.logbert.positional_z_max)."""
        tokens, _ = self._pad_batch(np.asarray(tokens))
        tokens = jax.device_put(tokens, self._batch_sharding)
        return self._normscore(self.params, tokens, mu, sigma)

    def train_step(self, rng: jax.Array, tokens: np.ndarray) -> float:
        # pad by wrapping real rows, NOT zeros: synthetic all-PAD rows would
        # enter the loss mean and train the model that empty sequences are
        # normal; duplicating real rows only slightly oversamples them
        tokens = np.asarray(tokens)
        n = len(tokens)
        dp = self.data_parallelism
        padded = ((n + dp - 1) // dp) * dp
        if padded != n:
            # modular repetition handles n < padded - n too (e.g. a 3-row
            # final batch on a data=8 mesh); a plain slice would come up
            # short and crash the sharded device_put
            tokens = tokens[np.arange(padded) % n]
        tokens = jax.device_put(narrow_tokens(tokens, self._vocab_size),
                                self._batch_sharding)
        self.params, self.opt_state, loss = self._train(
            self.params, self.opt_state, rng, tokens
        )
        return float(loss)
