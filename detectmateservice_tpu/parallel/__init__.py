"""Chip-plane parallelism layer (mesh, sharded execution, ring attention,
multi-host bootstrap).

Exports resolve lazily (PEP 562): ``mesh``/``sharded``/``ring`` import jax at
module level, but ``distributed`` is deliberately importless until a
coordinator is configured — non-jax pipeline stages (parsers, output writers)
read ``process_info`` through this package on every /admin/status call and
must not pay a jax import for it.
"""
from typing import TYPE_CHECKING

_EXPORTS = {
    "AXIS_DATA": "mesh",
    "AXIS_MODEL": "mesh",
    "AXIS_SEQ": "mesh",
    "LOGBERT_RULES": "mesh",
    "REPLICATED_RULES": "mesh",
    "batch_sharding": "mesh",
    "make_mesh": "mesh",
    "tree_shardings": "mesh",
    "initialize_from_settings": "distributed",
    "process_info": "distributed",
    "ring_attention": "ring",
    "ShardedScorer": "sharded",
}

__all__ = list(_EXPORTS)

if TYPE_CHECKING:  # static analyzers see the real symbols
    from .distributed import initialize_from_settings, process_info  # noqa: F401
    from .mesh import (  # noqa: F401
        AXIS_DATA,
        AXIS_MODEL,
        AXIS_SEQ,
        LOGBERT_RULES,
        REPLICATED_RULES,
        batch_sharding,
        make_mesh,
        tree_shardings,
    )
    from .ring import ring_attention  # noqa: F401
    from .sharded import ShardedScorer  # noqa: F401


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(f".{module}", __name__), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value
