from .mesh import (
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_SEQ,
    LOGBERT_RULES,
    REPLICATED_RULES,
    batch_sharding,
    make_mesh,
    tree_shardings,
)
from .distributed import initialize_from_settings, process_info
from .ring import ring_attention
from .sharded import ShardedScorer

__all__ = [
    "AXIS_DATA", "AXIS_MODEL", "AXIS_SEQ",
    "LOGBERT_RULES", "REPLICATED_RULES",
    "batch_sharding", "make_mesh", "tree_shardings",
    "ring_attention", "ShardedScorer",
    "initialize_from_settings", "process_info",
]
