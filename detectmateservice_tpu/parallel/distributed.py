"""Multi-host bootstrap: the DCN half of the two-plane comm design.

SURVEY §5.8 splits distribution into planes: the *service plane* (this
framework's pair sockets over ipc/tcp — the reference's NNG role) and the
*chip plane* (XLA collectives). Within one host the chip plane is free; to
span HOSTS the way the reference's deployment scales containers, JAX needs
its distributed runtime initialized so every process contributes its local
devices to one global mesh and XLA routes collectives over ICI within a pod
and DCN across pods — the role NCCL/MPI bootstrap plays in GPU stacks,
with zero hand-written collectives here.

Wireup: service settings carry the coordinator address and process
coordinates. The ``DETECTMATE_COORDINATOR_ADDRESS`` /
``DETECTMATE_NUM_PROCESSES`` / ``DETECTMATE_PROCESS_ID`` env vars reach the
same fields through the settings env layer (they are named exactly after
the fields — an env name the settings model does not know would be
REJECTED by ``extra="forbid"`` and crash every stage at startup), and are
also honored here directly for programmatic ``ServiceSettings`` that left
the fields unset. The scorer's ``mesh_shape`` then simply sees
``jax.devices()`` spanning all hosts. ``initialize_from_settings`` is
idempotent and a no-op when no coordinator is configured (single-host: the
common case, and the only one testable in this environment — multi-host
needs actual multiple hosts, so the seam is kept thin and std-jax so it
carries no untested custom protocol).
"""
from __future__ import annotations

import logging
import os
from typing import Any, Optional

_initialized = False


def initialize_from_settings(settings: Optional[Any] = None,
                             logger: Optional[logging.Logger] = None) -> bool:
    """Initialize ``jax.distributed`` from settings/env; returns whether the
    distributed runtime is (now) live. Safe to call multiple times.

    The source of the coordinator decides the source of the process
    coordinates: a settings-borne coordinator uses the settings'
    num_processes/process_id; an env-borne coordinator uses the env's
    (num_processes/process_id default to 1/0 in the model, so they cannot
    signal "unset" on their own).
    """
    global _initialized
    logger = logger or logging.getLogger(__name__)
    if _initialized:
        return True

    coordinator = (getattr(settings, "coordinator_address", None)
                   if settings is not None else None)
    if coordinator:
        num_processes = int(getattr(settings, "num_processes", 1) or 1)
        process_id = int(getattr(settings, "process_id", 0) or 0)
    else:
        coordinator = os.environ.get("DETECTMATE_COORDINATOR_ADDRESS") or None
        if coordinator is None:
            return False  # single-host deployment: nothing to do
        num_processes = int(os.environ.get("DETECTMATE_NUM_PROCESSES") or 1)
        process_id = int(os.environ.get("DETECTMATE_PROCESS_ID") or 0)

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "jax.distributed initialized: process %d/%d via %s — %d global / %d "
        "local devices", process_id, num_processes, coordinator,
        len(jax.devices()), len(jax.local_devices()))
    return True


def process_info() -> dict:
    """Report for /admin/status: this process's place in the global mesh.
    Importless when the runtime was never initialized — non-jax stages must
    not pay a jax import for a dict of constants."""
    if not _initialized:
        return {"initialized": False, "process_index": 0,
                "process_count": 1, "local_devices": None}
    import jax

    return {
        "initialized": True,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
    }
