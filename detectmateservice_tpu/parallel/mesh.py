"""Device-mesh construction and parameter-sharding rules.

The reference's only distribution mechanism is process-level scaling over
sockets (SURVEY.md §2.10); the TPU build's chip plane is a
``jax.sharding.Mesh`` with XLA collectives over ICI. Axes:

* ``data``  — batch (replica) parallelism for the scorer hot path,
* ``model`` — tensor parallelism for scorers that outgrow one chip,
* ``seq``   — sequence/context parallelism (ring attention, parallel/ring.py).

Everything goes through ``NamedSharding``/``PartitionSpec`` + ``jit`` so XLA
inserts the collectives (psum/all-gather/reduce-scatter) — never hand-rolled
point-to-point like the reference's NNG plane.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"


def make_mesh(shape: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a mesh; default = all devices on the ``data`` axis."""
    devices = list(devices if devices is not None else jax.devices())
    if not shape:
        shape = {AXIS_DATA: len(devices)}
    names = tuple(shape.keys())
    dims = tuple(shape.values())
    total = int(np.prod(dims))
    if total != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {total} devices, have {len(devices)}"
        )
    return Mesh(np.asarray(devices).reshape(dims), names)


# -- parameter partition rules ---------------------------------------------
# (path regex, PartitionSpec); first match wins. Megatron-style TP for the
# transformer: qkv/mlp_in shard the output feature dim, proj/mlp_out shard the
# input feature dim so XLA inserts one psum per block.
LOGBERT_RULES: List[Tuple[str, P]] = [
    (r"tok_embed/embedding$", P(None, AXIS_MODEL)),
    (r"pos_embed$", P()),
    (r"(qkv|mlp_in)/kernel$", P(None, AXIS_MODEL)),
    (r"(qkv|mlp_in)/bias$", P(AXIS_MODEL)),
    (r"(proj|mlp_out)/kernel$", P(AXIS_MODEL, None)),
    (r"(proj|mlp_out)/bias$", P()),
    (r".*", P()),
]

REPLICATED_RULES: List[Tuple[str, P]] = [(r".*", P())]


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def partition_spec_for(path: str, rules: Sequence[Tuple[str, P]]) -> P:
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return P()


def tree_shardings(mesh: Mesh, tree: Any,
                   rules: Sequence[Tuple[str, P]]) -> Any:
    """Map a param pytree to NamedShardings via the rule table. Axes that do
    not divide the param dim fall back to replication (safe default)."""

    def _one(path, leaf):
        spec = partition_spec_for(_path_str(path), rules)
        # replicate rather than crash when a rule references a mesh axis this
        # mesh doesn't have (e.g. LOGBERT_RULES on a data×seq mesh with no
        # 'model' axis) or when the axis doesn't divide the param dim
        if hasattr(leaf, "shape"):
            for dim, axis in zip(leaf.shape, tuple(spec) + (None,) * 8):
                if axis is None:
                    continue
                if axis not in mesh.shape or dim % mesh.shape[axis] != 0:
                    spec = P()
                    break
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(_one, tree)


def batch_sharding(mesh: Mesh, axis: str = AXIS_DATA) -> NamedSharding:
    """Leading-dim batch sharding for activations/inputs."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
