"""Ring attention: sequence-parallel exact attention over a device mesh.

First-class long-context support (task requirement; the reference has no
attention at all, SURVEY.md §5.7). Each device holds a ``[B, H, S/n, D]``
shard of the sequence; key/value shards rotate around the ring with
``lax.ppermute`` while every device folds each arriving block into a
streaming-softmax accumulator (ops/attention.blockwise_attention_step). After
``n`` hops every query shard has attended to the full sequence — exact
attention, O(S/n) memory per device, and the permute traffic rides ICI
neighbor links.

Run under ``shard_map`` over the ``seq`` axis of a mesh (tests use the
8-device virtual CPU mesh).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import blockwise_attention_step

# jax moved shard_map out of experimental (~0.6) and added the vma/pcast
# check (~0.8); support this image's 0.4.x AND current jax. On old jax the
# carry-type vma annotation does not exist and is not needed — _pcast
# degrades to identity there.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - version-dependent import path
    from jax.experimental.shard_map import shard_map as _shard_map

_pcast = getattr(jax.lax, "pcast", None)
if _pcast is None:  # pragma: no cover - version-dependent
    _pcast = lambda t, axes, to: t  # noqa: E731 — identity on pre-vma jax


def _ring_attention_shard(q, k, v, kv_valid, axis_name: str,
                          vary_axes: tuple = (), n: int = 1):
    """Per-device body. q/k/v: [B, H, Sl, D] local shards; kv_valid: [B, Sl]
    bool validity (PAD masking) for the local key shard. ``n`` is the ring
    size (the mesh axis size — static, passed by ring_attention, since
    ``jax.lax.axis_size`` only exists on newer jax).

    The hop loop is ``lax.scan`` (not fori_loop) so the whole ring is
    reverse-mode differentiable — ppermute's transpose is the inverted
    permutation — which is what lets the flagship *training* step run under a
    sequence-parallel mesh, not just inference."""
    b, h, s_local, d = q.shape

    # mark the accumulators as device-varying over every manually-mapped
    # mesh axis (ring axis + optional batch axis) so the scan carry type
    # matches (jax >= 0.8 shard_map vma check)
    vary = lambda t: _pcast(t, vary_axes or (axis_name,), to="varying")
    acc = vary(jnp.zeros((b, h, s_local, d), jnp.float32))
    row_max = vary(jnp.full((b, h, s_local), jnp.finfo(jnp.float32).min, jnp.float32))
    row_sum = vary(jnp.zeros((b, h, s_local), jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, _):
        acc, row_max, row_sum, k_blk, v_blk, valid_blk = carry
        mask = jnp.broadcast_to(valid_blk[:, None, None, :], (b, h, s_local, s_local))
        acc, row_max, row_sum = blockwise_attention_step(
            q, k_blk, v_blk, acc, row_max, row_sum, mask
        )
        # rotate kv one hop around the ring (neighbor ICI traffic)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        valid_blk = jax.lax.ppermute(valid_blk, axis_name, perm)
        return (acc, row_max, row_sum, k_blk, v_blk, valid_blk), None

    (acc, row_max, row_sum, *_), _ = jax.lax.scan(
        body, (acc, row_max, row_sum, k, v, kv_valid), None, length=n
    )
    return (acc / jnp.maximum(row_sum[..., None], 1e-30)).astype(q.dtype)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    mesh: Mesh,
    kv_valid: Optional[jax.Array] = None,
    axis_name: str = "seq",
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Exact attention with q/k/v sharded on the sequence dim of ``mesh``.

    q/k/v: [B, H, S, D] global; S must divide by mesh.shape[axis_name].
    kv_valid: optional [B, S] bool (False = PAD key, excluded everywhere).
    ``batch_axis`` names a mesh axis to shard the batch dim over as well
    (dp×sp: each data-replica row runs its own independent ring).
    """
    if kv_valid is None:
        kv_valid = jnp.ones((q.shape[0], q.shape[2]), dtype=bool)
    spec_qkv = P(batch_axis, None, axis_name, None)
    spec_valid = P(batch_axis, axis_name)
    vary_axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
    fn = _shard_map(
        partial(_ring_attention_shard, axis_name=axis_name,
                vary_axes=vary_axes, n=int(mesh.shape[axis_name])),
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_valid),
        out_specs=spec_qkv,
    )
    return fn(q, k, v, kv_valid)
