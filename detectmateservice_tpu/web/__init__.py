from .server import WebServer

__all__ = ["WebServer"]
