"""Admin route table: every HTTP route the admin plane serves, declared once.

``web/server.py`` dispatches requests through :data:`ROUTES` — there is no
second place a route can be added, so the table is the single source of
truth for the admin API surface. dmlint's cross-artifact contract DM-C007/8
(analysis/contracts.py) parses the ``Route(...)`` declarations below and
holds them in sync with the route table in ``docs/usage.md`` in both
directions: an undocumented route and a documented-but-phantom route both
fail the gate. The thread-affinity analyzer (DM-A) also parses this table:
every handler named in ROUTES is an ``admin``-domain thread entry point,
so a handler reaching an engine-owned seam (a replica socket, the WAL
spool write path) is a build-breaking finding — the state-mutating POST
handlers additionally carry explicit ``# dmlint: thread(admin)`` pragmas.

Handlers take ``(service, query, payload)`` — ``query`` is the parsed query
string (``parse_qs`` shape), ``payload`` the decoded JSON body (``{}`` for
an empty body; GET handlers receive ``None``) — and return a
:class:`Response`. Exceptions surface as HTTP 500 with a JSON detail;
``ValueError`` as HTTP 400 (client error semantics for bad parameters).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from prometheus_client import CONTENT_TYPE_LATEST, generate_latest


@dataclass(frozen=True)
class Response:
    status: int
    body: Any                        # dict/list → JSON; bytes → raw
    content_type: str = "application/json"
    # run AFTER the reply hits the wire (e.g. shutdown must answer first)
    after: Optional[Callable[[], None]] = None


@dataclass(frozen=True)
class Route:
    method: str
    path: str
    handler: Callable[..., Response]
    doc: str


def _int_param(query: Dict[str, List[str]], name: str,
               default: Optional[int] = None) -> Optional[int]:
    raw = (query.get(name) or [None])[0]
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer") from None


def _float_param(query: Dict[str, List[str]], name: str,
                 default: Optional[float] = None) -> Optional[float]:
    raw = (query.get(name) or [None])[0]
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number") from None


# -- GET handlers -----------------------------------------------------------
def _metrics(service, query, payload) -> Response:
    fmt = (query.get("format") or ["prometheus"])[0]
    if fmt == "openmetrics":
        # OpenMetrics exposition carries the exemplars (trace ids on the
        # e2e/queue-wait histogram buckets, dmtel); the handler contract
        # has no request headers, so the format is a query param instead
        # of Accept-negotiation
        from prometheus_client import REGISTRY
        from prometheus_client.openmetrics import exposition as om

        return Response(200, om.generate_latest(REGISTRY), om.CONTENT_TYPE_LATEST)
    if fmt != "prometheus":
        return Response(400, {"detail": f"unknown format {fmt!r}"})
    return Response(200, generate_latest(), CONTENT_TYPE_LATEST)


def _status(service, query, payload) -> Response:
    return Response(200, service._create_status_report())


def _health(service, query, payload) -> Response:
    deep = (query.get("deep") or ["0"])[0] not in ("", "0", "false")
    monitor = getattr(service, "health", None)
    if monitor is None:
        return Response(200, {"state": "unknown",
                              "detail": "no health monitor"})
    if deep:
        # fresh evaluation with per-check detail; non-200 on anything short
        # of healthy so orchestration healthchecks (docker-compose/k8s) can
        # gate on it directly
        report = monitor.evaluate()
        return Response(200 if report["state"] == "healthy" else 503, report)
    # cheap liveness: the watchdog's last roll-up, no evaluation on the
    # request path; degraded stays 200 (restarting a merely-degraded
    # container makes it worse)
    state = monitor.state
    return Response(503 if state == "unhealthy" else 200, {"state": state})


def _events(service, query, payload) -> Response:
    events = getattr(service, "events", None)
    if events is None:
        return Response(404, {"detail": "service has no event log"})
    limit = _int_param(query, "limit", default=-1)
    return Response(200, events.snapshot(limit if limit >= 0 else None))


def _trace(service, query, payload) -> Response:
    fmt = (query.get("format") or ["json"])[0]
    recorder = getattr(service.engine, "trace_recorder", None)
    if recorder is None:
        return Response(404, {"detail": "engine has no flight recorder"})
    if fmt == "chrome":
        # the pipeline view: on the collector stage this serves the
        # CROSS-STAGE Perfetto export (assembled traces, every hop of every
        # stage); elsewhere only the local recorder exists, and the local
        # view says so instead of masquerading as the pipeline
        collector = getattr(service, "telemetry", None)
        if collector is not None:
            return Response(200, collector.perfetto_events())
        doc = recorder.chrome_events()
        doc["localOnly"] = True  # hops of THIS process only (walkthrough.md)
        return Response(200, doc)
    if fmt == "json":
        body = recorder.snapshot()
        body["tracing_enabled"] = bool(
            getattr(service.settings, "engine_trace", False))
        return Response(200, body)
    return Response(400, {"detail": f"unknown format {fmt!r}"})


def _traces(service, query, payload) -> Response:
    collector = getattr(service, "telemetry", None)
    if collector is None:
        return Response(404, {"detail": "this stage runs no telemetry "
                                        "collector (telemetry_collector "
                                        "not set)"})
    trace_id = (query.get("id") or [None])[0]
    if trace_id is not None:
        trace = collector.trace(trace_id)
        if trace is None:
            return Response(404, {"detail": f"trace {trace_id!r} is not in "
                                            "the retained ring (sampled "
                                            "out, expired, or never seen)"})
        return Response(200, trace)
    fmt = (query.get("format") or ["json"])[0]
    if fmt == "perfetto":
        return Response(200, collector.perfetto_events())
    if fmt == "otlp":
        return Response(200, collector.otlp_payload())
    if fmt == "json":
        return Response(200, collector.snapshot(
            _int_param(query, "limit", default=None)))
    return Response(400, {"detail": f"unknown format {fmt!r}"})


def _xla(service, query, payload) -> Response:
    from ..engine import device_obs

    limit = _int_param(query, "limit", default=-1)
    snapshot = device_obs.get_ledger().snapshot(
        limit if limit is not None and limit >= 0 else None)
    return Response(200, snapshot)


def _replicas(service, query, payload) -> Response:
    router = getattr(service.engine, "router", None)
    if router is None:
        return Response(404, {"detail": "this stage is not a replica "
                                        "router (router_replicas not set)"})
    return Response(200, router.snapshot())


def _model(service, query, payload) -> Response:
    rollout = getattr(service, "rollout", None)
    if rollout is None:
        return Response(404, {"detail": "model lifecycle is not enabled on "
                                        "this stage (rollout_enabled)"})
    if (query.get("history") or ["0"])[0] not in ("", "0", "false"):
        limit = _int_param(query, "limit", default=0) or None
        return Response(200, rollout.history(limit))
    return Response(200, rollout.status())


def _drift(service, query, payload) -> Response:
    drift = getattr(service, "drift", None)
    if drift is None:
        return Response(404, {"detail": "drift monitoring is not enabled "
                                        "on this stage (drift_enabled)"})
    return Response(200, drift.status())


def _slo(service, query, payload) -> Response:
    tracker = getattr(service, "slo", None)
    if tracker is None:
        return Response(404, {"detail": "service has no SLO tracker"})
    body = tracker.snapshot()
    capacity = getattr(service, "capacity", None)
    # the capacity model rides along: burn says how fast the budget goes,
    # headroom says whether more traffic would make it worse
    body["capacity"] = capacity.status() if capacity is not None else None
    return Response(200, body)


def _load_status(service, query, payload) -> Response:
    from ..loadgen.generator import LOADGEN

    return Response(200, LOADGEN.status())


def _replay_status(service, query, payload) -> Response:
    from ..wal.replay import REPLAY

    status = REPLAY.status()
    spool = getattr(service.engine, "spool", None)
    status["spool"] = spool.stats() if spool is not None else None
    status["wal_dir"] = getattr(service.settings, "wal_dir", None)
    return Response(200, status)


def _tenants(service, query, payload) -> Response:
    admission = getattr(service, "admission", None)
    if admission is None:
        return Response(404, {"detail": "admission control is not enabled "
                                        "on this stage (shed_enabled)"})
    limit = _int_param(query, "limit", default=64)
    return Response(200, admission.snapshot(limit=limit))


def _profile_status(service, query, payload) -> Response:
    from ..utils.profiling import PROFILER

    status = PROFILER.status()
    status["profile_dir"] = (service.settings.profile_dir
                             or PROFILER.default_dir())
    return Response(200, status)


def _profile_latest(service, query, payload) -> Response:
    from ..utils.profiling import PROFILER

    base_dir = service.settings.profile_dir or PROFILER.default_dir()
    if PROFILER.status()["running"]:
        return Response(409, {"detail": "capture still running; retry when "
                                        "GET /admin/profile reports done"})
    archive = PROFILER.zip_latest(base_dir)
    if archive is None:
        return Response(404, {"detail": f"no completed capture under "
                                        f"{base_dir}"})
    _name, data = archive
    return Response(200, data, content_type="application/zip")


# -- POST handlers ----------------------------------------------------------
# dmlint: thread(admin)
def _start(service, query, payload) -> Response:
    return Response(200, {"detail": service.start()})


# dmlint: thread(admin)
def _stop(service, query, payload) -> Response:
    service.stop()
    return Response(200, {"detail": "engine stopped"})


# dmlint: thread(admin)
def _shutdown(service, query, payload) -> Response:
    # the reply must leave before run() unparks and tears the server down
    return Response(200, {"detail": "service shutting down"},
                    after=service.shutdown)


# dmlint: thread(admin)
def _reconfigure(service, query, payload) -> Response:
    config = (payload or {}).get("config") or {}
    persist = bool((payload or {}).get("persist", False))
    updated = service.reconfigure(config, persist=persist)
    return Response(200, {"detail": "reconfigured", "config": updated})


# dmlint: thread(admin)
def _checkpoint(service, query, payload) -> Response:
    return Response(200, service.checkpoint())


# dmlint: thread(admin)
def _profile_start(service, query, payload) -> Response:
    from ..utils.profiling import PROFILER, ProfileBusyError

    payload = payload or {}
    seconds = _float_param(query, "seconds")
    if seconds is None:
        seconds = payload.get("seconds")
    if seconds is None:
        # legacy body shape from the pre-ledger profile endpoint
        seconds = float(payload.get("duration_ms", 1000)) / 1000.0
    base_dir = (payload.get("out_dir") or service.settings.profile_dir
                or PROFILER.default_dir())
    try:
        info = PROFILER.start(base_dir, float(seconds),
                              service.settings.profile_max_captures)
    except ProfileBusyError as exc:
        return Response(409, {"detail": str(exc)})
    info["detail"] = "capture started"
    return Response(200, info)


# dmlint: thread(admin)
def _load_control(service, query, payload) -> Response:
    from ..loadgen.generator import (
        LOADGEN,
        LoadBusyError,
        LoadIdleError,
        LoadProfile,
    )

    payload = payload or {}
    action = str(payload.get("action", "start"))
    try:
        if action == "stop":
            return Response(200, LOADGEN.stop())
        if action != "start":
            raise ValueError(f"unknown action {action!r} "
                             "(expected 'start' or 'stop')")
        profile = LoadProfile.from_payload(payload)
        labels = dict(
            component_type=service.settings.component_type,
            component_id=service.settings.component_id or "loadgen")
        return Response(200, LOADGEN.start(profile, labels=labels))
    except (LoadBusyError, LoadIdleError) as exc:
        # one run per process; a second start (or a stop with nothing
        # running) is a state conflict, same semantics as /admin/profile
        return Response(409, {"detail": str(exc)})


# dmlint: thread(admin)
def _model_control(service, query, payload) -> Response:
    from ..rollout import RolloutError, StoreError

    rollout = getattr(service, "rollout", None)
    if rollout is None:
        return Response(404, {"detail": "model lifecycle is not enabled on "
                                        "this stage (rollout_enabled)"})
    payload = payload or {}
    action = str(payload.get("action", ""))
    version = payload.get("version")
    if version is not None:
        try:
            version = int(version)
        except (TypeError, ValueError):
            raise ValueError("version must be an integer") from None
    try:
        if action == "promote":
            return Response(200, rollout.promote(version))
        if action == "rollback":
            return Response(200, rollout.rollback())
        if action == "pin":
            return Response(200, rollout.pin(version))
        if action == "unpin":
            return Response(200, rollout.unpin())
        if action == "cycle":
            block = bool(payload.get("block", False))
            return Response(200, rollout.run_cycle(reason="operator",
                                                   block=block))
    except (RolloutError, StoreError) as exc:
        # state conflicts (nothing shadowing, unknown version, nothing to
        # roll back to) are client errors, not server faults
        raise ValueError(str(exc)) from exc
    raise ValueError(f"unknown action {action!r} (expected 'promote', "
                     "'rollback', 'pin', 'unpin', or 'cycle')")


# dmlint: thread(admin)
def _replay_control(service, query, payload) -> Response:
    from ..wal.replay import ReplayBusyError, ReplayError, start_service_replay

    try:
        return Response(200, start_service_replay(service, payload or {}))
    except ReplayError as exc:
        raise ValueError(str(exc)) from exc          # HTTP 400
    except ReplayBusyError as exc:
        # one replay per process, and pipeline mode must not interleave
        # with a running engine — state conflicts, same semantics as
        # /admin/profile and /admin/load
        return Response(409, {"detail": str(exc)})


# dmlint: thread(admin)
def _replicas_control(service, query, payload) -> Response:
    router = getattr(service.engine, "router", None)
    if router is None:
        return Response(404, {"detail": "this stage is not a replica "
                                        "router (router_replicas not set)"})
    payload = payload or {}
    action = str(payload.get("action", ""))
    addr = payload.get("replica")
    if action not in ("drain", "undrain"):
        raise ValueError(f"unknown action {action!r} "
                         "(expected 'drain' or 'undrain')")
    if not addr:
        raise ValueError("replica (the configured replica address) "
                         "is required")
    # ValueError from an unknown address surfaces as HTTP 400 with the
    # configured address list in the detail — the router raises it
    verb = router.drain if action == "drain" else router.undrain
    return Response(200, {"detail": f"{action} applied",
                          "replica": verb(str(addr))})


def _faults_status(service, query, payload) -> Response:
    from .. import faults

    inj = faults.active()
    if inj is None:
        return Response(200, {"armed": False})
    tail = _int_param(query, "tail", default=100) or 0
    return Response(200, inj.snapshot(fired_tail=tail))


# dmlint: thread(admin)
def _faults_control(service, query, payload) -> Response:
    from .. import faults
    from ..faults import FaultPlan, FaultPlanError

    payload = payload or {}
    action = str(payload.get("action", ""))
    if action == "disarm":
        previous = faults.disarm()
        body = {"detail": "disarmed", "armed": False}
        if previous is not None:
            # the final fired log, so a chaos driver can collect its
            # schedule artifact in the same call that ends the run
            body["final"] = previous.snapshot(fired_tail=0)
            body["final"]["armed"] = False
            body["fired_schedule"] = previous.fired_schedule()
        return Response(200, body)
    if action != "arm":
        raise ValueError(f"unknown action {action!r} "
                         "(expected 'arm' or 'disarm')")
    try:
        plan = FaultPlan.from_dict(payload.get("plan") or {})
    except FaultPlanError as exc:
        raise ValueError(str(exc)) from exc
    inj = faults.arm(plan, labels=dict(service._labels),
                     events=service.health.emit_event,
                     logger=service.logger)
    service.health.emit_event({
        "kind": "faults_armed", "seed": plan.seed,
        "specs": len(plan.specs), "source": "admin",
    })
    return Response(200, inj.snapshot(fired_tail=0))


def _dlq_status(service, query, payload) -> Response:
    dlq = getattr(service.engine, "dlq", None)
    if dlq is None:
        return Response(404, {"detail": "this stage has no dead-letter "
                                        "queue (engine not built)"})
    limit = _int_param(query, "limit", default=64) or 0
    return Response(200, dlq.snapshot(limit=limit))


# dmlint: thread(admin)
def _dlq_control(service, query, payload) -> Response:
    dlq = getattr(service.engine, "dlq", None)
    if dlq is None:
        return Response(404, {"detail": "this stage has no dead-letter "
                                        "queue (engine not built)"})
    payload = payload or {}
    action = str(payload.get("action", ""))
    entry_id = payload.get("id")
    if entry_id is not None:
        try:
            entry_id = int(entry_id)
        except (TypeError, ValueError):
            raise ValueError("id must be an integer DLQ entry id") from None
    if action == "purge":
        purged = dlq.purge(entry_id)
        return Response(200, {"detail": "purged", "purged": purged,
                              "depth_frames": int(dlq.depth_frames())})
    if action == "requeue":
        # at-most-once: once handed to the engine's requeue deque the
        # frames are no longer the DLQ's to protect
        taken = dlq.requeue(entry_id)
        queued = service.engine.requeue_frames(
            [frame for _id, frame in taken])
        return Response(200, {"detail": "requeued", "requeued": queued,
                              "ids": [i for i, _frame in taken],
                              "depth_frames": int(dlq.depth_frames())})
    raise ValueError(f"unknown action {action!r} "
                     "(expected 'requeue' or 'purge')")


# one row per route; dmlint DM-C007/8 keeps this table and the route table
# in docs/usage.md synchronized in both directions
ROUTES: Tuple[Route, ...] = (
    Route("GET", "/metrics", _metrics, "Prometheus exposition"),
    Route("GET", "/admin/status", _status, "status report"),
    Route("GET", "/admin/health", _health, "liveness / deep health"),
    Route("GET", "/admin/events", _events, "structured event ring"),
    Route("GET", "/admin/trace", _trace, "pipeline flight recorder"),
    Route("GET", "/admin/traces", _traces,
          "telemetry collector: assembled cross-stage traces "
          "(?id=<hex> for one, ?format=perfetto|otlp for exports)"),
    Route("GET", "/admin/xla", _xla,
          "XLA compile ledger + device-batch spans"),
    Route("GET", "/admin/profile", _profile_status,
          "profiler capture status"),
    Route("GET", "/admin/load", _load_status,
          "live SLO scorecard of the open-loop load run"),
    Route("GET", "/admin/profile/latest", _profile_latest,
          "download the newest completed capture as a zip"),
    Route("GET", "/admin/replicas", _replicas,
          "replica-router roll-up: per-replica state/backlog/inflight"),
    Route("GET", "/admin/model", _model,
          "model lifecycle status (?history=1 for the checkpoint log)"),
    Route("GET", "/admin/replay", _replay_status,
          "WAL replay status + the live ingress spool's stats"),
    Route("GET", "/admin/faults", _faults_status,
          "fault-injection status: armed plan, op counters, fired log"),
    Route("GET", "/admin/dlq", _dlq_status,
          "dead-letter queue: quarantined poison frames + totals"),
    Route("GET", "/admin/drift", _drift,
          "drift monitor snapshot: live-vs-baseline stats, hysteresis "
          "state, top drifting features"),
    Route("GET", "/admin/slo", _slo,
          "multi-window SLO burn rates, per-stage dwell attribution, and "
          "the capacity model"),
    Route("GET", "/admin/tenants", _tenants,
          "admission control: per-tier/per-tenant admitted+shed counters "
          "and the current degradation-ladder state"),
    Route("POST", "/admin/start", _start, "start the engine"),
    Route("POST", "/admin/stop", _stop, "stop the engine"),
    Route("POST", "/admin/shutdown", _shutdown, "shut the service down"),
    Route("POST", "/admin/reconfigure", _reconfigure,
          "validate + apply component config"),
    Route("POST", "/admin/checkpoint", _checkpoint,
          "checkpoint component state"),
    Route("POST", "/admin/profile", _profile_start,
          "start an on-demand jax.profiler capture"),
    Route("POST", "/admin/load", _load_control,
          "start/stop an open-loop load run against a pipeline"),
    Route("POST", "/admin/replicas", _replicas_control,
          "operator drain/undrain of one replica"),
    Route("POST", "/admin/model", _model_control,
          "model lifecycle verbs: promote/rollback/pin/unpin/cycle"),
    Route("POST", "/admin/faults", _faults_control,
          "arm a seeded fault plan or disarm the active one"),
    Route("POST", "/admin/dlq", _dlq_control,
          "requeue or purge quarantined frames (one id or all)"),
    Route("POST", "/admin/replay", _replay_control,
          "replay a recorded WAL spool: pipeline re-drive or offline "
          "shadow-scoring of a dmroll candidate"),
)


def route_table() -> Dict[Tuple[str, str], Route]:
    table: Dict[Tuple[str, str], Route] = {}
    for route in ROUTES:
        key = (route.method, route.path)
        if key in table:
            raise ValueError(f"duplicate route {key}")
        table[key] = route
    return table
