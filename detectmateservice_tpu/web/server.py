"""Admin plane: HTTP server exposing lifecycle verbs + Prometheus metrics.

Route parity with the reference (reference:
src/service/features/web/router.py:18-46, server.py:22-27):

* ``POST /admin/start`` / ``POST /admin/stop`` / ``POST /admin/shutdown``
* ``GET  /admin/status``
* ``POST /admin/reconfigure`` with JSON ``{"config": {...}, "persist": bool}``
* ``GET  /metrics`` → ``prometheus_client.generate_latest()``

The reference runs FastAPI/uvicorn on a thread with signal handlers disabled
(reference: server.py:40-42); this environment has neither, so the server is a
stdlib ``ThreadingHTTPServer`` on a daemon thread — same observable surface,
zero extra dependencies. The TPU build adds ``POST /admin/profile`` to capture
a jax.profiler trace, ``GET /admin/trace`` to read the engine's pipeline
flight recorder — ``?format=chrome`` returns a Perfetto/chrome://tracing
loadable trace-event document (closes the tracing gap noted in SURVEY.md
§5.1 at both the device and the pipeline layer) — plus the self-diagnosis
surface (engine/health.py): ``GET /admin/health`` (cheap liveness; ``?deep=1``
runs the checks and returns non-200 with per-check detail on degradation,
the docker-compose/k8s healthcheck target) and ``GET /admin/events`` (the
bounded structured-event ring: health transitions, thread exceptions,
WARNING+ log records).
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from prometheus_client import CONTENT_TYPE_LATEST, generate_latest


class WebServer:
    def __init__(self, service) -> None:
        self.service = service
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    @property
    def port(self) -> int:
        """Actual bound port (useful when settings request port 0)."""
        with self._lock:
            if self._httpd is not None:
                return self._httpd.server_address[1]
        return self.service.settings.http_port

    def start(self) -> None:
        with self._lock:
            if self._httpd is not None:
                return
            handler = _make_handler(self.service)
            self._httpd = ThreadingHTTPServer(
                (self.service.settings.http_host, self.service.settings.http_port),
                handler,
            )
            self._httpd.daemon_threads = True
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="WebServerThread",
                daemon=True,
                kwargs={"poll_interval": 0.1},
            )
            self._thread.start()

    def stop(self) -> None:
        # swap the references out under the lock, block outside it:
        # shutdown() waits for serve_forever's poll loop and join() for the
        # thread — holding the lock across either would stall a concurrent
        # start()/port() for up to the join timeout (dmlint: DM-L002)
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=2.0)


def _make_handler(service):
    class AdminHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args) -> None:
            logging.getLogger("web").debug("%s " + fmt, self.client_address[0], *args)

        # -- helpers ---------------------------------------------------
        def _send(self, code: int, body: bytes, content_type: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, payload: Any) -> None:
            self._send(code, json.dumps(payload).encode("utf-8"))

        def _read_json(self) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
            length = int(self.headers.get("Content-Length") or 0)
            if length == 0:
                return {}, None
            try:
                return json.loads(self.rfile.read(length) or b"{}"), None
            except json.JSONDecodeError as exc:
                return None, str(exc)

        # -- routes ----------------------------------------------------
        def do_GET(self) -> None:
            parsed = urlparse(self.path)
            if parsed.path == "/metrics":
                self._send(200, generate_latest(), CONTENT_TYPE_LATEST)
            elif parsed.path == "/admin/status":
                self._send_json(200, service._create_status_report())
            elif parsed.path == "/admin/health":
                query = parse_qs(parsed.query)
                deep = (query.get("deep") or ["0"])[0] not in ("", "0", "false")
                monitor = getattr(service, "health", None)
                if monitor is None:
                    self._send_json(200, {"state": "unknown",
                                          "detail": "no health monitor"})
                elif deep:
                    # fresh evaluation with per-check detail; non-200 on
                    # anything short of healthy so orchestration healthchecks
                    # (docker-compose/k8s) can gate on it directly
                    report = monitor.evaluate()
                    code = 200 if report["state"] == "healthy" else 503
                    self._send_json(code, report)
                else:
                    # cheap liveness: the watchdog's last roll-up, no
                    # evaluation on the request path; degraded stays 200
                    # (restarting a merely-degraded container makes it worse)
                    state = monitor.state
                    self._send_json(503 if state == "unhealthy" else 200,
                                    {"state": state})
            elif parsed.path == "/admin/events":
                query = parse_qs(parsed.query)
                events = getattr(service, "events", None)
                if events is None:
                    self._send_json(404, {"detail": "service has no event log"})
                    return
                try:
                    limit = int((query.get("limit") or ["-1"])[0])
                except ValueError:
                    self._send_json(400, {"detail": "limit must be an integer"})
                    return
                self._send_json(
                    200, events.snapshot(limit if limit >= 0 else None))
            elif parsed.path == "/admin/trace":
                query = parse_qs(parsed.query)
                fmt = (query.get("format") or ["json"])[0]
                recorder = getattr(service.engine, "trace_recorder", None)
                if recorder is None:
                    self._send_json(404, {"detail": "engine has no flight recorder"})
                elif fmt == "chrome":
                    self._send_json(200, recorder.chrome_events())
                elif fmt == "json":
                    body = recorder.snapshot()
                    body["tracing_enabled"] = bool(
                        getattr(service.settings, "engine_trace", False))
                    self._send_json(200, body)
                else:
                    self._send_json(400, {"detail": f"unknown format {fmt!r}"})
            else:
                self._send_json(404, {"detail": "not found"})

        def do_POST(self) -> None:
            try:
                if self.path == "/admin/start":
                    self._send_json(200, {"detail": service.start()})
                elif self.path == "/admin/stop":
                    service.stop()
                    self._send_json(200, {"detail": "engine stopped"})
                elif self.path == "/admin/shutdown":
                    self._send_json(200, {"detail": "service shutting down"})
                    service.shutdown()
                elif self.path == "/admin/reconfigure":
                    payload, err = self._read_json()
                    if err is not None:
                        self._send_json(400, {"detail": f"invalid JSON: {err}"})
                        return
                    config = (payload or {}).get("config") or {}
                    persist = bool((payload or {}).get("persist", False))
                    updated = service.reconfigure(config, persist=persist)
                    self._send_json(200, {"detail": "reconfigured", "config": updated})
                elif self.path == "/admin/checkpoint":
                    self._send_json(200, service.checkpoint())
                elif self.path == "/admin/profile":
                    payload, _ = self._read_json()
                    result = _capture_profile(service, payload or {})
                    self._send_json(200, result)
                else:
                    self._send_json(404, {"detail": "not found"})
            except Exception as exc:  # admin errors surface as HTTP 500s
                try:
                    self._send_json(500, {"detail": str(exc)})
                except (BrokenPipeError, ConnectionResetError):
                    pass

    return AdminHandler


def _capture_profile(service, payload: Dict[str, Any]) -> Dict[str, Any]:
    """Capture a jax.profiler trace for ``duration_ms`` (TPU-build addition)."""
    from ..utils.profiling import capture_trace

    duration_ms = int(payload.get("duration_ms", 1000))
    out_dir = payload.get("out_dir") or service.settings.profile_dir or "/tmp/detectmate_profile"
    return capture_trace(out_dir, duration_ms)
