"""Admin plane: HTTP server exposing lifecycle verbs + Prometheus metrics.

Route parity with the reference (reference:
src/service/features/web/router.py:18-46, server.py:22-27) — but the route
surface itself lives in ``web/router.py`` as a declarative table; this
module is only the transport shell (socket lifecycle, JSON encode/decode,
error mapping). dmlint DM-C007/8 pins the table to the ``docs/usage.md``
route reference in both directions.

The reference runs FastAPI/uvicorn on a thread with signal handlers disabled
(reference: server.py:40-42); this environment has neither, so the server is
a stdlib ``ThreadingHTTPServer`` on a daemon thread — same observable
surface, zero extra dependencies. Error mapping: a handler raising
``ValueError`` is a client error (HTTP 400); anything else surfaces as
HTTP 500 with a JSON detail.
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .router import Response, route_table


class WebServer:
    def __init__(self, service) -> None:
        self.service = service
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    @property
    def port(self) -> int:
        """Actual bound port (useful when settings request port 0)."""
        with self._lock:
            if self._httpd is not None:
                return self._httpd.server_address[1]
        return self.service.settings.http_port

    def start(self) -> None:
        with self._lock:
            if self._httpd is not None:
                return
            handler = _make_handler(self.service)
            self._httpd = ThreadingHTTPServer(
                (self.service.settings.http_host, self.service.settings.http_port),
                handler,
            )
            self._httpd.daemon_threads = True
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="WebServerThread",
                daemon=True,
                kwargs={"poll_interval": 0.1},
            )
            self._thread.start()

    def stop(self) -> None:
        # swap the references out under the lock, block outside it:
        # shutdown() waits for serve_forever's poll loop and join() for the
        # thread — holding the lock across either would stall a concurrent
        # start()/port() for up to the join timeout (dmlint: DM-L002)
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=2.0)


def _make_handler(service):
    table = route_table()

    class AdminHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt: str, *args) -> None:
            logging.getLogger("web").debug("%s " + fmt, self.client_address[0], *args)

        # -- helpers ---------------------------------------------------
        def _send(self, code: int, body: bytes, content_type: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, payload: Any) -> None:
            self._send(code, json.dumps(payload).encode("utf-8"))

        def _read_json(self) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
            length = int(self.headers.get("Content-Length") or 0)
            if length == 0:
                return {}, None
            try:
                return json.loads(self.rfile.read(length) or b"{}"), None
            except json.JSONDecodeError as exc:
                return None, str(exc)

        def _dispatch(self, method: str,
                      payload: Optional[Dict[str, Any]]) -> None:
            parsed = urlparse(self.path)
            route = table.get((method, parsed.path))
            if route is None:
                self._send_json(404, {"detail": "not found"})
                return
            try:
                response: Response = route.handler(
                    service, parse_qs(parsed.query), payload)
            except ValueError as exc:       # bad parameters — client error
                self._send_json(400, {"detail": str(exc)})
                return
            except Exception as exc:        # admin errors surface as 500s
                try:
                    self._send_json(500, {"detail": str(exc)})
                except (BrokenPipeError, ConnectionResetError):
                    pass
                return
            body = response.body
            if isinstance(body, (bytes, bytearray)):
                self._send(response.status, bytes(body), response.content_type)
            else:
                self._send_json(response.status, body)
            if response.after is not None:
                response.after()

        # -- routes ----------------------------------------------------
        def do_GET(self) -> None:
            self._dispatch("GET", None)

        def do_POST(self) -> None:
            payload, err = self._read_json()
            if err is not None:
                self._send_json(400, {"detail": f"invalid JSON: {err}"})
                return
            self._dispatch("POST", payload)

    return AdminHandler
