"""Pipeline message schemas with dict-style and attribute access.

Capability parity with the reference library's ``detectmatelibrary.schemas``
surface (reference: docs/interfaces.md:120-130, evidence of the wrapper API at
tests/library_integration/library_integration_base_fixtures.py:81-83 — kwargs /
dict construction, ``.serialize()`` / ``.deserialize()``, ``obj["field"]``
access as in docs/interfaces.md:199-200).

Wire format is proto3 and field-number compatible with the reference's
``schemas.proto`` (decoded from container/fluentout/schemas_pb.rb:8).
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, Mapping, Optional

from . import schemas_pb2 as _pb

SCHEMA_VERSION = "1.0.0"

__all__ = [
    "SCHEMA_VERSION",
    "SchemaError",
    "BaseSchema",
    "LogSchema",
    "ParserSchema",
    "DetectorSchema",
    "OutputSchema",
]


class SchemaError(Exception):
    """Raised on invalid schema field access or failed (de)serialization."""


def _is_repeated(desc: Any) -> bool:
    flag = getattr(desc, "is_repeated", None)
    if flag is not None:
        return bool(flag() if callable(flag) else flag)
    return desc.label == desc.LABEL_REPEATED


class BaseSchema:
    """Wraps a generated protobuf message with dict + attribute access.

    ``obj["field"]`` and ``obj.field`` both work; repeated and map fields
    return the live protobuf containers so ``obj["alertsObtain"].update(...)``
    mutates the message in place (matching the reference library's usage,
    docs/interfaces.md:199-200).
    """

    _PB = None  # type: ignore[assignment]

    def __init__(self, data: Optional[Mapping[str, Any]] = None, **kwargs: Any):
        self._msg = self._PB()  # type: ignore[misc]
        setattr(self._msg, "__version__", SCHEMA_VERSION)
        if data is not None:
            if not isinstance(data, Mapping):
                raise SchemaError(
                    f"{type(self).__name__} expects a mapping, got {type(data).__name__}"
                )
            self.update(data)
        if kwargs:
            self.update(kwargs)

    # -- field access ------------------------------------------------------
    def _field_names(self) -> set:
        return {f.name for f in self._PB.DESCRIPTOR.fields}

    def __getitem__(self, key: str) -> Any:
        if key not in self._field_names():
            raise SchemaError(f"{type(self).__name__} has no field {key!r}")
        return getattr(self._msg, key)

    def __setitem__(self, key: str, value: Any) -> None:
        self._set_field(key, value)

    def __getattr__(self, name: str) -> Any:
        # only called when normal lookup fails; delegate to the message
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return getattr(self.__dict__["_msg"], name)
        except AttributeError as exc:
            raise AttributeError(f"{type(self).__name__} has no field {name!r}") from exc

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._set_field(name, value)

    def _set_field(self, key: str, value: Any) -> None:
        desc = self._PB.DESCRIPTOR.fields_by_name.get(key)
        if desc is None:
            raise SchemaError(f"{type(self).__name__} has no field {key!r}")
        try:
            if _is_repeated(desc):
                if desc.message_type is not None and desc.message_type.GetOptions().map_entry:
                    field = getattr(self._msg, key)
                    field.clear()
                    field.update(value)
                else:
                    field = getattr(self._msg, key)
                    del field[:]
                    field.extend(value)
            else:
                setattr(self._msg, key, value)
        except (TypeError, ValueError) as exc:
            raise SchemaError(f"cannot set {type(self).__name__}.{key}: {exc}") from exc

    def update(self, data: Mapping[str, Any]) -> None:
        for key, value in data.items():
            self._set_field(key, value)

    def get(self, key: str, default: Any = None) -> Any:
        try:
            return self[key]
        except SchemaError:
            return default

    def __contains__(self, key: str) -> bool:
        return key in self._field_names()

    def __iter__(self) -> Iterator[str]:
        return iter(sorted(self._field_names()))

    def keys(self):
        return sorted(self._field_names())

    # -- (de)serialization -------------------------------------------------
    def serialize(self) -> bytes:
        return self._msg.SerializeToString()

    def deserialize(self, raw: bytes) -> "BaseSchema":
        try:
            self._msg.ParseFromString(raw)
        except Exception as exc:  # DecodeError
            raise SchemaError(f"cannot deserialize {type(self).__name__}: {exc}") from exc
        return self

    @classmethod
    def from_bytes(cls, raw: bytes) -> "BaseSchema":
        return cls().deserialize(raw)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for f in self._PB.DESCRIPTOR.fields:
            value = getattr(self._msg, f.name)
            if _is_repeated(f):
                if f.message_type is not None and f.message_type.GetOptions().map_entry:
                    out[f.name] = dict(value)
                else:
                    out[f.name] = list(value)
            else:
                out[f.name] = value
        return out

    def __eq__(self, other: object) -> bool:
        if isinstance(other, BaseSchema):
            return self._msg == other._msg
        return NotImplemented

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.to_dict()!r})"


class LogSchema(BaseSchema):
    """Reader output: one raw log line + provenance."""

    _PB = _pb.LogSchema


class ParserSchema(BaseSchema):
    """Parser output: template + extracted variables for one log line."""

    _PB = _pb.ParserSchema


class DetectorSchema(BaseSchema):
    """Detector output: one alert (only emitted when an anomaly is found)."""

    _PB = _pb.DetectorSchema


class OutputSchema(BaseSchema):
    """Aggregated output record."""

    _PB = _pb.OutputSchema
