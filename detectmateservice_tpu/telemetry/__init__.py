"""dmtel — cross-stage trace assembly, tail-based sampling, OTLP export.

PR 1 made every engine stamp its hop (stage, recv_ns, send_ns) into the v2
frame it forwards, but each process kept only its own bounded flight-recorder
ring: the richest debugging signal in the system was discarded at every stage
boundary. This package is the fleet-scale half of that telemetry:

* :mod:`spans`     — the engine-side exporter: completed hop records become
  self-contained span dicts and leave the process via a bounded non-blocking
  queue + sender thread (hot-loop cost: one deque append per frame);
* :mod:`collector` — ``dmcollect``: assembles spans into whole-pipeline
  traces (out-of-order arrival, at-least-once dedup, watermark completion)
  and tail-samples them — 100% of the anomalous tail, a configured ratio of
  the healthy rest;
* :mod:`otlp`      — self-contained OTLP/JSON-over-HTTP encoder + push, so
  assembled traces land in Jaeger/Tempo without an otel-SDK dependency;
* :mod:`perfetto`  — the cross-stage Perfetto (Chrome trace-event) view that
  supersedes the per-process ``GET /admin/trace?format=chrome``.

The wire between exporter and collector is the span frame
(``engine/framing.py`` MAGIC_SPAN, docs/transport.md); the settings knobs are
the ``telemetry_*`` block (docs/configuration.md).
"""
from __future__ import annotations

from .collector import TailSampler, TelemetryCollector, TraceAssembler
from .spans import SpanExporter

__all__ = [
    "SpanExporter",
    "TailSampler",
    "TelemetryCollector",
    "TraceAssembler",
]
