"""dmcollect — cross-stage trace assembly and tail-based sampling.

One collector per pipeline (its own runnable component, like the router):
every traced engine points ``telemetry_addr`` at it, and its assembler turns
the per-stage span stream back into whole-pipeline traces:

* **out-of-order arrival** — stages flush on their own cadence, so the
  terminal hop of a trace routinely arrives before an upstream hop; spans
  are keyed on trace id and merged whenever they arrive;
* **at-least-once dedup** — a router requeue redelivers a frame, and both
  deliveries stamp the same stage; duplicate (trace, stage) hops collapse
  to the EARLIEST attempt instead of producing two-headed traces;
* **watermark completion** — a trace is complete when its terminal hop has
  been seen AND the global send-time watermark (the max ``send_ns`` across
  every span received) has advanced ``telemetry_settle_ms`` past the
  trace's own newest hop: later traffic proves the stragglers had their
  chance. Traces that never complete are flushed after
  ``telemetry_trace_timeout_s`` on the collector's clock and counted
  incomplete — an incomplete trace is itself a signal (a stage died, shed
  mid-pipeline, or an exporter dropped the span).

Tail-based sampling then decides retention: traces that erred, shed,
quarantined, hit a fault site, ran past the SLO target, or never completed
are kept at 100%; the healthy rest is sampled at
``telemetry_sample_healthy_ratio`` by a deterministic hash of the trace id
(stable across restarts, so one trace's fate never depends on collector
uptime). Kept traces land in a bounded ring behind ``GET /admin/traces``
(JSON / Perfetto / OTLP) and, when ``telemetry_otlp_url`` is set, are
pushed OTLP/JSON-over-HTTP to Jaeger/Tempo by a dedicated export thread.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..engine import metrics as m
from ..engine.framing import FramingError, unpack_spans
from ..engine.socket import TransportError
from . import otlp, perfetto

# verdict precedence: the worst thing that happened to a trace names it
_FLAG_VERDICTS = ("error", "quarantined", "shed", "fault")


class _OpenTrace:
    """Assembly state for one trace id."""

    __slots__ = ("hops", "flags", "tenant_bucket", "terminal_send_ns",
                 "max_send_ns", "first_local_ns")

    def __init__(self, first_local_ns: int) -> None:
        self.hops: Dict[str, Dict[str, Any]] = {}   # stage → span dict
        self.flags: set = set()
        self.tenant_bucket: Optional[str] = None
        self.terminal_send_ns: Optional[int] = None
        self.max_send_ns = 0
        self.first_local_ns = first_local_ns


class TraceAssembler:
    """Pure assembly logic (no sockets, no threads — the unit under
    tests/test_telemetry.py). Clocks are injected: ``now_ns`` is the
    collector's local clock, span timestamps are producer ``time.time_ns()``
    epoch values that only ever compare against each other."""

    def __init__(self, settle_ns: int, timeout_ns: int) -> None:
        self._settle_ns = max(0, int(settle_ns))
        self._timeout_ns = max(1, int(timeout_ns))
        self._open: Dict[int, _OpenTrace] = {}
        self.watermark = 0
        self.deduped = 0

    @property
    def backlog(self) -> int:
        return len(self._open)

    def add(self, span: Dict[str, Any], now_ns: int) -> str:
        """Merge one span record; returns ``"hop"``, ``"dup"``, or
        ``"flag"`` (malformed records raise KeyError/ValueError — the
        collector counts and drops them)."""
        trace_id = int(span["trace_id"], 16)
        rec = self._open.get(trace_id)
        if rec is None:
            rec = _OpenTrace(now_ns)
            self._open[trace_id] = rec
        if span.get("tenant_bucket") is not None:
            rec.tenant_bucket = str(span["tenant_bucket"])
        if span.get("recv_ns") is None:
            # flag-only annotation from a cold path (shed/quarantine/error)
            rec.flags.update(span.get("flags", ()))
            return "flag"
        rec.flags.update(span.get("flags", ()))
        stage = str(span["stage"])
        send_ns = int(span["send_ns"])
        if send_ns > self.watermark:
            self.watermark = send_ns
        existing = rec.hops.get(stage)
        if existing is not None:
            # at-least-once redelivery: keep the FIRST attempt's timing
            self.deduped += 1
            if int(span["recv_ns"]) < int(existing["recv_ns"]):
                rec.hops[stage] = dict(span)
            return "dup"
        rec.hops[stage] = dict(span)
        if span.get("terminal"):
            rec.terminal_send_ns = send_ns
        if send_ns > rec.max_send_ns:
            rec.max_send_ns = send_ns
        return "hop"

    def poll(self, now_ns: int) -> Tuple[List[Dict[str, Any]],
                                         List[Dict[str, Any]]]:
        """Flush ready traces → ``(completed, expired)``. Completed traces
        saw their terminal hop (watermark-settled or timed out with it);
        expired ones hit ``telemetry_trace_timeout_s`` without one."""
        completed: List[Dict[str, Any]] = []
        expired: List[Dict[str, Any]] = []
        done: List[int] = []
        for trace_id, rec in self._open.items():
            has_terminal = rec.terminal_send_ns is not None
            settled = (has_terminal
                       and self.watermark >= rec.max_send_ns + self._settle_ns)
            timed_out = now_ns - rec.first_local_ns >= self._timeout_ns
            if not settled and not timed_out:
                continue
            done.append(trace_id)
            trace = self._build(trace_id, rec, complete=has_terminal)
            (completed if has_terminal else expired).append(trace)
        for trace_id in done:
            del self._open[trace_id]
        return completed, expired

    @staticmethod
    def _build(trace_id: int, rec: _OpenTrace,
               complete: bool) -> Dict[str, Any]:
        hops = sorted(rec.hops.values(), key=lambda h: int(h["recv_ns"]))
        ingest_ns = min((int(h["ingest_ns"]) for h in hops
                         if h.get("ingest_ns") is not None), default=None)
        e2e_s = None
        if complete and ingest_ns is not None:
            e2e_s = max(0, rec.terminal_send_ns - ingest_ns) / 1e9
        return {
            "trace_id": f"{trace_id:016x}",
            "ingest_ns": ingest_ns,
            "e2e_seconds": e2e_s,
            "complete": bool(complete),
            "flags": sorted(rec.flags),
            "tenant_bucket": rec.tenant_bucket,
            "hops": [{"stage": h["stage"],
                      "recv_ns": int(h["recv_ns"]),
                      "send_ns": int(h["send_ns"]),
                      "replica": h.get("replica", "")}
                     for h in hops],
        }


class TailSampler:
    """Keep/drop verdicts biased toward the anomalous tail."""

    def __init__(self, healthy_ratio: float, slo_s: float) -> None:
        self._ratio = min(1.0, max(0.0, float(healthy_ratio)))
        self._slo_s = float(slo_s)

    def verdict(self, trace: Dict[str, Any]) -> Tuple[bool, str]:
        """``(keep, verdict)`` — every verdict value becomes a
        ``telemetry_spans_total{verdict=...}`` label, so the set is small
        and closed: error / quarantined / shed / fault / incomplete /
        slow / healthy."""
        flags = trace.get("flags") or ()
        for flag in _FLAG_VERDICTS:
            if flag in flags:
                return True, flag
        if not trace.get("complete"):
            return True, "incomplete"
        e2e = trace.get("e2e_seconds")
        if e2e is not None and e2e > self._slo_s:
            return True, "slow"
        return self._keep_healthy(int(trace["trace_id"], 16)), "healthy"

    def _keep_healthy(self, trace_id: int) -> bool:
        if self._ratio >= 1.0:
            return True
        if self._ratio <= 0.0:
            return False
        # Fibonacci-hash the id into [0, 1): deterministic per trace, so a
        # restarted collector (or a test) reproduces the same sample set
        h = (trace_id * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        return (h >> 40) / float(1 << 24) < self._ratio


class TelemetryCollector:
    """The runnable collector: listener socket + assembly thread + export
    thread, constructed by ``core.Service`` when ``telemetry_collector`` is
    set (the admin plane serves its ring via ``GET /admin/traces``)."""

    def __init__(self, settings, factory, labels: Dict[str, str],
                 monitor=None, logger: Optional[logging.Logger] = None,
                 ) -> None:
        self._addr = settings.telemetry_collector_addr
        self._factory = factory
        self._labels = dict(labels)
        self._monitor = monitor
        self._logger = logger or logging.getLogger("detectmate.telemetry")
        self._otlp_url = getattr(settings, "telemetry_otlp_url", None)
        self.assembler = TraceAssembler(
            settle_ns=int(float(settings.telemetry_settle_ms) * 1e6),
            timeout_ns=int(float(settings.telemetry_trace_timeout_s) * 1e9))
        self.sampler = TailSampler(
            healthy_ratio=settings.telemetry_sample_healthy_ratio,
            slo_s=float(settings.telemetry_slo_ms) / 1000.0)
        self._retained: deque = deque(
            maxlen=int(getattr(settings, "telemetry_retain_traces", 256)))
        self._lock = threading.Lock()
        self._stats = {"spans": 0, "assembled": 0, "incomplete": 0,
                       "kept": 0, "dropped": 0, "bad_frames": 0}
        # label children hoisted once (DM-H001); verdict children on demand
        self._m_assembled = m.TELEMETRY_TRACES_ASSEMBLED().labels(**labels)
        self._m_dropped = m.TELEMETRY_TRACES_DROPPED().labels(**labels)
        self._m_incomplete = m.TELEMETRY_TRACES_INCOMPLETE().labels(**labels)
        self._m_deduped = m.TELEMETRY_SPANS_DEDUPED().labels(**labels)
        self._m_backlog = m.TELEMETRY_COLLECTOR_BACKLOG().labels(**labels)
        self._m_verdict: Dict[str, Any] = {}
        self._m_otlp: Dict[str, Any] = {}
        self._export_q: deque = deque(maxlen=1024)
        self._sock = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._export_thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._sock = self._factory.create(self._addr, self._logger, None)
        self._sock.recv_timeout = 100
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-collector", daemon=True)
        self._thread.start()
        if self._otlp_url:
            self._export_thread = threading.Thread(
                target=self._run_export, name="telemetry-otlp", daemon=True)
            self._export_thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        for thread in (self._thread, self._export_thread):
            if thread is not None:
                thread.join(timeout=timeout)
        self._thread = self._export_thread = None
        sock = self._sock
        self._sock = None
        if sock is not None:
            try:
                sock.close()
            # dmlint: ignore[DM-R001] best-effort close during shutdown
            except Exception:
                pass

    @property
    def backlog(self) -> int:
        return self.assembler.backlog

    # -- collector thread -------------------------------------------------

    def _run(self) -> None:  # dmlint: thread(any)
        while not self._stop.is_set():
            try:
                raw = self._sock.recv()
            except TransportError:
                raw = None
            except Exception:
                self._logger.exception("telemetry collector recv failed")
                raw = None
            if raw is not None:
                self.ingest_frame(raw)
            self.pump(time.time_ns())
        # final pump so short-lived runs (smokes) flush their tail
        self.pump(time.time_ns())

    def ingest_frame(self, raw: bytes) -> int:
        """One span frame → assembler. Returns spans merged (0 on a frame
        that is not a span frame, or is garbled — counted, never raised:
        a poisoned telemetry channel must not kill the collector)."""
        try:
            spans = unpack_spans(raw)
        except FramingError:
            spans = None
        if spans is None:
            with self._lock:
                self._stats["bad_frames"] += 1
            return 0
        now_ns = time.time_ns()
        merged = 0
        for span in spans:
            try:
                outcome = self.assembler.add(span, now_ns)
            except (KeyError, TypeError, ValueError):
                with self._lock:
                    self._stats["bad_frames"] += 1
                continue
            if outcome == "dup":
                self._m_deduped.inc()
            merged += 1
        return merged

    def pump(self, now_ns: int) -> None:
        """Advance assembly: flush completed/expired traces through the
        tail sampler into the retained ring, update gauges. Called from the
        collector thread each cycle (and directly by tests/smokes)."""
        completed, expired = self.assembler.poll(now_ns)
        for trace in completed:
            self._m_assembled.inc()
            self._finish(trace, assembled=True)
        for trace in expired:
            self._m_incomplete.inc()
            self._finish(trace, assembled=False)
        self._m_backlog.set(self.assembler.backlog)

    def _finish(self, trace: Dict[str, Any], assembled: bool) -> None:
        keep, verdict = self.sampler.verdict(trace)
        trace["verdict"] = verdict
        child = self._m_verdict.get(verdict)
        if child is None:
            child = m.TELEMETRY_SPANS().labels(verdict=verdict,
                                               **self._labels)
            self._m_verdict[verdict] = child
        n_hops = len(trace["hops"])
        if n_hops:
            child.inc(n_hops)
        with self._lock:
            self._stats["spans"] += n_hops
            if assembled:
                self._stats["assembled"] += 1
            else:
                self._stats["incomplete"] += 1
            if keep:
                self._stats["kept"] += 1
                self._retained.append(trace)
            else:
                self._stats["dropped"] += 1
        if not keep:
            self._m_dropped.inc()
        elif self._otlp_url:
            self._export_q.append(trace)

    # -- OTLP export thread -----------------------------------------------

    def _run_export(self) -> None:  # dmlint: thread(any)
        while not self._stop.is_set():
            self._stop.wait(0.25)
            self.export_pending()

    def export_pending(self) -> int:
        """Push queued kept traces to ``telemetry_otlp_url`` as one
        OTLP/JSON batch; returns traces shipped."""
        batch: List[Dict[str, Any]] = []
        q = self._export_q
        while q:
            try:
                batch.append(q.popleft())
            except IndexError:
                break
        if not batch:
            return 0
        doc = otlp.encode_traces(batch, self._labels)
        try:
            otlp.push(self._otlp_url, doc)
            result = "ok"
        except Exception as exc:
            result = "error"
            self._logger.warning("OTLP push to %s failed: %s",
                                 self._otlp_url, exc)
        child = self._m_otlp.get(result)
        if child is None:
            child = m.TELEMETRY_OTLP_PUSHES().labels(result=result,
                                                     **self._labels)
            self._m_otlp[result] = child
        child.inc()
        return len(batch) if result == "ok" else 0

    # -- admin surfaces (web/router.py GET /admin/traces) ------------------

    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            traces = list(self._retained)
            stats = dict(self._stats)
        traces.reverse()  # newest first
        if limit is not None:
            traces = traces[:max(0, int(limit))]
        stats["deduped"] = self.assembler.deduped
        stats["backlog"] = self.assembler.backlog
        return {
            "stats": stats,
            "traces": [{"trace_id": t["trace_id"],
                        "verdict": t.get("verdict"),
                        "complete": t["complete"],
                        "e2e_seconds": t["e2e_seconds"],
                        "stages": len(t["hops"]),
                        "flags": t["flags"]}
                       for t in traces],
        }

    def trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Full assembled trace by 16-hex id (the stage waterfall behind
        ``client.py trace show``)."""
        want = trace_id.lower().lstrip("0x").rjust(16, "0")
        with self._lock:
            for t in reversed(self._retained):
                if t["trace_id"] == want:
                    return t
        return None

    def retained(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._retained)

    def perfetto_events(self) -> Dict[str, Any]:
        """Cross-stage Chrome trace-event document (Perfetto-loadable) of
        every retained trace — the pipeline view that supersedes the
        per-process ``GET /admin/trace?format=chrome``."""
        return perfetto.trace_events(self.retained())

    def otlp_payload(self) -> Dict[str, Any]:
        """The retained ring as one OTLP/JSON document (the CI smoke's
        artifact; also ``GET /admin/traces?format=otlp``)."""
        return otlp.encode_traces(self.retained(), self._labels)
