"""Engine-side span export: hop records → span frames, off the hot path.

The engine loop already pays one ``time.time_ns()`` per frame to stamp its
hop into the forwarded v2 trace block; this module makes that same record
leave the process. The contract with the hot loop is strict:

* ``offer()`` is the ONLY hot-path surface and costs one bounded-deque
  append (a ``len`` check + ``append``, both GIL-atomic) — no lock, no
  allocation beyond the tuple the caller already built, no clock read;
* when the queue is full the SPAN is dropped, never the frame — the
  pipeline must not feel its own telemetry (``telemetry_spans_export_
  dropped_total``, plus a rate-limited ``telemetry_export_degraded``
  event);
* everything with real cost — dict building, tenant→bucket hashing, JSON
  encoding, the socket send — happens on the sender thread at
  ``telemetry_flush_interval_ms`` cadence.

Cold paths (shed refusals, quarantines, dispatch errors) annotate a trace
through ``offer_flag``; flags ride the same queue as 3-tuples and become
flag-only span records the collector merges into the trace's verdict.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..engine import metrics as m
from ..engine.framing import pack_spans
from ..shed.quota import tenant_bucket

# re-emit the degraded event at most this often while drops continue — the
# event ring must show the condition, not one entry per dropped span
_DEGRADED_EVENT_INTERVAL_S = 60.0


class SpanExporter:
    """Ships completed hop spans to the telemetry collector over the
    engine's own transport backend (``telemetry_addr``)."""

    def __init__(self, settings, factory, stage: str,
                 labels: Dict[str, str],
                 logger: Optional[logging.Logger] = None,
                 events: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 ) -> None:
        self._addr = settings.telemetry_addr
        self._cap = int(getattr(settings, "telemetry_queue_size", 4096))
        self._flush_s = max(
            0.001,
            float(getattr(settings, "telemetry_flush_interval_ms", 50.0))
            / 1000.0)
        self._buckets = int(getattr(settings, "shed_tenant_buckets", 16) or 16)
        self._factory = factory
        self._stage = stage
        self._replica = labels.get("component_id", "")
        self._logger = logger
        self._events = events
        # the bounded hot-path queue: hop 6-tuples and flag 3-tuples mixed
        # in arrival order. A deque, not queue.Queue — offer() must never
        # take a lock or wake a waiter.
        self._q: deque = deque()
        self._m_dropped = m.TELEMETRY_EXPORT_DROPPED().labels(**labels)
        self._sock = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_degraded_emit = 0.0
        self._send_errors = 0

    # -- hot path ---------------------------------------------------------

    def offer(self, trace_id: int, ingest_ns: int, recv_ns: int,
              send_ns: int, terminal: bool, tenant: Optional[str]) -> None:
        """Enqueue one completed hop. Called from the engine loop per frame
        (``_stamp_trace`` / ``_finalize_traces``); bounded and non-blocking
        by construction."""
        # dmlint: hot-loop
        q = self._q
        if len(q) < self._cap:
            q.append((trace_id, ingest_ns, recv_ns, send_ns, terminal,
                      tenant))
        else:
            self._m_dropped.inc()

    def offer_flag(self, trace_id: Optional[int], flag: str) -> None:
        """Annotate ``trace_id`` with a verdict flag (``shed`` /
        ``quarantined`` / ``error`` / ``fault``). Cold paths only — a shed
        refusal, a poison frame, a dispatch exception."""
        if trace_id is None:
            return
        q = self._q
        if len(q) < self._cap:
            q.append(("flag", trace_id, flag))
        else:
            self._m_dropped.inc()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-sender", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=timeout)
        self._thread = None
        sock = self._sock
        self._sock = None
        if sock is not None:
            try:
                sock.close()
            # dmlint: ignore[DM-R001] best-effort close; the send path broke it
            except Exception:
                pass

    @property
    def backlog(self) -> int:
        return len(self._q)

    # -- sender thread ----------------------------------------------------

    def _run(self) -> None:  # dmlint: thread(any)
        while not self._stop.is_set():
            self._stop.wait(self._flush_s)
            self.flush()
        self.flush()  # final drain so short-lived smokes lose nothing

    def flush(self) -> int:
        """Drain the queue into one span frame and send it. Returns the
        number of spans shipped (0 when idle or the link refused). Public
        for tests and for the engine's stop epilogue."""
        q = self._q
        if not q:
            return 0
        spans: List[Dict[str, Any]] = []
        while q:
            try:
                item = q.popleft()
            except IndexError:
                break
            if item[0] == "flag":
                spans.append({
                    "trace_id": f"{item[1]:016x}",
                    "stage": self._stage,
                    "replica": self._replica,
                    "flags": [item[2]],
                })
                continue
            trace_id, ingest_ns, recv_ns, send_ns, terminal, tenant = item
            span: Dict[str, Any] = {
                "trace_id": f"{trace_id:016x}",
                "stage": self._stage,
                "replica": self._replica,
                "ingest_ns": ingest_ns,
                "recv_ns": recv_ns,
                "send_ns": send_ns,
                "terminal": bool(terminal),
            }
            if tenant is not None:
                span["tenant_bucket"] = tenant_bucket(tenant, self._buckets)
            spans.append(span)
        if not spans:
            return 0
        frame = pack_spans(spans)
        try:
            sock = self._sock
            if sock is None:
                sock = self._factory.create_output(self._addr, self._logger)
                self._sock = sock
            sock.send(frame)
        except Exception as exc:
            # span loss is the designed failure mode: count it, surface it,
            # drop the batch — never backpressure into the engine
            self._m_dropped.inc(len(spans))
            self._send_errors += 1
            self._sock = None
            self._note_degraded(f"send to {self._addr} failed: {exc}")
            return 0
        return len(spans)

    def _note_degraded(self, detail: str) -> None:
        now = time.monotonic()
        if now - self._last_degraded_emit < _DEGRADED_EVENT_INTERVAL_S:
            return
        self._last_degraded_emit = now
        if self._events is not None:
            try:
                self._events({"kind": "telemetry_export_degraded",
                              "detail": detail,
                              "send_errors": self._send_errors})
            # dmlint: ignore[DM-R001] a broken event ring must not kill sending
            except Exception:
                pass
        elif self._logger is not None:
            self._logger.warning("telemetry export degraded: %s", detail)
