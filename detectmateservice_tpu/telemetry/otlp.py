"""Self-contained OTLP/JSON encoder + HTTP push (no otel-SDK dependency).

Assembled pipeline traces → one ``ExportTraceServiceRequest`` JSON document
(the OTLP/HTTP ``v1/traces`` wire shape), POSTed with urllib. Hand-rolled on
purpose, matching the repo's in-house style (cf. the web server, the
prometheus exposition): the subset of OTLP a hop span needs is ~40 lines,
and an SDK would drag in exporters, processors, and a second notion of a
span.

Mapping: the pipeline's 64-bit trace id left-pads to OTLP's 128-bit
``traceId``; each hop becomes one span whose ``spanId`` is a stable 8-byte
blake2b of (trace id, stage) — so re-exports are idempotent — parented on
the previous hop in recv-time order; verdict/flags/tenant ride as
attributes; an ``error``/``quarantined`` verdict sets OTLP status ERROR.
"""
from __future__ import annotations

import hashlib
import json
import urllib.request
from typing import Any, Dict, List, Optional

_SPAN_KIND_INTERNAL = 1
_STATUS_OK = 1
_STATUS_ERROR = 2
_ERROR_VERDICTS = ("error", "quarantined")


def span_id(trace_id: str, stage: str) -> str:
    """Stable 16-hex OTLP span id for one (trace, stage) hop."""
    digest = hashlib.blake2b(f"{trace_id}/{stage}".encode("utf-8"),
                             digest_size=8)
    return digest.hexdigest()


def _attr(key: str, value: Any) -> Dict[str, Any]:
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, int):
        return {"key": key, "value": {"intValue": str(value)}}
    if isinstance(value, float):
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


def encode_traces(traces: List[Dict[str, Any]],
                  resource: Optional[Dict[str, str]] = None,
                  ) -> Dict[str, Any]:
    """Assembled trace dicts (collector ``_build`` shape) → OTLP/JSON
    ``ExportTraceServiceRequest``."""
    resource_attrs = [_attr("service.name", "detectmate")]
    for key, value in sorted((resource or {}).items()):
        resource_attrs.append(_attr(f"detectmate.{key}", value))
    spans: List[Dict[str, Any]] = []
    for trace in traces:
        otlp_trace_id = trace["trace_id"].rjust(32, "0")
        verdict = trace.get("verdict") or "healthy"
        is_error = (verdict in _ERROR_VERDICTS
                    or any(f in _ERROR_VERDICTS
                           for f in trace.get("flags", ())))
        parent = ""
        for hop in trace["hops"]:
            attrs = [_attr("detectmate.stage", hop["stage"]),
                     _attr("detectmate.verdict", verdict)]
            if hop.get("replica"):
                attrs.append(_attr("detectmate.replica", hop["replica"]))
            if trace.get("tenant_bucket") is not None:
                attrs.append(_attr("detectmate.tenant_bucket",
                                   trace["tenant_bucket"]))
            for flag in trace.get("flags", ()):
                attrs.append(_attr(f"detectmate.flag.{flag}", True))
            if not trace.get("complete", True):
                attrs.append(_attr("detectmate.incomplete", True))
            sid = span_id(trace["trace_id"], hop["stage"])
            spans.append({
                "traceId": otlp_trace_id,
                "spanId": sid,
                "parentSpanId": parent,
                "name": hop["stage"],
                "kind": _SPAN_KIND_INTERNAL,
                "startTimeUnixNano": str(hop["recv_ns"]),
                "endTimeUnixNano": str(max(hop["recv_ns"], hop["send_ns"])),
                "attributes": attrs,
                "status": {"code": _STATUS_ERROR if is_error
                           else _STATUS_OK},
            })
            parent = sid
    return {
        "resourceSpans": [{
            "resource": {"attributes": resource_attrs},
            "scopeSpans": [{
                "scope": {"name": "detectmate.telemetry", "version": "1"},
                "spans": spans,
            }],
        }],
    }


def push(url: str, doc: Dict[str, Any], timeout: float = 5.0) -> int:
    """POST the document to an OTLP/HTTP traces endpoint (e.g.
    ``http://tempo:4318/v1/traces``); returns the HTTP status, raises on
    transport/HTTP failure (the caller counts)."""
    body = json.dumps(doc, separators=(",", ":")).encode("utf-8")
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status
