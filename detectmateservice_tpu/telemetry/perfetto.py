"""Cross-stage Perfetto (Chrome trace-event) export of assembled traces.

Same document shape as ``FlightRecorder.chrome_events`` (engine/tracing.py)
— one pid per trace, "X" slices per stage hop, "transit" slices for the
wire+queue gaps — but built from the COLLECTOR's assembled traces, so the
slices span every stage of the pipeline instead of the one process serving
the request. This is the view ``GET /admin/trace?format=chrome`` documents;
on a collector stage it serves this, elsewhere it falls back to the local
recorder (docs/walkthrough.md).
"""
from __future__ import annotations

from typing import Any, Dict, List


def trace_events(traces: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Assembled trace dicts → Chrome trace-event JSON (Perfetto-loadable).
    Input hops are already recv-sorted by the assembler; verdict and flags
    ride in the slice args so the anomalous tail is searchable in the UI."""
    seen = set()
    events: List[Dict[str, Any]] = []
    for trace in traces:
        if trace["trace_id"] in seen:
            continue
        seen.add(trace["trace_id"])
        pid = int(trace["trace_id"], 16) % (1 << 31)
        name = f"trace {trace['trace_id']}"
        verdict = trace.get("verdict")
        if verdict:
            name += f" [{verdict}]"
        events.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": name},
        })
        prev_send = trace.get("ingest_ns")
        for hop in trace["hops"]:
            if prev_send is not None and hop["recv_ns"] > prev_send:
                events.append({
                    "name": "transit", "cat": "pipeline", "ph": "X",
                    "pid": pid, "tid": 0,
                    "ts": prev_send / 1000.0,
                    "dur": (hop["recv_ns"] - prev_send) / 1000.0,
                })
            args: Dict[str, Any] = {"trace_id": trace["trace_id"]}
            if verdict:
                args["verdict"] = verdict
            if trace.get("flags"):
                args["flags"] = list(trace["flags"])
            if hop.get("replica"):
                args["replica"] = hop["replica"]
            events.append({
                "name": hop["stage"], "cat": "pipeline", "ph": "X",
                "pid": pid, "tid": 0,
                "ts": hop["recv_ns"] / 1000.0,
                "dur": max(0, hop["send_ns"] - hop["recv_ns"]) / 1000.0,
                "args": args,
            })
            prev_send = hop["send_ns"]
    return {"traceEvents": events, "displayTimeUnit": "ms"}
