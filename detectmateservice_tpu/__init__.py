"""DetectMate TPU: a TPU-native log-processing / anomaly-detection framework.

Package exports match the reference service's public surface
(reference: src/service/__init__.py) plus the TPU-build factories.
"""
from .core import Service
from .settings import ServiceSettings
from .engine import Engine, EngineSocketFactory, ZmqPairSocketFactory, InprocQueueSocketFactory
from .metadata import VERSION as __version__

__all__ = [
    "Service",
    "ServiceSettings",
    "Engine",
    "EngineSocketFactory",
    "ZmqPairSocketFactory",
    "InprocQueueSocketFactory",
    "__version__",
]
