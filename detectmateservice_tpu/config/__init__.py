from .manager import ConfigManager, ServiceConfig
from .resolver import ComponentResolver
from .loader import ComponentLoader, ConfigClassLoader

__all__ = [
    "ConfigManager",
    "ServiceConfig",
    "ComponentResolver",
    "ComponentLoader",
    "ConfigClassLoader",
]
