"""Component-config lifecycle: load / save / update, lock-guarded.

Capability parity with the reference's ``ConfigManager``
(reference: src/service/features/config_manager.py:18-130):

* on-disk component config is namespaced *category → ClassName → params*
  (reference: config_manager.py:12-15, tests/config/detector_config.yaml:1-17),
* ``load()`` creates-and-saves defaults when the file is missing
  (reference: config_manager.py:34-46),
* ``save()`` prefers the config object's ``to_dict()`` to strip defaults
  (reference: config_manager.py:85-92),
* ``update()`` re-validates (reference: config_manager.py:118-125),
* all public methods are RLock-guarded (reference: config_manager.py:28).
"""
from __future__ import annotations

import logging
import threading
from pathlib import Path
from typing import Any, Dict, Optional, Type

import yaml
from pydantic import BaseModel, ConfigDict, ValidationError


class ConfigError(Exception):
    """Raised on config load/validate/save failures."""


class ServiceConfig(BaseModel):
    """Loose top-level shape of a component config file.

    The service validates only the category namespacing; strict validation is
    the component's job (reference: config_manager.py:12-15,53-60).
    """

    model_config = ConfigDict(extra="allow")

    detectors: Optional[Dict[str, Any]] = None
    parsers: Optional[Dict[str, Any]] = None
    readers: Optional[Dict[str, Any]] = None
    outputs: Optional[Dict[str, Any]] = None


class ConfigManager:
    """Owns the component config file and its in-memory copy."""

    def __init__(
        self,
        config_file: str,
        config_schema: Optional[Type[BaseModel]] = None,
        logger: Optional[logging.Logger] = None,
    ) -> None:
        self._path = Path(config_file)
        self._schema = config_schema
        self._logger = logger or logging.getLogger(__name__)
        self._lock = threading.RLock()
        self._config: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    def load(self) -> Dict[str, Any]:
        """Read + validate the file; create it with defaults if missing."""
        with self._lock:
            if not self._path.exists():
                self._logger.info("config file %s missing; writing defaults", self._path)
                self._config = self._default_config()
                self._write(self._config)
                return dict(self._config)
            try:
                with open(self._path, "r", encoding="utf-8") as fh:
                    data = yaml.safe_load(fh) or {}
            except (OSError, yaml.YAMLError) as exc:
                raise ConfigError(f"cannot read config file {self._path}: {exc}") from exc
            self._config = self._validate(data)
            return dict(self._config)

    def get(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._config)

    def update(self, data: Dict[str, Any]) -> Dict[str, Any]:
        """Replace the in-memory config after re-validation."""
        with self._lock:
            self._config = self._validate(data)
            return dict(self._config)

    def validate(self, data: Dict[str, Any]) -> Dict[str, Any]:
        """Validate WITHOUT mutating state (pre-flight for a live component
        applying the change before the manager commits it)."""
        with self._lock:
            return dict(self._validate(data))

    def save(self, data: Optional[Dict[str, Any]] = None) -> None:
        """Persist config to disk, stripping defaults where the object can."""
        with self._lock:
            payload = self._config if data is None else self._validate(data)
            to_dict = getattr(payload, "to_dict", None)
            if callable(to_dict):
                payload = to_dict()
            self._write(payload)
            self._config = dict(payload)

    # ------------------------------------------------------------------
    def _validate(self, data: Dict[str, Any]) -> Dict[str, Any]:
        if not isinstance(data, dict):
            raise ConfigError(f"component config must be a mapping, got {type(data).__name__}")
        try:
            ServiceConfig.model_validate(data)
        except ValidationError as exc:
            raise ConfigError(f"invalid component config: {exc}") from exc
        return dict(data)

    def _default_config(self) -> Dict[str, Any]:
        if self._schema is not None:
            try:
                instance = self._schema()
                to_dict = getattr(instance, "to_dict", None)
                if callable(to_dict):
                    return to_dict()
                return instance.model_dump()
            except Exception:
                self._logger.warning("could not build defaults from %s", self._schema)
        return {}

    def _write(self, data: Dict[str, Any]) -> None:
        try:
            self._path.parent.mkdir(parents=True, exist_ok=True)
            with open(self._path, "w", encoding="utf-8") as fh:
                yaml.safe_dump(data, fh, sort_keys=False)
        except OSError as exc:
            raise ConfigError(f"cannot write config file {self._path}: {exc}") from exc
