"""Short-name → dotted-path component resolution by package walk.

Capability parity with the reference's ``ComponentResolver``
(reference: src/service/features/component_resolver.py:29-123): a bare class
name is resolved by walking every module under the component library root and
matching the first ``CoreComponent`` subclass whose ``__name__`` matches; a
dotted path is returned as-is; the config class is ``<ClassName>Config`` looked
up in the same module, falling back to the base ``CoreConfig``.
"""
from __future__ import annotations

import importlib
import inspect
import logging
import pkgutil
from typing import Optional, Tuple

# Module-level seam so tests can point resolution at a fake library package
# (the reference tests monkeypatch DEFAULT_ROOT the same way,
# reference: tests/test_component_loader/test_component_loader.py:21-53).
DEFAULT_ROOT = "detectmateservice_tpu.library"


class ResolverError(Exception):
    """Raised when a component name cannot be resolved."""


class ComponentResolver:
    def __init__(self, root: Optional[str] = None, logger: Optional[logging.Logger] = None):
        self._root = root or DEFAULT_ROOT
        self._logger = logger or logging.getLogger(__name__)

    def resolve(self, name: str) -> Tuple[str, Optional[str]]:
        """Resolve ``name`` to ``(component_path, config_class_path|None)``.

        Dotted paths pass through unchanged with a sibling ``<Class>Config``
        guess (reference: component_resolver.py:42-46); short names trigger a
        package walk (reference: component_resolver.py:60-95).
        """
        if "." in name:
            module_path, cls_name = name.rsplit(".", 1)
            return name, f"{module_path}.{cls_name}Config"
        module_name, cls_name = self._find_by_walk(name)
        config_path = self._find_config_class(module_name, cls_name)
        return f"{module_name}.{cls_name}", config_path

    # ------------------------------------------------------------------
    def _find_by_walk(self, short_name: str) -> Tuple[str, str]:
        from detectmateservice_tpu.library.common.core import CoreComponent

        try:
            root_pkg = importlib.import_module(self._root)
        except ImportError as exc:
            raise ResolverError(f"component library root {self._root!r} not importable: {exc}") from exc

        candidates = [self._root]
        if hasattr(root_pkg, "__path__"):
            for info in pkgutil.walk_packages(root_pkg.__path__, prefix=self._root + "."):
                candidates.append(info.name)

        for module_name in candidates:
            try:
                module = importlib.import_module(module_name)
            except Exception:  # broken optional module must not kill the walk
                continue
            for attr_name, attr in vars(module).items():
                if (
                    inspect.isclass(attr)
                    and attr.__name__ == short_name
                    and issubclass(attr, CoreComponent)
                    and attr is not CoreComponent
                ):
                    return module_name, attr_name
        raise ResolverError(
            f"no CoreComponent subclass named {short_name!r} found under {self._root!r}"
        )

    def _find_config_class(self, module_name: str, cls_name: str) -> Optional[str]:
        from detectmateservice_tpu.library.common.core import CoreConfig

        config_name = f"{cls_name}Config"
        try:
            module = importlib.import_module(module_name)
        except ImportError:
            return None
        attr = getattr(module, config_name, None)
        if inspect.isclass(attr) and issubclass(attr, CoreConfig):
            return f"{module_name}.{config_name}"
        return f"{DEFAULT_ROOT}.common.core.CoreConfig"
