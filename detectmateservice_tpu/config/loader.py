"""Dynamic component / config-class loading with a pinned error taxonomy.

Capability parity with the reference's ``ComponentLoader`` and
``ConfigClassLoader`` (reference: src/service/features/component_loader.py:13-67,
config_loader.py:17-80):

* import the module path as given, then retry with the library-root prefix
  (reference: component_loader.py:34-43),
* instantiate ``cls(config=config)``, or no-arg when config is falsy
  (reference: component_loader.py:47-50; pinned by
  tests/test_component_loader/test_component_loader.py:90-139),
* gate on ``isinstance(instance, CoreComponent)`` /
  ``issubclass(cls, CoreConfig)`` (reference: component_loader.py:52-56,
  config_loader.py:49-71),
* error taxonomy: ImportError for missing modules, AttributeError for missing
  classes, RuntimeError for contract violations
  (reference: component_loader.py:58-67).
"""
from __future__ import annotations

import importlib
import inspect
import logging
from typing import Any, Optional, Type

from . import resolver as _resolver_mod


def _import_with_fallback(path: str, root: str) -> tuple:
    """Return (module, class_name); try ``path`` as-is then root-prefixed."""
    module_path, cls_name = path.rsplit(".", 1)
    last_exc: Optional[ImportError] = None
    for candidate in (module_path, f"{root}.{module_path}"):
        try:
            return importlib.import_module(candidate), cls_name
        except ImportError as exc:
            last_exc = exc
    raise ImportError(f"cannot import module for component path {path!r}: {last_exc}")


class ComponentLoader:
    def __init__(self, root: Optional[str] = None, logger: Optional[logging.Logger] = None):
        self._root = root or _resolver_mod.DEFAULT_ROOT
        self._logger = logger or logging.getLogger(__name__)

    def load_component(self, path: str, config: Any = None) -> Any:
        """Import, instantiate, and contract-check a component."""
        from detectmateservice_tpu.library.common.core import CoreComponent

        if "." not in path:
            raise ImportError(
                f"component path {path!r} must be dotted (module.ClassName); "
                "use ComponentResolver for short names"
            )
        module, cls_name = _import_with_fallback(path, self._root)
        cls = getattr(module, cls_name, None)
        if cls is None:
            raise AttributeError(f"module {module.__name__!r} has no class {cls_name!r}")
        try:
            instance = cls(config=config) if config else cls()
        except TypeError as exc:
            raise RuntimeError(f"cannot instantiate component {path!r}: {exc}") from exc
        if not isinstance(instance, CoreComponent):
            raise RuntimeError(
                f"{path!r} resolved to {type(instance).__name__}, which is not a CoreComponent"
            )
        self._logger.info("loaded component %s", path)
        return instance


class ConfigClassLoader:
    def __init__(self, root: Optional[str] = None, logger: Optional[logging.Logger] = None):
        self._root = root or _resolver_mod.DEFAULT_ROOT
        self._logger = logger or logging.getLogger(__name__)

    def load_config_class(self, path: str) -> Type:
        """Import and contract-check a config class (CoreConfig subclass)."""
        from detectmateservice_tpu.library.common.core import CoreConfig

        if "." not in path:
            raise ImportError(f"config class path {path!r} must be dotted (module.ClassName)")
        module, cls_name = _import_with_fallback(path, self._root)
        cls = getattr(module, cls_name, None)
        if cls is None:
            raise AttributeError(f"module {module.__name__!r} has no class {cls_name!r}")
        if not (inspect.isclass(cls) and issubclass(cls, CoreConfig)):
            raise RuntimeError(f"{path!r} is not a CoreConfig subclass")
        return cls
