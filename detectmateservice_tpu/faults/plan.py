"""FaultPlan: a seeded, deterministic schedule of injected faults.

The plan is the *contract* of every chaos run: given the same seed and the
same spec list, the decision for (site, kind, op_index) is a pure function —
no RNG state, no wall clock — so a recorded chaos scenario replays its exact
fault schedule from nothing but the committed seed. That property is what
every future chaos bisection depends on: shrink the window, rerun, and the
faults land on the same operations.

A :class:`FaultSpec` names one fault stream:

* ``site`` — the instrumented boundary (see :data:`SITES`): socket ops
  (``sock_send``/``sock_recv``/``sock_dial``), filesystem ops
  (``wal_append``/``wal_fsync``/``fs_commit``), processor dispatch
  (``proc``);
* ``kind`` — what happens there (latency/drop/error for sockets,
  eio/enospc/torn for disk, raise/hang/slow for the processor);
* ``rate`` — per-operation probability, drawn deterministically from the
  seed (``rate=1.0`` fires on every op in the window);
* ``start_op``/``stop_op`` — the op-index window the stream is live in
  (op indices are per-site counters, so timing is expressed in operations,
  not wall seconds — the only clock that replays exactly);
* ``delay_ms`` — for latency/slow/hang kinds, how long the site stalls;
* ``match`` — processor site only: a substring that marks POISON payloads.
  A match-spec ignores ``rate``/windows and fires deterministically for
  every chunk containing the marker — the reproducible poison frame the
  dead-letter quarantine exists for.

The decision draw hashes ``seed:site:kind:op`` (crc32 → uniform in [0,1)),
so it is independent of evaluation order, platform, and process — two runs
that perform the same operations inject the same faults.
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# site → allowed kinds; arming validates against this so a typo'd spec
# fails loudly instead of silently never firing
SITES: Dict[str, Tuple[str, ...]] = {
    "sock_send": ("latency", "drop", "error"),
    "sock_recv": ("latency", "drop", "error"),
    "sock_dial": ("error",),
    "wal_append": ("eio", "enospc"),
    "wal_fsync": ("eio", "enospc"),
    "fs_commit": ("eio", "torn"),
    "proc": ("raise", "hang", "slow"),
}


class FaultPlanError(ValueError):
    """A fault plan names an unknown site/kind or carries a bad field."""


@dataclass(frozen=True)
class FaultSpec:
    site: str
    kind: str
    rate: float = 1.0
    start_op: int = 0
    stop_op: Optional[int] = None
    delay_ms: float = 0.0
    match: str = ""

    def validate(self) -> None:
        kinds = SITES.get(self.site)
        if kinds is None:
            raise FaultPlanError(
                f"unknown fault site {self.site!r} (sites: {sorted(SITES)})")
        if self.kind not in kinds:
            raise FaultPlanError(
                f"site {self.site!r} has no kind {self.kind!r} "
                f"(kinds: {kinds})")
        if not 0.0 <= self.rate <= 1.0:
            raise FaultPlanError(f"rate {self.rate} outside [0, 1]")
        if self.start_op < 0:
            raise FaultPlanError(f"start_op {self.start_op} negative")
        if self.stop_op is not None and self.stop_op <= self.start_op:
            raise FaultPlanError(
                f"stop_op {self.stop_op} <= start_op {self.start_op}")
        if self.match and self.site != "proc":
            raise FaultPlanError(
                f"match is processor-site only (spec site {self.site!r})")

    def doc(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"site": self.site, "kind": self.kind,
                               "rate": self.rate, "start_op": self.start_op}
        if self.stop_op is not None:
            out["stop_op"] = self.stop_op
        if self.delay_ms:
            out["delay_ms"] = self.delay_ms
        if self.match:
            out["match"] = self.match
        return out


@dataclass(frozen=True)
class FaultPlan:
    seed: int
    specs: Tuple[FaultSpec, ...] = field(default_factory=tuple)

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "FaultPlan":
        """Build (and validate) a plan from the JSON shape the settings
        file and ``POST /admin/faults`` carry:
        ``{"seed": int, "specs": [{"site": ..., "kind": ..., ...}, ...]}``."""
        if not isinstance(doc, dict):
            raise FaultPlanError("fault plan must be a JSON object")
        try:
            seed = int(doc.get("seed", 0))
        except (TypeError, ValueError):
            raise FaultPlanError(f"bad seed {doc.get('seed')!r}")
        raw = doc.get("specs", [])
        if not isinstance(raw, list):
            raise FaultPlanError("specs must be a list")
        specs = []
        allowed = {"site", "kind", "rate", "start_op", "stop_op",
                   "delay_ms", "match"}
        for i, entry in enumerate(raw):
            if not isinstance(entry, dict):
                raise FaultPlanError(f"spec #{i} is not an object")
            unknown = set(entry) - allowed
            if unknown:
                raise FaultPlanError(
                    f"spec #{i} has unknown fields {sorted(unknown)}")
            try:
                spec = FaultSpec(
                    site=str(entry.get("site", "")),
                    kind=str(entry.get("kind", "")),
                    rate=float(entry.get("rate", 1.0)),
                    start_op=int(entry.get("start_op", 0)),
                    stop_op=(None if entry.get("stop_op") is None
                             else int(entry["stop_op"])),
                    delay_ms=float(entry.get("delay_ms", 0.0)),
                    match=str(entry.get("match", "")))
            except (TypeError, ValueError) as exc:
                raise FaultPlanError(f"spec #{i} malformed: {exc}")
            spec.validate()
            specs.append(spec)
        return cls(seed=seed, specs=tuple(specs))

    def doc(self) -> Dict[str, Any]:
        return {"seed": self.seed, "specs": [s.doc() for s in self.specs]}

    # -- the deterministic decision --------------------------------------
    def draw(self, site: str, kind: str, op: int) -> float:
        """Uniform [0, 1) draw for one (site, kind, op) — a pure function
        of the seed, independent of call order and process."""
        key = f"{self.seed}:{site}:{kind}:{op}".encode("ascii")
        return (zlib.crc32(key) & 0xFFFFFFFF) / 4294967296.0

    def due(self, spec: FaultSpec, op: int) -> bool:
        """Whether ``spec`` fires on its site's ``op``-th operation.
        Match-specs are payload-driven (the injector tests the payload);
        this covers the windowed/rated streams."""
        if spec.match:
            return False
        if op < spec.start_op:
            return False
        if spec.stop_op is not None and op >= spec.stop_op:
            return False
        if spec.rate >= 1.0:
            return True
        return self.draw(spec.site, spec.kind, op) < spec.rate

    def schedule(self, site: str, ops: int) -> List[Tuple[int, str]]:
        """The planned (op_index, kind) fault list for a site's first
        ``ops`` operations — computable with zero runtime state, which is
        exactly the replayability proof the chaos soak gates on: two
        fresh plans with the same seed produce identical schedules."""
        out: List[Tuple[int, str]] = []
        for op in range(ops):
            for spec in self.specs:
                if spec.site == site and self.due(spec, op):
                    out.append((op, spec.kind))
                    break
        return out
