"""dmfault: seeded, deterministic fault injection at the I/O boundaries.

The package splits into the pure plan (:mod:`plan` — seeded schedule,
validation, replayable decisions) and the armed runtime (:mod:`injector` —
counters, metrics, events, the actual raises/sleeps). Production pays one
branch per site: each instrumented boundary does::

    inj = faults._ACTIVE
    if inj is not None:
        ...  # fault check

and ``_ACTIVE`` is None unless an operator armed a plan via settings
(``fault_plan_file``) or ``POST /admin/faults``. Arming swaps a single
module-global reference (GIL-atomic), so sites racing an arm/disarm see
either the old injector or the new one, never a torn state.
"""
from __future__ import annotations

from typing import Any, Optional

from .injector import FaultInjected, FaultInjector
from .plan import SITES, FaultPlan, FaultPlanError, FaultSpec

__all__ = [
    "SITES", "FaultPlan", "FaultPlanError", "FaultSpec",
    "FaultInjected", "FaultInjector", "arm", "disarm", "active",
]

# the one production branch: None → every site is a no-op
_ACTIVE: Optional[FaultInjector] = None


def arm(plan: FaultPlan, **kwargs: Any) -> FaultInjector:
    """Arm ``plan`` process-wide; returns the live injector. Re-arming
    replaces the previous injector (its fired log is dropped — snapshot
    first if you need it)."""
    global _ACTIVE
    injector = FaultInjector(plan, **kwargs)
    _ACTIVE = injector
    return injector


def disarm() -> Optional[FaultInjector]:
    """Disarm fault injection; returns the injector that was active (so
    callers can keep its fired log as the run artifact)."""
    global _ACTIVE
    injector = _ACTIVE
    _ACTIVE = None
    return injector


def active() -> Optional[FaultInjector]:
    return _ACTIVE
