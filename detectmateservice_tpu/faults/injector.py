"""FaultInjector: the armed runtime that executes a :class:`FaultPlan`.

Production builds pay one branch per instrumented site — ``faults._ACTIVE``
is ``None`` unless an operator armed a plan (settings ``fault_plan_file`` or
``POST /admin/faults``), and every site guards its call with that check.
Armed, each site call advances that site's op counter, asks the plan
whether a fault is due, and executes it:

* filesystem sites raise the real ``OSError`` (``EIO``/``ENOSPC``) the
  disk would have raised — the degradation policy under test sees exactly
  the production failure shape;
* socket sites stall (``latency``), swallow (``drop``), or raise
  (``error`` → ``ECONNRESET``);
* the processor site raises :class:`FaultInjected` (``raise``/poison
  ``match``) or stalls (``slow``/``hang``) — an injected processor
  exception travels the same except-path a real model bug would.

Every executed fault is recorded in a bounded ``fired`` log —
``(site, kind, op)`` triples, the artifact the chaos soak compares against
the plan's precomputed schedule to prove determinism — counted on
``faults_injected_total{site,kind}``, and surfaced as a rate-limited
``fault_injected`` structured event.

Thread-safety: sites fire from the engine thread, the rollout thread
(checkpoint commits), and admin verbs; op counters and the fired log are
mutated under one small lock (only ever paid while armed — chaos runs, not
production). Sleeps happen outside the lock.
"""
from __future__ import annotations

import errno
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .plan import SITES, FaultPlan, FaultSpec

_ERRNOS = {"eio": errno.EIO, "enospc": errno.ENOSPC}
_EVENT_INTERVAL_S = 1.0      # per-site fault_injected event rate limit
_MAX_FIRED = 10000           # bounded fired log (schedule artifact)


class FaultInjected(RuntimeError):
    """An injected processor-dispatch fault (never raised unarmed)."""


class FaultInjector:
    def __init__(
        self,
        plan: FaultPlan,
        *,
        labels: Optional[Dict[str, str]] = None,
        events: Optional[Callable[[Dict[str, Any]], Any]] = None,
        logger: Optional[logging.Logger] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.plan = plan
        # the metric carries the standard component labels; a bare injector
        # (unit tests, scripts) gets a recognizable default pair
        self._labels = {"component_type": "faults", "component_id": "chaos"}
        self._labels.update(labels or {})
        self._events = events
        self._logger = logger or logging.getLogger("faults")
        self._sleep = sleep
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for spec in plan.specs:
            self._by_site.setdefault(spec.site, []).append(spec)
        self._lock = threading.Lock()
        self._ops: Dict[str, int] = {site: 0 for site in SITES}
        self.fired: List[Dict[str, Any]] = []
        self._fired_dropped = 0
        self._injected_total = 0
        self._last_event_t: Dict[str, float] = {}
        # hoisted metric children per (site, kind); lazy import so merely
        # importing the faults package (plan validation, docs tooling)
        # stays dependency-free
        self._m_injected: Dict[tuple, Any] = {}
        try:
            from ..engine import metrics as m

            self._metrics = m
        except ImportError:             # pragma: no cover - hermetic envs
            self._metrics = None

    # -- decision core ----------------------------------------------------
    def _advance(self, site: str) -> int:
        with self._lock:
            op = self._ops.get(site, 0)
            self._ops[site] = op + 1
        return op

    def _record(self, spec: FaultSpec, op: int) -> None:
        with self._lock:
            self._injected_total += 1
            if len(self.fired) < _MAX_FIRED:
                self.fired.append(
                    {"site": spec.site, "kind": spec.kind, "op": op})
            else:
                self._fired_dropped += 1
        if self._metrics is not None:
            child = self._m_injected.get((spec.site, spec.kind))
            if child is None:
                child = self._metrics.FAULTS_INJECTED().labels(
                    site=spec.site, kind=spec.kind, **self._labels)
                self._m_injected[(spec.site, spec.kind)] = child
            child.inc()
        now = time.monotonic()
        last = self._last_event_t.get(spec.site, -_EVENT_INTERVAL_S)
        if now - last >= _EVENT_INTERVAL_S:
            self._last_event_t[spec.site] = now
            event = {"kind": "fault_injected", "site": spec.site,
                     "fault_kind": spec.kind, "op": op,
                     "seed": self.plan.seed}
            if self._events is not None:
                self._events(event)
            else:
                self._logger.warning("fault_injected: %s", event)

    def _due(self, site: str) -> Optional[tuple]:
        op = self._advance(site)
        for spec in self._by_site.get(site, ()):
            if not spec.match and self.plan.due(spec, op):
                return spec, op
        return None

    # -- site entry points -------------------------------------------------
    def fs(self, site: str) -> bool:
        """Filesystem site: raises the injected ``OSError`` for eio/enospc;
        returns True when a ``torn`` commit is due (the caller aborts
        between temp write and rename), else False."""
        hit = self._due(site)
        if hit is None:
            return False
        spec, op = hit
        self._record(spec, op)
        if spec.kind == "torn":
            return True
        code = _ERRNOS[spec.kind]
        raise OSError(code, f"injected {spec.kind} at {site} op {op}")

    def sock(self, site: str) -> Optional[str]:
        """Socket site: sleeps through a latency fault (returns None),
        returns ``"drop"`` for a drop fault, raises ``OSError`` for
        error/partition faults."""
        hit = self._due(site)
        if hit is None:
            return None
        spec, op = hit
        self._record(spec, op)
        if spec.kind == "latency":
            if spec.delay_ms > 0:
                self._sleep(spec.delay_ms / 1000.0)
            return None
        if spec.kind == "drop":
            return "drop"
        raise OSError(errno.ECONNRESET,
                      f"injected socket error at {site} op {op}")

    def proc(self, frames: Sequence[bytes]) -> None:
        """Processor-dispatch site: raises :class:`FaultInjected` for a
        rate-based ``raise`` fault or any poison ``match`` hit, sleeps for
        slow/hang. Called with the chunk about to be dispatched — and again
        with single-frame chunks during poison isolation, where a match
        fires again by construction (that determinism is what drives the
        frame into the dead-letter queue instead of an endless retry)."""
        op = self._advance("proc")
        for spec in self._by_site.get("proc", ()):
            if spec.match:
                needle = spec.match.encode("utf-8")
                if any(needle in frame for frame in frames):
                    self._record(spec, op)
                    raise FaultInjected(
                        f"injected poison: payload matched {spec.match!r}")
            elif self.plan.due(spec, op):
                self._record(spec, op)
                if spec.kind == "raise":
                    raise FaultInjected(f"injected processor raise at op {op}")
                if spec.delay_ms > 0:       # slow / hang
                    self._sleep(spec.delay_ms / 1000.0)
                return

    # -- admin plane -------------------------------------------------------
    def snapshot(self, fired_tail: int = 100) -> Dict[str, Any]:
        with self._lock:
            ops = dict(self._ops)
            tail = list(self.fired[-fired_tail:])
            total = self._injected_total
            dropped = self._fired_dropped
        return {
            "armed": True,
            "plan": self.plan.doc(),
            "ops": {site: n for site, n in sorted(ops.items()) if n},
            "injected_total": total,
            "fired_logged": total - dropped,
            "fired_tail": tail,
        }

    def fired_schedule(self) -> List[Dict[str, Any]]:
        """The full (bounded) fired log — the committed chaos artifact."""
        with self._lock:
            return list(self.fired)
