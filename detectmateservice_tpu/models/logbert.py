"""LogBERT-style Transformer anomaly scorer (flax).

The neural scorer the reference lacks (its ML is classical; SURVEY.md §2.9
"the TPU build adds the neural scorer") and the BASELINE.json config #3
("detector w/ LogBERT-style Transformer anomaly scorer (jit, batch=32)").

Design, TPU-first:
* fixed [B, S] int32 inputs from the hashing tokenizer — no dynamic shapes,
* bfloat16 activations with fp32 logits/softmax accumulation (MXU-friendly),
* masked-token training on normal traffic (optax adamw); anomaly score at
  inference is the pseudo-negative-log-likelihood of the observed tokens, so
  one forward pass scores a whole micro-batch,
* attention goes through ops/attention so the blockwise/ring/pallas variants
  can be swapped in.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from ..ops.attention import attention
from .base import SequenceScorerBase, positional_z_max, token_nll  # noqa: F401 — token_nll/positional_z_max re-exported for compat
from .tokenizer import MASK_ID, PAD_ID


@dataclasses.dataclass(frozen=True)
class LogBERTConfig:
    vocab_size: int = 32768
    dim: int = 256
    depth: int = 4
    heads: int = 4
    mlp_ratio: int = 4
    seq_len: int = 32
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    mask_prob: float = 0.15
    learning_rate: float = 1e-3
    # 0 = mean NLL over all observed tokens; k > 0 = mean of the k most
    # surprising tokens (sharper for single-field anomalies)
    score_topk: int = 0
    # 0 = exact full-vocab NLL; 0 < C < vocab_size = candidate-vocab
    # approximation (models/base.py _token_nlls_candidate): ~V/C fewer head
    # FLOPs, the family's device bottleneck (66k → 262k lines/s at C=2048)
    score_vocab: int = 0
    # "auto" = pallas flash kernel on TPU for long sequences, fused einsum
    # otherwise; "einsum" | "flash" | "blockwise" force a path
    attn_impl: str = "auto"
    # candidate scoring-head implementation: "auto"/"einsum" = S-chunked
    # einsum + low-precision logsumexp (models/base.py); "pallas" = fused
    # online-logsumexp kernel that never materializes the [N, C] logits
    # (ops/scorehead.py — route here once measured faster on real chips)
    head_impl: str = "auto"


class Block(nn.Module):
    config: LogBERTConfig

    @nn.compact
    def __call__(self, x: jax.Array, pad_mask: jax.Array) -> jax.Array:
        cfg = self.config
        head_dim = cfg.dim // cfg.heads
        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        qkv = nn.Dense(3 * cfg.dim, dtype=cfg.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s, _ = q.shape
        reshape = lambda t: t.reshape(b, s, cfg.heads, head_dim).transpose(0, 2, 1, 3)
        out = attention(reshape(q), reshape(k), reshape(v),
                        key_mask=pad_mask, impl=cfg.attn_impl)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, cfg.dim)
        x = x + nn.Dense(cfg.dim, dtype=cfg.dtype, name="proj")(out)
        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        y = nn.Dense(cfg.dim * cfg.mlp_ratio, dtype=cfg.dtype, name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(cfg.dim, dtype=cfg.dtype, name="mlp_out")(y)
        return x + y


class LogBERT(nn.Module):
    config: LogBERTConfig

    def setup(self) -> None:
        cfg = self.config
        self.tok_embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype)
        self.pos_embed = self.param(
            "pos_embed", nn.initializers.normal(0.02), (cfg.seq_len, cfg.dim)
        )
        self.blocks = [Block(cfg) for _ in range(cfg.depth)]
        self.final_ln = nn.LayerNorm(dtype=cfg.dtype)

    def hidden(self, tokens: jax.Array) -> jax.Array:
        """[B, S] int32 → [B, S, D] fp32 final hidden states (pre-head).

        Exposed separately (``apply(..., method="hidden")``) so the scorer
        can compute NLLs in sequence chunks without ever materializing the
        [B, S, V] logits tensor — at V=32k and large micro-batches that
        tensor alone exceeds HBM (models/base.py chunked NLL)."""
        cfg = self.config
        pad_mask = tokens != PAD_ID
        x = self.tok_embed(tokens) + self.pos_embed[
            None, : tokens.shape[1]].astype(cfg.dtype)
        for blk in self.blocks:
            x = blk(x, pad_mask)
        return self.final_ln(x).astype(jnp.float32)

    def __call__(self, tokens: jax.Array) -> jax.Array:
        """[B, S] int32 → [B, S, V] fp32 logits (weight-tied head).

        The head is an explicit einsum with bf16 multiplies and fp32
        accumulation (MXU-native) rather than ``Embed.attend`` (bf16
        accumulation): fp32 logits keep the loss numerics stable and the
        formulation matches the chunked scoring path bit-for-bit.
        """
        cfg = self.config
        return jnp.einsum("bsd,vd->bsv", self.hidden(tokens).astype(cfg.dtype),
                          self.tok_embed.embedding.astype(cfg.dtype),
                          preferred_element_type=jnp.float32)


def masked_lm_loss(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    tok_lp = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    mask = mask.astype(jnp.float32)
    return -(tok_lp * mask).sum() / jnp.maximum(mask.sum(), 1.0)


class LogBERTScorer(SequenceScorerBase):
    """Masked-LM transformer scorer (jit wiring + NLL scoring from
    SequenceScorerBase; this class owns only the model and its loss)."""

    name = "logbert"

    def __init__(self, config: Optional[LogBERTConfig] = None):
        super().__init__(config or LogBERTConfig())

    def _build_model(self) -> LogBERT:
        return LogBERT(self.config)

    def _train_impl(self, params, opt_state, rng, tokens):
        cfg = self.config
        tokens = tokens.astype(jnp.int32)

        def loss_fn(p):
            mask_rng, _ = jax.random.split(rng)
            maskable = tokens != PAD_ID
            mask = (
                jax.random.uniform(mask_rng, tokens.shape) < cfg.mask_prob
            ) & maskable
            corrupted = jnp.where(mask, MASK_ID, tokens)
            logits = self.model.apply(p, corrupted)
            return masked_lm_loss(logits, tokens, mask)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss
