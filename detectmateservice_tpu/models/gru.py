"""DeepLog-style recurrent (GRU) next-token anomaly scorer (flax).

Third rung of the scorer ladder (mlp → gru → logbert). A causal next-token
language model over the hashed token stream: each position's token is
predicted from the learned prefix state, so the anomaly score is the true
autoregressive NLL of the sequence — the DeepLog formulation — rather than
the bag (mlp) or masked-LM pseudo-NLL (logbert). The reference has no
accelerator or sequence model at all (SURVEY.md §0 "no training, no
GPU/accelerator code"); this family exists because recurrent scorers catch
*order* anomalies (a valid token in the wrong place) that the bag model is
blind to, at ~1/4 of the transformer's FLOPs for short log sequences.

TPU-first design notes:
* fixed [B, S] int32 inputs; the time loop is ``flax.linen.RNN`` (lax.scan
  under jit — traced once, no Python-level unrolling, static shapes),
* per-step matmuls are [B, D]x[D, 3D] — batched and MXU-tiled; bfloat16
  activations with fp32 logits/log-softmax accumulation,
* weight-tied output head (``embed.attend``) keeps HBM traffic at one
  embedding table,
* the scan carries [B, D] per layer — tiny versus the transformer's
  [B, S, S] attention intermediates, so very large micro-batches fit.

Interface-compatible with MLPScorer/LogBERTScorer (score / train_step /
_score_impl / _token_nlls_impl / _normscore_impl / init), so the detector
(`library/detectors/jax_scorer.py`) and parallel.ShardedScorer compose with
it unchanged.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from .base import SequenceScorerBase
from .tokenizer import PAD_ID


@dataclasses.dataclass(frozen=True)
class GRUScorerConfig:
    vocab_size: int = 32768
    dim: int = 128
    depth: int = 1                    # stacked GRU layers
    seq_len: int = 32
    dtype: Any = jnp.bfloat16
    learning_rate: float = 2e-3
    # 0 = mean NLL over observed tokens; k > 0 = mean of the k most
    # surprising (same knob as LogBERTConfig.score_topk)
    score_topk: int = 0
    # candidate-vocab approximate NLL (same knob as LogBERTConfig.score_vocab)
    score_vocab: int = 0
    # candidate scoring-head implementation (same knob as LogBERTConfig)
    head_impl: str = "auto"


class GRULM(nn.Module):
    config: GRUScorerConfig

    def setup(self) -> None:
        cfg = self.config
        self.tok_embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype)
        self.bos_embed = self.param(
            "bos_embed", nn.initializers.normal(0.02), (cfg.dim,))
        self.rnns = [nn.RNN(nn.GRUCell(features=cfg.dim, dtype=cfg.dtype))
                     for _ in range(cfg.depth)]
        self.final_ln = nn.LayerNorm(dtype=cfg.dtype)

    def hidden(self, tokens: jax.Array) -> jax.Array:
        """[B, S] int32 → [B, S, D] fp32 causal hidden states (pre-head).

        Position t's state is computed from tokens[<t] plus a learned BOS
        embedding, so every position (including 0) has a real prediction and
        the per-position NLLs line up 1:1 with the input tokens — the same
        alignment contract positional_z_max and the calibration pass assume.
        Exposed separately for the chunked NLL path (models/base.py)."""
        cfg = self.config
        emb = self.tok_embed(tokens)             # [B, S, D]
        # teacher-forced shift-right: the input at step t is token t-1
        x = jnp.concatenate(
            [jnp.broadcast_to(self.bos_embed.astype(cfg.dtype),
                              (tokens.shape[0], 1, cfg.dim)),
             emb[:, :-1]], axis=1)
        for rnn in self.rnns:
            x = rnn(x)                           # lax.scan over time
        return self.final_ln(x).astype(jnp.float32)

    def __call__(self, tokens: jax.Array) -> jax.Array:
        """[B, S] int32 → [B, S, V] fp32 causal next-token logits
        (weight-tied einsum head, bf16 multiplies / fp32 accumulation —
        see LogBERT.__call__)."""
        cfg = self.config
        return jnp.einsum("bsd,vd->bsv", self.hidden(tokens).astype(cfg.dtype),
                          self.tok_embed.embedding.astype(cfg.dtype),
                          preferred_element_type=jnp.float32)


def causal_lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token NLL over all non-PAD positions (scalar)."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    tok_lp = jnp.take_along_axis(logprobs, tokens[..., None], axis=-1)[..., 0]
    mask = (tokens != PAD_ID).astype(jnp.float32)
    return -(tok_lp * mask).sum() / jnp.maximum(mask.sum(), 1.0)


class GRUScorer(SequenceScorerBase):
    """Causal GRU LM scorer (jit wiring + NLL scoring from
    SequenceScorerBase; this class owns only the model and its loss)."""

    name = "gru"

    def __init__(self, config: Optional[GRUScorerConfig] = None):
        super().__init__(config or GRUScorerConfig())

    def _build_model(self) -> GRULM:
        return GRULM(self.config)

    def _train_impl(self, params, opt_state, rng, tokens):
        del rng  # teacher forcing is deterministic; no corruption step
        tokens = tokens.astype(jnp.int32)

        def loss_fn(p):
            return causal_lm_loss(self.model.apply(p, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss
