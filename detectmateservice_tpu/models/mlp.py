"""Embedding + MLP bag-of-tokens NLL scorer — the lightweight TPU scorer.

First-rung model of the scorer ladder (SURVEY.md §7 step 5: "First scorer:
embedding+MLP; then the LogBERT-style Transformer"). A CBOW-style log-linear
language model: masked mean-pool of token embeddings → small MLP → weight-tied
logits over the vocab; the anomaly score is the mean NLL of the sequence's
observed tokens. Tokens never seen in training keep unaligned random
embeddings and draw low probability, so novelty shows up directly as surprise
— the same signal LogBERT's pseudo-NLL gives, at a fraction of the FLOPs
(one [B,D]×[D,V] matmul per batch, MXU-friendly).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from .base import ScorerBase, positional_z_max
from .tokenizer import PAD_ID


@dataclasses.dataclass(frozen=True)
class MLPScorerConfig:
    vocab_size: int = 32768
    dim: int = 128
    hidden: int = 256
    seq_len: int = 32
    dtype: Any = jnp.bfloat16
    learning_rate: float = 3e-3
    # scoring-head path: "auto"/"einsum" = weight-tied attend + log_softmax
    # ([B, V] logits materialize); "pallas" = fused online-logsumexp kernel
    # (ops/scorehead.py) + direct target dots — no [B, V] tensor in HBM
    head_impl: str = "auto"


class EmbedMLPModel(nn.Module):
    config: MLPScorerConfig

    def setup(self) -> None:
        cfg = self.config
        # explicit names preserve the param-tree layout of the original
        # nn.compact formulation (checkpoint compatibility, tree version 1)
        self.tok_embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                                  name="tok_embed")
        self.fc1 = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="Dense_0")
        self.fc2 = nn.Dense(cfg.dim, dtype=cfg.dtype, name="Dense_1")

    def hidden(self, tokens: jax.Array) -> jax.Array:
        """[B, S] int32 → [B, D] context vector (pre-head). Exposed via
        ``apply(..., method="hidden")`` so the pallas head can compute the
        logsumexp without materializing the [B, V] logits."""
        cfg = self.config
        emb = self.tok_embed(tokens)
        mask = (tokens != PAD_ID).astype(cfg.dtype)[..., None]
        pooled = (emb * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
        return self.fc2(nn.gelu(self.fc1(pooled)))

    def __call__(self, tokens: jax.Array) -> jax.Array:
        """[B, S] int32 → [B, V] fp32 logits (context token distribution)."""
        return self.tok_embed.attend(self.hidden(tokens).astype(jnp.float32))


def _masked_mean_nll(tok_lp: jax.Array, tokens: jax.Array) -> jax.Array:
    """[B, S] per-token log-probs → [B] mean NLL over non-PAD positions.
    The single home for the reduction both head implementations share —
    the parity tests and threshold calibration assume they stay locked."""
    mask = (tokens != PAD_ID).astype(jnp.float32)
    return -(tok_lp * mask).sum(-1) / jnp.maximum(mask.sum(-1), 1.0)


def bag_nll(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean NLL of each sequence's non-PAD tokens under its single context
    distribution → [B] fp32."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)           # [B, V]
    tok_lp = jnp.take_along_axis(logprobs, tokens, axis=-1)  # [B, S]
    return _masked_mean_nll(tok_lp, tokens)


class MLPScorer(ScorerBase):
    """Bag-of-tokens scorer. Jit wiring/init/score/train_step come from
    ScorerBase; the impls are custom because the model emits ONE context
    distribution per sequence ([B, V] logits), not per-position [B, S, V]."""

    name = "mlp"

    def __init__(self, config: Optional[MLPScorerConfig] = None):
        super().__init__(config or MLPScorerConfig())

    def _build_model(self) -> EmbedMLPModel:
        return EmbedMLPModel(self.config)

    def _pallas_token_logprobs(self, params, tokens: jax.Array) -> jax.Array:
        """[B, S] per-token log-probs via the fused head: lse from the
        online kernel (no [B, V] logits in HBM), target logits from direct
        h·emb[token] dots; bf16 multiplies with fp32 accumulation, like
        the sequence heads."""
        dtype = self.config.dtype
        h = self.model.apply(params, tokens, method="hidden").astype(dtype)
        emb = params["params"]["tok_embed"]["embedding"].astype(dtype)
        lse = self._pallas_lse_rows(h, emb)                     # [B]
        tgt = jnp.einsum("bsd,bd->bs", emb[tokens], h,
                         preferred_element_type=jnp.float32)
        return tgt - lse[:, None]

    def _use_pallas_head(self) -> bool:
        return getattr(self.config, "head_impl", "auto") == "pallas"

    def _score_impl(self, params, tokens: jax.Array) -> jax.Array:
        # tokens may arrive as uint16 (the half-width wire format the
        # detector uploads to cut host→device bandwidth); compute in int32
        tokens = tokens.astype(jnp.int32)
        if self._use_pallas_head():
            return _masked_mean_nll(
                self._pallas_token_logprobs(params, tokens), tokens)
        return bag_nll(self.model.apply(params, tokens), tokens)

    def _token_nlls_impl(self, params, tokens: jax.Array) -> jax.Array:
        """[B, S] per-position NLL under the bag context distribution."""
        tokens = tokens.astype(jnp.int32)
        if self._use_pallas_head():
            tok_lp = self._pallas_token_logprobs(params, tokens)
        else:
            logprobs = jax.nn.log_softmax(
                self.model.apply(params, tokens), axis=-1)
            tok_lp = jnp.take_along_axis(logprobs, tokens, axis=-1)  # [B, S]
        return -tok_lp * (tokens != PAD_ID).astype(jnp.float32)

    def _normscore_impl(self, params, tokens: jax.Array,
                        mu: jax.Array, sigma: jax.Array) -> jax.Array:
        tokens = tokens.astype(jnp.int32)
        return positional_z_max(self._token_nlls_impl(params, tokens),
                                tokens, mu, sigma)

    def _train_impl(self, params, opt_state, rng, tokens):
        del rng  # no stochastic corruption in the bag model
        tokens = tokens.astype(jnp.int32)

        def loss_fn(p):
            return bag_nll(self.model.apply(p, tokens), tokens).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss
