"""Embedding + MLP bag-of-tokens NLL scorer — the lightweight TPU scorer.

First-rung model of the scorer ladder (SURVEY.md §7 step 5: "First scorer:
embedding+MLP; then the LogBERT-style Transformer"). A CBOW-style log-linear
language model: masked mean-pool of token embeddings → small MLP → weight-tied
logits over the vocab; the anomaly score is the mean NLL of the sequence's
observed tokens. Tokens never seen in training keep unaligned random
embeddings and draw low probability, so novelty shows up directly as surprise
— the same signal LogBERT's pseudo-NLL gives, at a fraction of the FLOPs
(one [B,D]×[D,V] matmul per batch, MXU-friendly).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from .base import ScorerBase, positional_z_max
from .tokenizer import PAD_ID


@dataclasses.dataclass(frozen=True)
class MLPScorerConfig:
    vocab_size: int = 32768
    dim: int = 128
    hidden: int = 256
    seq_len: int = 32
    dtype: Any = jnp.bfloat16
    learning_rate: float = 3e-3


class EmbedMLPModel(nn.Module):
    config: MLPScorerConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        """[B, S] int32 → [B, V] fp32 logits (context token distribution)."""
        cfg = self.config
        embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype, name="tok_embed")
        emb = embed(tokens)
        mask = (tokens != PAD_ID).astype(cfg.dtype)[..., None]
        pooled = (emb * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
        h = nn.Dense(cfg.hidden, dtype=cfg.dtype)(pooled)
        h = nn.gelu(h)
        h = nn.Dense(cfg.dim, dtype=cfg.dtype)(h)
        return embed.attend(h.astype(jnp.float32))  # weight-tied output head


def bag_nll(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean NLL of each sequence's non-PAD tokens under its single context
    distribution → [B] fp32."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)           # [B, V]
    tok_lp = jnp.take_along_axis(logprobs, tokens, axis=-1)  # [B, S]
    mask = (tokens != PAD_ID).astype(jnp.float32)
    return -(tok_lp * mask).sum(-1) / jnp.maximum(mask.sum(-1), 1.0)


class MLPScorer(ScorerBase):
    """Bag-of-tokens scorer. Jit wiring/init/score/train_step come from
    ScorerBase; the impls are custom because the model emits ONE context
    distribution per sequence ([B, V] logits), not per-position [B, S, V]."""

    name = "mlp"

    def __init__(self, config: Optional[MLPScorerConfig] = None):
        super().__init__(config or MLPScorerConfig())

    def _build_model(self) -> EmbedMLPModel:
        return EmbedMLPModel(self.config)

    def _score_impl(self, params, tokens: jax.Array) -> jax.Array:
        # tokens may arrive as uint16 (the half-width wire format the
        # detector uploads to cut host→device bandwidth); compute in int32
        tokens = tokens.astype(jnp.int32)
        return bag_nll(self.model.apply(params, tokens), tokens)

    def _token_nlls_impl(self, params, tokens: jax.Array) -> jax.Array:
        """[B, S] per-position NLL under the bag context distribution."""
        tokens = tokens.astype(jnp.int32)
        logprobs = jax.nn.log_softmax(self.model.apply(params, tokens), axis=-1)
        tok_lp = jnp.take_along_axis(logprobs, tokens, axis=-1)  # [B, S]
        return -tok_lp * (tokens != PAD_ID).astype(jnp.float32)

    def _normscore_impl(self, params, tokens: jax.Array,
                        mu: jax.Array, sigma: jax.Array) -> jax.Array:
        tokens = tokens.astype(jnp.int32)
        return positional_z_max(self._token_nlls_impl(params, tokens),
                                tokens, mu, sigma)

    def _train_impl(self, params, opt_state, rng, tokens):
        del rng  # no stochastic corruption in the bag model
        tokens = tokens.astype(jnp.int32)

        def loss_fn(p):
            return bag_nll(self.model.apply(p, tokens), tokens).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss
