from .tokenizer import HashTokenizer, PAD_ID, MASK_ID, CLS_ID
from .mlp import MLPScorer, MLPScorerConfig, EmbedMLPModel
from .gru import GRUScorer, GRUScorerConfig, GRULM
from .logbert import LogBERTScorer, LogBERTConfig, LogBERT

__all__ = [
    "HashTokenizer", "PAD_ID", "MASK_ID", "CLS_ID",
    "MLPScorer", "MLPScorerConfig", "EmbedMLPModel",
    "GRUScorer", "GRUScorerConfig", "GRULM",
    "LogBERTScorer", "LogBERTConfig", "LogBERT",
]
