"""Shared scorer scaffolding: jit wiring, init, and the per-position NLL
scoring contract the detector and parallel.ShardedScorer program against.

Every scorer family (mlp / gru / logbert) exposes the same surface —
``init``, ``score``, ``train_step``, and the jitted ``_score_impl`` /
``_token_nlls_impl`` / ``_normscore_impl`` — so the execution layers are
model-agnostic. The wire-format contract lives here exactly once: token
batches may arrive as uint16 (the half-width upload format that halves the
dominant tunneled-TPU transfer cost) and every impl casts back to int32 as
its first op.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import optax

from .tokenizer import PAD_ID


def reduce_nlls(nlls: jax.Array, mask: jax.Array, topk: int = 0) -> jax.Array:
    """[B, S] per-position NLLs (PAD = 0) + fp32 mask → [B] sequence score.

    ``topk > 0`` averages only the k most surprising tokens instead of all
    of them — a log line that is normal except for one injected value should
    score on the anomaly, not have it diluted across the other ~30 tokens.
    The single home of this reduction: token_nll (calibration/tests) and
    SequenceScorerBase._score_impl (the chunked hot path) both call it, so
    the two can never desynchronize.
    """
    if topk > 0:
        k = min(topk, nlls.shape[-1])
        top = jax.lax.top_k(nlls, k)[0]
        denom = jnp.minimum(jnp.maximum(mask.sum(-1), 1.0), float(k))
        return top.sum(-1) / denom
    return nlls.sum(-1) / jnp.maximum(mask.sum(-1), 1.0)


def token_nll(logits: jax.Array, tokens: jax.Array, topk: int = 0) -> jax.Array:
    """Per-sequence NLL of the observed non-PAD tokens → [B] fp32.

    This is the anomaly score: a model trained on normal traffic assigns
    high NLL (= surprise) to unseen token patterns.
    """
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    tok_lp = jnp.take_along_axis(logprobs, tokens[..., None], axis=-1)[..., 0]
    mask = (tokens != PAD_ID).astype(jnp.float32)
    return reduce_nlls(-tok_lp * mask, mask, topk)  # PAD positions are 0


def positional_z_max(nlls: jax.Array, tokens: jax.Array,
                     mu: jax.Array, sigma: jax.Array) -> jax.Array:
    """Per-position-normalized anomaly score: max over positions of
    ``(NLL - mu_pos) / sigma_pos`` → [B] fp32.

    ``mu``/``sigma`` [S] are calibrated on training traffic. High-entropy
    positions (random pids, timestamps) get large sigma and self-suppress;
    low-entropy positions (process names, paths) get small sigma, so an
    unseen value there produces a large z — the signal a plain sequence-mean
    NLL dilutes across the other ~30 tokens. All-PAD rows score 0.
    """
    mask = tokens != PAD_ID
    z = (nlls - mu) / sigma
    z = jnp.where(mask, z, -jnp.inf)
    zmax = jnp.max(z, axis=-1)
    # -inf only means an all-PAD row (score 0); +inf is a maximally
    # anomalous token (NLL overflow) and must stay an alert, not become 0
    return jnp.where(jnp.isneginf(zmax), 0.0, zmax)


class ScorerBase:
    """Owns the optimizer, jit wiring, and public score/train surface.

    Subclasses provide ``name``, ``_build_model()``, ``_train_impl`` and the
    three scoring impls (or inherit them from SequenceScorerBase).
    """

    name = "base"

    def __init__(self, config: Any):
        self.config = config
        self.model = self._build_model()
        self.optimizer = optax.adamw(config.learning_rate)
        self._score = jax.jit(self._score_impl)
        self._train = jax.jit(self._train_impl)
        self._token_nlls = jax.jit(self._token_nlls_impl)
        self._normscore = jax.jit(self._normscore_impl)

    # -- subclass hooks -------------------------------------------------
    def _build_model(self):
        raise NotImplementedError

    def _train_impl(self, params, opt_state, rng, tokens):
        raise NotImplementedError

    def _score_impl(self, params, tokens: jax.Array) -> jax.Array:
        raise NotImplementedError

    def _token_nlls_impl(self, params, tokens: jax.Array) -> jax.Array:
        raise NotImplementedError

    def _normscore_impl(self, params, tokens: jax.Array,
                        mu: jax.Array, sigma: jax.Array) -> jax.Array:
        raise NotImplementedError

    # -- shared surface -------------------------------------------------
    @staticmethod
    def _pallas_lse_rows(rows: jax.Array, emb_matrix: jax.Array) -> jax.Array:
        """[N] logsumexp of rows·emb_matrixᵀ via the fused kernel
        (ops/scorehead.py): the [N, V] logits never leave VMEM. The ONE
        home for the lazy import + interpret-on-CPU routing, shared by
        every ``head_impl: pallas`` path (mlp context vectors and the
        sequence models' flattened hidden states alike)."""
        from ..ops.scorehead import candidate_lse

        on_tpu = any(dev.platform == "tpu" for dev in jax.devices())
        return candidate_lse(rows, emb_matrix, interpret=not on_tpu)

    def init(self, rng: jax.Array) -> Tuple[Any, Any]:
        dummy = jnp.zeros((1, self.config.seq_len), jnp.int32)
        params = self.model.init(rng, dummy)
        return params, self.optimizer.init(params)

    def score(self, params, tokens) -> jax.Array:
        return self._score(params, tokens)

    def train_step(self, params, opt_state, rng, tokens):
        return self._train(params, opt_state, rng, tokens)


class SequenceScorerBase(ScorerBase):
    """Scoring impls for models with per-position predictions (gru, logbert):
    anomaly score = (top-k) mean NLL of the observed tokens.

    NLLs are computed in **sequence chunks** against the model's [B, S, D]
    hidden states (``model.hidden``) instead of taking the [B, S, V] logits
    tensor from ``__call__``: at V=32k a 16k-row micro-batch's logits alone
    are 64 GB — far past HBM — while the chunked path's high-water mark is
    B×Sc×V with Sc chosen to fit. Training keeps the direct logits path
    (train batches are small); scoring is where the big batches live.
    """

    # fp32 elements the per-chunk logits may occupy (~1 GB); the largest
    # divisor of S that fits becomes the chunk length
    _CHUNK_ELEMENT_BUDGET = 1 << 28

    def _score_impl(self, params, tokens: jax.Array) -> jax.Array:
        # tokens may arrive as uint16 (half-width wire format); int32 inside
        tokens = tokens.astype(jnp.int32)
        nlls = self._token_nlls_impl(params, tokens)
        mask = (tokens != PAD_ID).astype(jnp.float32)
        return reduce_nlls(nlls, mask, getattr(self.config, "score_topk", 0))

    def _candidate_ids(self, vocab: int, n: int) -> jax.Array:
        """Fixed, seeded candidate-vocab subset for approximate scoring.

        Deterministic for a given (vocab, n) so the threshold calibrated by
        ``fit`` and every later detect call — including after a checkpoint
        restore — score with the SAME approximation; the subset constant
        folds into the jitted program."""
        import numpy as np

        cached = getattr(self, "_cand_cache", None)
        if cached is None or cached[0] != (vocab, n):
            ids = np.random.default_rng(0x5EED).choice(vocab, size=n,
                                                       replace=False)
            # cache NUMPY, not a jnp array: jnp values materialized inside a
            # jit trace are tracers, and caching one on self leaks it into
            # later traces (UnexpectedTracerError); numpy constant-folds
            # cleanly into every program that uses it
            self._cand_cache = ((vocab, n), np.sort(ids).astype(np.int32))
        return self._cand_cache[1]

    def _token_nlls_impl(self, params, tokens: jax.Array) -> jax.Array:
        """[B, S] per-position NLL (PAD positions → 0).

        Two paths, one contract:

        * exact — full-vocab logits in sequence chunks (below),
        * candidate-vocab (``score_vocab`` in (0, V)) — the logsumexp is
          estimated over a fixed seeded subset C of the vocab with the
          uniform-proposal correction ``+ log(V/|C|)``, while the target
          token's logit stays EXACT (direct hidden·emb[target] dot). Head
          FLOPs drop V/|C|-fold (the chunked full head is the sequence
          families' device bottleneck: measured 247 ms vs 63 ms per 16k×32
          batch at V=32k, C=2048, i.e. 66k → 262k lines/s on one v5e).
          Scores are approximate but CONSISTENTLY so — calibration (fit)
          and detection use the same subset, so the threshold stays in the
          same units; measured corr(exact, approx) ≈ 0.995.
        """
        tokens = tokens.astype(jnp.int32)
        dtype = getattr(self.config, "dtype", jnp.bfloat16)
        score_vocab = int(getattr(self.config, "score_vocab", 0) or 0)
        if score_vocab > 0:
            return self._token_nlls_candidate(params, tokens, dtype,
                                              score_vocab)
        return self._token_nlls_exact(params, tokens, dtype)

    @classmethod
    def _pallas_lse(cls, hidden: jax.Array, emb_matrix: jax.Array) -> jax.Array:
        """[B, S] logsumexp of hidden·emb_matrixᵀ — the sequence-model view
        over ScorerBase._pallas_lse_rows."""
        b, s, d = hidden.shape
        return cls._pallas_lse_rows(hidden.reshape(b * s, d),
                                    emb_matrix).reshape(b, s)

    @staticmethod
    def _lse_low_precision(logits, dtype) -> jax.Array:
        """logsumexp with the exp in the model's compute dtype and the SUM
        reduced in fp32 (the r3 roofline's "bf16 logsumexp, fp32 reduce"
        lever): the candidate head is VPU-softmax-bound, and bf16 exp runs
        the elementwise pass at twice the lane width. The max is subtracted
        first (standard stabilization) so bf16's ~3-digit mantissa applies
        to values in (-inf, 0] — measured NLL drift vs the fp32 lse is
        <1e-2 nats, far under the sigma-scale thresholds, and fit/detect
        share the path so the units stay consistent."""
        m = jnp.max(logits, axis=-1, keepdims=True)
        e = jnp.exp((logits - m).astype(dtype))
        s = jnp.sum(e, axis=-1, dtype=jnp.float32)  # fp32 accumulator
        return jnp.log(s) + m[..., 0].astype(jnp.float32)

    def _token_nlls_candidate(self, params, tokens: jax.Array, dtype,
                              n_cand: int) -> jax.Array:
        emb = params["params"]["tok_embed"]["embedding"]
        v = emb.shape[0]
        if n_cand >= v:
            return self._token_nlls_exact(params, tokens, dtype)
        hidden = self.model.apply(params, tokens, method="hidden").astype(dtype)
        emb = emb.astype(dtype)
        emb_c = emb[self._candidate_ids(v, n_cand)]     # [C, D]
        correction = jnp.log(float(v) / n_cand)
        # exact target logit: direct dot against the gathered target rows
        tgt = jnp.einsum("bsd,bsd->bs", hidden, emb[tokens],
                         preferred_element_type=jnp.float32)
        b, s, d = hidden.shape
        if getattr(self.config, "head_impl", "auto") == "pallas":
            # fused online-logsumexp kernel: the [N, C] logits never touch
            # HBM; no S-chunking needed — the kernel's working set is one
            # (block_n × block_c) tile in VMEM
            lse = self._pallas_lse(hidden, emb_c) + correction
            return -(tgt - lse) * (tokens != PAD_ID).astype(jnp.float32)
        # the [B, Sc, C] candidate logits are stored in the compute dtype
        # (bf16 halves their HBM footprint → Sc doubles per chunk vs fp32,
        # the "larger S-chunks" lever); MXU accumulation is fp32 either way
        elem_bytes = jnp.dtype(dtype).itemsize
        budget = self._CHUNK_ELEMENT_BUDGET * 4 // max(1, elem_bytes)
        sc = max(1, min(s, budget // max(1, b * n_cand)))
        while s % sc:
            sc -= 1
        n_chunks = s // sc
        if n_chunks == 1:
            logits_c = jnp.einsum("bsd,cd->bsc", hidden, emb_c,
                                  preferred_element_type=dtype)
            lse = self._lse_low_precision(logits_c, dtype) + correction
        else:
            h = hidden.reshape(b, n_chunks, sc, d).transpose(1, 0, 2, 3)

            def step(carry, h_c):
                logits_c = jnp.einsum("bsd,cd->bsc", h_c, emb_c,
                                      preferred_element_type=dtype)
                return carry, self._lse_low_precision(logits_c, dtype)

            _, lse = jax.lax.scan(step, None, h)        # [n_chunks, B, Sc]
            lse = lse.transpose(1, 0, 2).reshape(b, s) + correction
        return -(tgt - lse) * (tokens != PAD_ID).astype(jnp.float32)

    def _token_nlls_exact(self, params, tokens: jax.Array, dtype) -> jax.Array:
        """Full-vocab per-position NLL, chunked over S.

        bf16 multiplies with fp32 accumulation (MXU-native); identical
        formulation to the models' __call__ head so full and chunked
        paths agree bit-for-bit. ``head_impl: pallas`` swaps the chunked
        einsum+lse for the fused online-logsumexp kernel — the [B, Sc, V]
        logits (the exact path's HBM high-water) never materialize; the
        target logit comes from the equivalent direct hidden·emb[token]
        dot."""
        hidden = self.model.apply(params, tokens, method="hidden").astype(dtype)
        emb = params["params"]["tok_embed"]["embedding"].astype(dtype)
        b, s, d = hidden.shape
        v = emb.shape[0]
        if getattr(self.config, "head_impl", "auto") == "pallas":
            lse = self._pallas_lse(hidden, emb)
            tgt = jnp.einsum("bsd,bsd->bs", hidden, emb[tokens],
                             preferred_element_type=jnp.float32)
            return -(tgt - lse) * (tokens != PAD_ID).astype(jnp.float32)
        sc = max(1, min(s, self._CHUNK_ELEMENT_BUDGET // max(1, b * v)))
        while s % sc:
            sc -= 1
        n_chunks = s // sc
        if n_chunks == 1:
            logits = jnp.einsum("bsd,vd->bsv", hidden, emb,
                                preferred_element_type=jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, tokens[..., None], axis=-1)[..., 0]
            return -(tgt - lse) * (tokens != PAD_ID).astype(jnp.float32)
        h = hidden.reshape(b, n_chunks, sc, d).transpose(1, 0, 2, 3)
        t = tokens.reshape(b, n_chunks, sc).transpose(1, 0, 2)

        def step(carry, ht):
            h_c, t_c = ht
            logits = jnp.einsum("bsd,vd->bsv", h_c, emb,
                                preferred_element_type=jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
            return carry, tgt - lse  # [B, Sc] log-probs

        _, lp = jax.lax.scan(step, None, (h, t))
        lp = lp.transpose(1, 0, 2).reshape(b, s)
        return -lp * (tokens != PAD_ID).astype(jnp.float32)

    def _normscore_impl(self, params, tokens: jax.Array,
                        mu: jax.Array, sigma: jax.Array) -> jax.Array:
        tokens = tokens.astype(jnp.int32)
        return positional_z_max(self._token_nlls_impl(params, tokens),
                                tokens, mu, sigma)
