"""Hashing tokenizer: log text → fixed-shape int32 token ids.

The TPU scorer path needs *fixed shapes* out of ragged log lines (SURVEY.md §7
hard part #2). A feature-hashing tokenizer needs no vocabulary file, is
deterministic across processes/restarts, and is cheap enough for the
per-message CPU featurization stage. PAD=0, MASK=1, CLS=2 are reserved.
"""
from __future__ import annotations

import re
import zlib
from typing import List, Optional, Sequence

import numpy as np

PAD_ID = 0
MASK_ID = 1
CLS_ID = 2
_RESERVED = 3

_SPLIT_RE = re.compile(r"[^A-Za-z0-9]+")


def _hash_token(token: str, vocab_size: int) -> int:
    return _RESERVED + zlib.crc32(token.encode("utf-8")) % (vocab_size - _RESERVED)


def narrow_tokens(array: np.ndarray, vocab_size: int) -> np.ndarray:
    """Narrow an int32 token batch to the uint16 wire format when the vocab
    fits (ids max out at vocab_size-1). Host→device bandwidth is the measured
    hot-path bottleneck on tunneled TPUs (~90 ms per 4 MB batch), so every
    upload site narrows through this one rule and the jitted scorer impls
    cast back to int32 on device. Non-int32 input is returned unchanged."""
    if array.dtype == np.int32 and vocab_size <= 65536:
        return array.astype(np.uint16)
    return array


class HashTokenizer:
    def __init__(self, vocab_size: int = 32768, seq_len: int = 32,
                 lowercase: bool = True):
        if vocab_size <= _RESERVED:
            raise ValueError("vocab_size too small")
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.lowercase = lowercase

    def tokens(self, text: str) -> List[str]:
        if self.lowercase:
            text = text.lower()
        return [t for t in _SPLIT_RE.split(text) if t]

    def encode(self, text: str) -> np.ndarray:
        """One line → [seq_len] int32, CLS-prefixed, PAD-padded/truncated."""
        ids = [CLS_ID]
        for tok in self.tokens(text):
            ids.append(_hash_token(tok, self.vocab_size))
            if len(ids) >= self.seq_len:
                break
        out = np.full((self.seq_len,), PAD_ID, dtype=np.int32)
        out[: len(ids)] = ids
        return out

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        """Batch of lines → [B, seq_len] int32."""
        out = np.zeros((len(texts), self.seq_len), dtype=np.int32)
        for i, text in enumerate(texts):
            self.encode_into(text, out[i])
        return out

    def encode_into(self, text: str, out_row: np.ndarray) -> None:
        """Encode one line into a preallocated zeroed [seq_len] row.

        Hot-path variant: no per-message array allocation (the profile showed
        per-row ``np.full`` + wrapper overhead costing ~2/3 of featurization).
        """
        crc = zlib.crc32
        vocab = self.vocab_size - _RESERVED
        seq_len = self.seq_len
        if self.lowercase:
            text = text.lower()
        i = 1
        out_row[0] = CLS_ID
        for tok in _SPLIT_RE.split(text):
            if tok:
                out_row[i] = _RESERVED + crc(tok.encode("utf-8")) % vocab
                i += 1
                if i >= seq_len:
                    return

    def encode_parsed(self, template: str, variables: Sequence[str],
                      header_variables: Optional[dict] = None) -> np.ndarray:
        """ParserSchema content → [seq_len] int32 (template tokens carry the
        event structure; variable values carry the anomaly signal)."""
        parts = [template] + list(variables)
        if header_variables:
            parts.extend(f"{k}={v}" for k, v in sorted(header_variables.items()))
        return self.encode(" ".join(parts))
