"""Weight-only int8 quantization for the serving path (dmwarm, PR 17).

``dtype: int8w`` stores the scorer's large weight tensors as int8 plus a
per-output-channel float32 scale and dequantizes INSIDE the jitted impls.
The matmuls stay in the model's float compute dtype (bf16 on accelerators,
f32 on CPU-sim) — the win is weight *streaming*: an int8 embedding/kernel
moves 4× fewer bytes than f32 through the memory hierarchy, and the
detector's dominant GEMM (dim × vocab logits) is weight-bandwidth-bound.
Measured on CPU-sim: ~1.9× on the logits GEMM vs the f32/bf16 weight path.

Representation: every param leaf becomes a tuple —
``(q_int8, scale_f32)`` for quantized leaves, ``(w,)`` passthrough for the
small ones (biases, norms). Tuples are pytree containers, so the quantized
tree jits/shards like any other tree; ``dequantize_tree`` rebuilds a tree
with the original structure for the unmodified model impls.

The swap is gated by a differential-parity harness in the detector
(library/detectors/jax_scorer.py ``_activate_int8``): the quantized path
must produce ZERO alert-decision flips on the parity corpus or the float
path stays live.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

# leaves below this element count ride through unquantized: biases and
# norm vectors are a rounding error of the weight bytes, and quantizing
# them adds decision noise for no bandwidth win
QUANT_MIN_SIZE = 1024

# symmetric int8: scales map the per-channel absmax onto +/-127
_QMAX = 127.0


def _is_quant_leaf(x: Any) -> bool:
    return isinstance(x, tuple)


def eligible(leaf: Any) -> bool:
    """Whether a param leaf gets int8 storage: a float tensor with a
    channel structure (ndim >= 2) and enough elements to matter."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return False
    import numpy as np

    if not np.issubdtype(np.dtype(dtype), np.floating):
        return False
    size = 1
    for d in shape:
        size *= int(d)
    return len(shape) >= 2 and size >= QUANT_MIN_SIZE


def quantize_tree(params: Any) -> Any:
    """Float param tree → quantized tree of ``(q, scale)`` / ``(w,)``
    tuples. Scales are per-channel over the LAST axis (Dense kernels are
    [in, out] and the embedding is [vocab, dim], so the last axis is the
    output-channel axis for both)."""
    import jax
    import jax.numpy as jnp

    def _quantize(w):
        if not eligible(w):
            return (w,)
        w32 = jnp.asarray(w, jnp.float32)
        amax = jnp.max(jnp.abs(w32), axis=tuple(range(w32.ndim - 1)))
        # floor: an all-zero channel quantizes to zeros with scale 1 instead
        # of dividing by zero
        scale = jnp.maximum(amax, 1e-8) / _QMAX
        q = jnp.clip(jnp.round(w32 / scale), -_QMAX, _QMAX).astype(jnp.int8)
        return (q, scale.astype(jnp.float32))

    return jax.tree_util.tree_map(_quantize, params)


def dequantize_tree(qtree: Any, dtype: Any) -> Any:
    """Quantized tree → float tree in ``dtype`` (traceable: runs inside the
    jitted score impls, where XLA fuses the dequant into weight streaming)."""
    import jax
    import jax.numpy as jnp

    def _dequantize(leaf):
        if len(leaf) == 1:
            return leaf[0]
        q, scale = leaf
        return q.astype(dtype) * scale.astype(dtype)

    return jax.tree_util.tree_map(_dequantize, qtree,
                                  is_leaf=_is_quant_leaf)


def quant_shardings(params: Any, shardings: Any, mesh: Any) -> Any:
    """Sharding tree for ``quantize_tree(params)`` on a mesh: the int8
    payload shards exactly like its float leaf; the per-channel scale
    shards along the leaf's LAST-axis placement (a TP-sharded [in, out]
    kernel has TP-sharded [out] scales)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def _shard(w, s):
        if not eligible(w):
            return (s,)
        spec = tuple(getattr(s, "spec", ()) or ())
        ndim = len(getattr(w, "shape", ()))
        last = spec[-1] if len(spec) >= ndim and ndim > 0 else None
        return (s, NamedSharding(mesh, PartitionSpec(last)))

    return jax.tree_util.tree_map(_shard, params, shardings)


def quant_stats(qtree: Any) -> Dict[str, Any]:
    """Byte accounting for logs / reports: how much weight traffic the
    int8 representation removed."""
    import jax
    import numpy as np

    stats = {"quantized_leaves": 0, "passthrough_leaves": 0,
             "int8_bytes": 0, "float_bytes": 0}

    def _count(leaf):
        if len(leaf) == 1:
            stats["passthrough_leaves"] += 1
            w = leaf[0]
            stats["float_bytes"] += int(np.prod(w.shape)) * w.dtype.itemsize
        else:
            q, scale = leaf
            stats["quantized_leaves"] += 1
            stats["int8_bytes"] += int(np.prod(q.shape))
            stats["float_bytes"] += int(np.prod(scale.shape)) * 4
        return leaf

    jax.tree_util.tree_map(_count, qtree, is_leaf=_is_quant_leaf)
    return stats
