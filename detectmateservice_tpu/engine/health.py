"""Per-process self-diagnosis: heartbeats, watchdog checks, structured events.

The data plane is a set of opaque loops — the engine hot loop, the output
fan-out pump, the detector's dispatch/upload workers — and before this module
a wedged loop looked identical to an idle one (``engine_running`` only knows
"running"/"stopped"). Following Dapper's rule that cross-cutting telemetry
must ride the hot path at near-zero cost, the instrumentation contract is:

* each loop stamps a :class:`Heartbeat` — ONE monotonic clock write per
  iteration, no locks, no allocation — and
* a single watchdog thread per service derives per-subsystem checks from the
  stamps with hysteresis (degrade immediately, recover only after N clean
  intervals so a flapping loop cannot strobe alerts), rolling them into the
  ``engine_health_state`` Enum and ``engine_heartbeat_age_seconds{loop=...}``
  gauges (engine/metrics.py).

The four derived checks:

* ``process_wedged``   — the engine loop stopped cycling (stuck inside
  ``process()`` or a hard-blocked recv). Suppressed while the output pump is
  actively waiting: a loop blocked in flow control is *saturated*, not
  wedged, and must be attributed to the output check.
* ``ingest_stalled``   — no ingress frame for a while. Informational by
  default (an idle pipeline is healthy); set
  ``watchdog_ingest_stall_seconds > 0`` on stages that are supposed to see
  continuous traffic to make silence a degradation.
* ``output_saturated`` — the block-backpressure pump has been waiting on a
  full peer queue continuously (gauge twin: ``output_send_backlog``).
* ``device_inflight_stuck`` — the detector holds in-flight scored batches
  and its drain counter has not moved (a stuck device queue / readback).

Every check transition (and the roll-up state transition) is emitted as a
structured JSON event — component id, stage, check, old/new status, detail,
and the most recent trace id from the PR-1 flight recorder — into a bounded
in-memory :class:`EventLog` ring served at ``GET /admin/events``, and through
the component logger (as real JSON lines when ``log_format: json``).
"""
from __future__ import annotations

import json
import logging
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import metrics as m
from . import tracing

# check / roll-up status values, in increasing severity
PASS = "pass"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
_SEVERITY = {PASS: 0, DEGRADED: 1, UNHEALTHY: 2}

HEALTHY = "healthy"  # roll-up name for "every check passes"

# The canonical registry of structured-event ``kind``s — every
# ``emit_event`` payload's kind MUST be declared here. dmlint's DM-E rules
# (analysis/contracts.py) parse this table and hold it in both directions
# against the literal kinds at the emit sites, the event-kind reference in
# docs/prometheus.md, and the kinds scripts/soak.py scenarios gate on — an
# event renamed at its emit site but not here (or vice versa) fails the
# gate instead of silently breaking a soak scenario's verdict.
# tests/test_health.py derives its known-kind set from this registry, the
# same pattern test_observability.py uses for REGISTERED_SERIES.
EVENT_KINDS = {
    "health_transition": "a watchdog check (or the roll-up state) changed",
    "log": "a WARNING+ log record mirrored into the event ring",
    "thread_exception": "an uncaught exception in any thread",
    "unexpected_recompile": "XLA compiled a bucket believed warm",
    "replica_drain": "router stopped dispatching to a failing replica",
    "replica_drained": "a draining replica settled (clean or by timeout)",
    "replica_recovering": "a drained replica's probe recovered; re-dialing",
    "replica_restarted": "a replica process restart observed between polls",
    "replica_undrain": "a recovered replica resumed dispatch",
    "model_candidate_ready": "a rollout cycle produced a shadow candidate",
    "model_promoted": "a candidate passed the gate and was hot-swapped in",
    "model_rolled_back": "the previous live model version was restored",
    "model_canary_holdback": "the shadow gate rejected a candidate",
    "model_pinned": "an operator pinned the served model version",
    "model_unpinned": "an operator lifted the model pin",
    "drift_detected": "the live score distribution diverged from the "
                      "pinned baseline past the hysteresis gate",
    "drift_cleared": "drift stats returned under threshold (typically "
                     "after a promote re-pinned the baseline)",
    "drift_baseline_pinned": "the drift monitor (re)pinned its reference "
                             "score distribution (boot, resume, promote)",
    "drift_cycle": "sustained drift pulled a rollout cycle forward of "
                   "its interval clock",
    "load_shed": "ingress admission control refused frames (tenant over "
                 "quota, or its tier gated by the degradation ladder)",
    "shed_ladder_transition": "the overload degradation ladder changed state",
    "wal_degraded": "the ingress spool hit (or recovered from) a disk "
                    "error; state says degraded or restored",
    "frame_quarantined": "a poison frame exhausted its attempts and moved "
                         "to the dead-letter queue",
    "fault_injected": "an armed fault plan executed a fault at an "
                      "instrumented site (chaos runs only)",
    "faults_armed": "a seeded fault-injection plan was armed (settings "
                    "file or POST /admin/faults)",
    "telemetry_export_degraded": "the engine-side span exporter is shedding "
                                 "spans (bounded queue full or the "
                                 "telemetry link is down); traces assembled "
                                 "by the collector will be incomplete",
}


class Heartbeat:
    """A loop's liveness stamp. ``beat()`` is the whole hot-path cost: one
    monotonic clock read + one attribute store (atomic under the GIL — the
    watchdog thread reads it without a lock by design)."""

    __slots__ = ("name", "last", "waiting", "waiting_since")

    def __init__(self, name: str) -> None:
        now = time.monotonic()
        self.name = name
        self.last = now
        # flow-control wait state (output pump): while ``waiting`` the loop
        # is alive-but-blocked on a peer; ``waiting_since`` dates the block
        self.waiting = False
        self.waiting_since = now

    def beat(self) -> None:
        self.last = time.monotonic()

    def wait_begin(self) -> None:
        now = time.monotonic()
        self.last = now
        self.waiting_since = now
        self.waiting = True

    def wait_end(self) -> None:
        self.last = time.monotonic()
        self.waiting = False

    def age(self, now: Optional[float] = None) -> float:
        return max(0.0, (now if now is not None else time.monotonic()) - self.last)


class EventLog:
    """Bounded ring of structured events (health transitions, thread
    exceptions, WARNING+ log records), served at ``GET /admin/events``.
    Events are plain JSON-serializable dicts stamped with a wall-clock ``ts``
    and a monotonically increasing ``seq`` so a poller can detect loss."""

    def __init__(self, maxlen: int = 512) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, maxlen))
        self._total = 0

    def emit(self, event: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._total += 1
            stamped = {"seq": self._total, "ts": round(time.time(), 6)}
            stamped.update(event)
            self._ring.append(stamped)
            return stamped

    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            events = list(self._ring)
            total = self._total
        if limit is not None and limit >= 0:
            events = events[-limit:]
        return {"total": total, "events": events}


# ---------------------------------------------------------------------------
# checks — each evaluates to (status, detail) against a monotonic `now`
# ---------------------------------------------------------------------------
class ProcessWedgedCheck:
    """The engine loop stopped cycling. A loop blocked inside the output
    pump's flow-control wait is NOT wedged — the pump heartbeat accounts for
    it and ``output_saturated`` takes the blame instead."""

    name = "process_wedged"

    def __init__(self, hb_loop: Heartbeat, hb_output: Optional[Heartbeat],
                 active_fn: Optional[Callable[[], bool]],
                 stall_s: float, unhealthy_s: float) -> None:
        self._hb_loop = hb_loop
        self._hb_output = hb_output
        self._active_fn = active_fn
        self._stall_s = stall_s
        self._unhealthy_s = unhealthy_s

    def evaluate(self, now: float) -> Tuple[str, str]:
        if self._active_fn is not None and not self._active_fn():
            return PASS, "engine not running"
        out = self._hb_output
        if out is not None and out.waiting and out.age(now) <= self._stall_s:
            return PASS, ("loop blocked in output flow control "
                          "(see output_saturated)")
        age = self._hb_loop.age(now)
        if age >= self._unhealthy_s:
            return UNHEALTHY, f"engine loop last beat {age:.1f}s ago"
        if age >= self._stall_s:
            return DEGRADED, f"engine loop last beat {age:.1f}s ago"
        return PASS, f"loop beat {age:.2f}s ago"


class IngestStalledCheck:
    """No ingress frame for a while. Idle is healthy by default — only a
    stage configured to *expect* traffic (``watchdog_ingest_stall_seconds``)
    degrades on silence."""

    name = "ingest_stalled"

    def __init__(self, hb_ingest: Heartbeat,
                 active_fn: Optional[Callable[[], bool]],
                 stall_s: float) -> None:
        self._hb = hb_ingest
        self._active_fn = active_fn
        self._stall_s = stall_s

    def evaluate(self, now: float) -> Tuple[str, str]:
        if self._active_fn is not None and not self._active_fn():
            return PASS, "engine not running"
        age = self._hb.age(now)
        if self._stall_s > 0 and age >= self._stall_s:
            return DEGRADED, (f"no ingress frame for {age:.1f}s "
                              f"(stage expects traffic within {self._stall_s:.0f}s)")
        return PASS, f"last ingress frame {age:.1f}s ago"


class OutputSaturatedCheck:
    """The block-backpressure pump has been waiting on a full peer queue
    continuously — the downstream is not draining."""

    name = "output_saturated"

    def __init__(self, hb_output: Heartbeat,
                 active_fn: Optional[Callable[[], bool]],
                 stall_s: float, unhealthy_s: float) -> None:
        self._hb = hb_output
        self._active_fn = active_fn
        self._stall_s = stall_s
        self._unhealthy_s = unhealthy_s

    def evaluate(self, now: float) -> Tuple[str, str]:
        if self._active_fn is not None and not self._active_fn():
            return PASS, "engine not running"
        if not self._hb.waiting:
            return PASS, "outputs draining"
        waited = max(0.0, now - self._hb.waiting_since)
        if waited >= self._unhealthy_s:
            return UNHEALTHY, f"output send blocked {waited:.1f}s (peer queue full)"
        if waited >= self._stall_s:
            return DEGRADED, f"output send blocked {waited:.1f}s (peer queue full)"
        return PASS, f"output briefly backpressured ({waited:.2f}s)"


class InflightStuckCheck:
    """Work is pending but the drain/progress counter has not moved — a
    stuck device queue, a readback that never lands, a dead worker."""

    def __init__(self, name: str, pending_fn: Callable[[], int],
                 progress_fn: Callable[[], int],
                 stall_s: float, unhealthy_s: float) -> None:
        self.name = name
        self._pending_fn = pending_fn
        self._progress_fn = progress_fn
        self._stall_s = stall_s
        self._unhealthy_s = unhealthy_s
        self._last_progress: Optional[int] = None
        self._stuck_since: Optional[float] = None

    def evaluate(self, now: float) -> Tuple[str, str]:
        try:
            pending = int(self._pending_fn() or 0)
            progress = int(self._progress_fn() or 0)
        except Exception as exc:  # noqa: BLE001 — probes must not kill the watchdog
            return PASS, f"probe unavailable: {exc}"
        if pending <= 0:
            self._last_progress = progress
            self._stuck_since = None
            return PASS, "nothing in flight"
        if self._last_progress is None or progress != self._last_progress:
            self._last_progress = progress
            self._stuck_since = now
            return PASS, f"{pending} in flight, draining"
        # pending > 0, progress frozen. Re-arm the stuck clock if the idle
        # branch cleared it — otherwise a queue that wedges on the first
        # batch after an idle watchdog tick would never accumulate stuck
        # time and never be reported.
        if self._stuck_since is None:
            self._stuck_since = now
        stuck = now - self._stuck_since
        if stuck >= self._unhealthy_s:
            return UNHEALTHY, (f"{pending} in flight, no drain progress "
                               f"for {stuck:.1f}s")
        if stuck >= self._stall_s:
            return DEGRADED, (f"{pending} in flight, no drain progress "
                              f"for {stuck:.1f}s")
        return PASS, f"{pending} in flight, waiting {stuck:.2f}s"


class DegradationLadder:
    """The global overload state machine (dmshed): how much of the tenant
    population ingress admission keeps serving as backlog grows.

    Four states — ``normal`` → ``shed_best_effort`` → ``shed_burst`` →
    ``emergency`` — driven by the process's aggregate backlog (detector
    pending batches, router unacked window, durable-spool depth: whatever
    probe callables the service registers). Climbing is immediate and jumps
    straight to the highest threshold exceeded (an overloaded process must
    start shedding within one watchdog interval); descending takes
    ``recovery_intervals`` consecutive evaluations below the next state's
    threshold and moves ONE step at a time — the same asymmetric hysteresis
    the watchdog checks use, so a backlog oscillating around a threshold
    cannot strobe tiers on and off.

    Registered as a HealthMonitor check (rides the watchdog cadence); the
    engine's admission controller reads ``state_index`` per frame — a
    GIL-atomic int attribute, no lock on the hot path. Every transition
    emits a ``shed_ladder_transition`` structured event and updates the
    ``shed_ladder_state`` Enum."""

    name = "overload_ladder"

    STATES = ("normal", "shed_best_effort", "shed_burst", "emergency")
    # ladder state -> roll-up contribution: shedding best-effort traffic is
    # a degradation; emergency (guaranteed-only) means the process is
    # effectively down for most tenants
    _STATUS = (PASS, DEGRADED, DEGRADED, UNHEALTHY)

    def __init__(self, thresholds: Tuple[float, float, float],
                 labels: Dict[str, str],
                 recovery_intervals: int = 2,
                 events: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 ) -> None:
        t1, t2, t3 = thresholds
        if not (0 < t1 <= t2 <= t3):
            raise ValueError(
                f"ladder thresholds must satisfy 0 < t1 <= t2 <= t3, got "
                f"({t1}, {t2}, {t3})")
        self._thresholds = (float(t1), float(t2), float(t3))
        self._recovery_intervals = max(1, recovery_intervals)
        self._events = events
        self._backlog_fns: List[Callable[[], float]] = []
        self.state_index = 0   # read per frame by AdmissionController
        self._clean_streak = 0
        self._metric = m.SHED_LADDER_STATE().labels(**labels)
        self._metric.state(self.STATES[0])

    def add_backlog_source(self, fn: Callable[[], float]) -> None:
        """Register one backlog probe (messages/frames pending somewhere in
        the process); the ladder drives off the SUM of all sources."""
        self._backlog_fns.append(fn)

    def backlog(self) -> float:
        total = 0.0
        for fn in self._backlog_fns:
            try:
                total += float(fn() or 0)
            except Exception:  # noqa: BLE001 — probes must not kill the watchdog
                continue
        return total

    def _target_state(self, backlog: float) -> int:
        target = 0
        for index, threshold in enumerate(self._thresholds, start=1):
            if backlog >= threshold:
                target = index
        return target

    def evaluate(self, now: float) -> Tuple[str, str]:
        backlog = self.backlog()
        target = self._target_state(backlog)
        current = self.state_index
        if target > current:
            # climb fast: straight to the highest exceeded threshold
            self._transition(current, target, backlog)
            current = target
            self._clean_streak = 0
        elif target < current:
            # recover slow: one step down per recovery window
            self._clean_streak += 1
            if self._clean_streak >= self._recovery_intervals:
                self._transition(current, current - 1, backlog)
                current -= 1
                self._clean_streak = 0
        else:
            self._clean_streak = 0
        detail = (f"backlog {backlog:.0f} "
                  f"(thresholds {self._thresholds[0]:.0f}/"
                  f"{self._thresholds[1]:.0f}/{self._thresholds[2]:.0f})")
        return self._STATUS[current], f"{self.STATES[current]}: {detail}"

    def _transition(self, old: int, new: int, backlog: float) -> None:
        self.state_index = new
        self._metric.state(self.STATES[new])
        event = {
            "kind": "shed_ladder_transition",
            "check": self.name,
            "from": self.STATES[old],
            "to": self.STATES[new],
            "backlog": round(backlog, 1),
        }
        if self._events is not None:
            self._events(event)


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------
class HealthMonitor:
    """Owns the heartbeats, the derived checks, and the watchdog thread.

    One per :class:`~detectmateservice_tpu.core.Service`; the engine and the
    loaded component register their heartbeats/probes at construction.
    ``evaluate()`` is safe to call from any thread (the ``?deep=1`` admin
    endpoint and tests drive it directly) and is what the watchdog runs on
    its interval. Transitions apply asymmetric hysteresis: a check degrades
    on the FIRST failing evaluation (a stall must alert within one watchdog
    interval) but only recovers after ``recovery_intervals`` consecutive
    clean ones (no flapping)."""

    def __init__(
        self,
        labels: Dict[str, str],
        *,
        stage: Optional[str] = None,
        stall_seconds: float = 10.0,
        unhealthy_seconds: float = 30.0,
        interval_s: float = 2.0,
        recovery_intervals: int = 2,
        ingest_stall_seconds: float = 0.0,
        events: Optional[EventLog] = None,
        logger: Optional[logging.Logger] = None,
    ) -> None:
        self._labels = dict(labels)
        self._stage = stage or labels.get("component_type") or "core"
        self._stall_s = stall_seconds
        self._unhealthy_s = max(unhealthy_seconds, stall_seconds)
        self._interval_s = interval_s
        self._recovery_intervals = max(1, recovery_intervals)
        self._ingest_stall_s = ingest_stall_seconds
        self._events = events
        self._logger = logger
        self.trace_recorder = None  # FlightRecorder, attached by the Service
        # wall-clock start time, reported as ``started_unix``: a restart
        # signal for pollers (the replica router re-anchors its ack
        # watermark when this changes — cumulative counters reset with the
        # process, and monotonicity alone cannot catch a fast restart)
        self._started_unix = round(time.time(), 3)

        self._lock = threading.Lock()
        self._heartbeats: Dict[str, Heartbeat] = {}
        self._checks: List[Any] = []
        self._latched: Dict[str, str] = {}    # check -> failing status held
        self._streaks: Dict[str, int] = {}    # consecutive clean evals while latched
        self._effective: Dict[str, str] = {}  # check -> last reported status
        self._state = HEALTHY
        self._last_report: Optional[Dict[str, Any]] = None

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self._state_metric = m.ENGINE_HEALTH_STATE().labels(**self._labels)
        self._state_metric.state(HEALTHY)

    # -- registration ----------------------------------------------------
    def _export_heartbeat(self, hb: Heartbeat) -> None:
        """Export ``engine_heartbeat_age_seconds{loop=...}`` computed AT
        SCRAPE TIME (``set_function``), not copied on watchdog evaluations —
        so the gauge stays truthful, and ``EngineLoopStalled`` in
        ops/alerts.yml keeps firing, even when the watchdog thread itself is
        dead or wedged. A process too hung to serve the scrape at all is the
        alert layer's ``up == 0`` rule."""
        m.HEARTBEAT_AGE().labels(loop=hb.name, **self._labels).set_function(hb.age)

    def register_heartbeat(self, name: str) -> Heartbeat:
        """Create (or return) a named heartbeat exported as an
        ``engine_heartbeat_age_seconds{loop=name}`` gauge. No check is
        derived — use the ``register_*`` wiring helpers for that."""
        with self._lock:
            hb = self._heartbeats.get(name)
            if hb is None:
                hb = Heartbeat(name)
                self._heartbeats[name] = hb
                self._export_heartbeat(hb)
            return hb

    def register_engine(self, hb_loop: Heartbeat, hb_ingest: Heartbeat,
                        hb_output: Heartbeat,
                        active_fn: Optional[Callable[[], bool]] = None) -> None:
        """Wire the engine's three heartbeats into the standard loop checks
        (called by ``Engine.__init__`` when a monitor is provided)."""
        with self._lock:
            for hb in (hb_loop, hb_ingest, hb_output):
                self._heartbeats[hb.name] = hb
                self._export_heartbeat(hb)
            self._checks.append(ProcessWedgedCheck(
                hb_loop, hb_output, active_fn, self._stall_s, self._unhealthy_s))
            self._checks.append(IngestStalledCheck(
                hb_ingest, active_fn, self._ingest_stall_s))
            self._checks.append(OutputSaturatedCheck(
                hb_output, active_fn, self._stall_s, self._unhealthy_s))

    def register_progress(self, name: str, pending_fn: Callable[[], int],
                          progress_fn: Callable[[], int]) -> None:
        """Derive a stuck-queue check from a (pending, progress) probe pair:
        fails when pending > 0 and progress stops advancing."""
        with self._lock:
            self._checks.append(InflightStuckCheck(
                name, pending_fn, progress_fn, self._stall_s, self._unhealthy_s))

    def add_check(self, check: Any) -> None:
        """Register a custom check object (``.name`` + ``.evaluate(now) ->
        (status, detail)``) — also the failure-injection seam for tests."""
        with self._lock:
            self._checks.append(check)

    def remove_check(self, name: str) -> None:
        with self._lock:
            self._checks = [c for c in self._checks if c.name != name]
            self._latched.pop(name, None)
            self._streaks.pop(name, None)
            self._effective.pop(name, None)

    # -- evaluation ------------------------------------------------------
    @property
    def state(self) -> str:
        # the lock (not a bare read) so a concurrent evaluate()'s roll-up
        # transition is never observed half-applied; uncontended acquire is
        # ~100 ns and this is the cheap-liveness path, not the hot loop
        with self._lock:
            return self._state

    def report(self) -> Dict[str, Any]:
        """The most recent evaluation (evaluating now if none ran yet)."""
        with self._lock:
            report = self._last_report
        return report or self.evaluate()

    # safe from any thread (admin ?deep=1, watchdog, tests): every
    # dmlint: thread(any) — mutation below runs under self._lock
    def evaluate(self) -> Dict[str, Any]:
        """Run every check once, apply hysteresis, update the metrics, emit
        transition events, and return the full report."""
        now = time.monotonic()
        with self._lock:
            results: List[Dict[str, str]] = []
            worst = PASS
            for check in list(self._checks):
                try:
                    status, detail = check.evaluate(now)
                except Exception as exc:  # noqa: BLE001 — a crashing check is itself a failure
                    status, detail = DEGRADED, f"check crashed: {exc!r}"
                status, detail = self._apply_hysteresis(check.name, status, detail)
                results.append({"name": check.name, "status": status,
                                "detail": detail})
                if _SEVERITY[status] > _SEVERITY[worst]:
                    worst = status
            state = {PASS: HEALTHY, DEGRADED: DEGRADED,
                     UNHEALTHY: UNHEALTHY}[worst]
            if state != self._state:
                self._emit_transition("state", self._state, state,
                                      "roll-up of "
                                      + (", ".join(r["name"] for r in results
                                                   if r["status"] != PASS)
                                         or "all checks passing"))
                self._state = state
            self._state_metric.state(state)
            # ages here are for the report only — the exported gauge is
            # bound to hb.age via set_function and refreshes at scrape time
            ages = {name: round(hb.age(now), 3)
                    for name, hb in self._heartbeats.items()}
            report = {
                "state": state,
                "stage": self._stage,
                "component_type": self._labels.get("component_type"),
                "component_id": self._labels.get("component_id"),
                "started_unix": self._started_unix,
                "checks": results,
                "heartbeat_age_seconds": ages,
            }
            self._last_report = report
            return report

    def _apply_hysteresis(self, name: str, status: str,
                          detail: str) -> Tuple[str, str]:
        if status == PASS:
            latched = self._latched.get(name)
            if latched is not None:
                streak = self._streaks.get(name, 0) + 1
                if streak >= self._recovery_intervals:
                    del self._latched[name]
                    self._streaks.pop(name, None)
                else:
                    self._streaks[name] = streak
                    status = latched
                    detail = (f"recovering ({streak}/{self._recovery_intervals}"
                              f" clean intervals): {detail}")
        else:
            self._latched[name] = status
            self._streaks[name] = 0
        prev = self._effective.get(name, PASS)
        if status != prev:
            self._emit_transition(name, prev, status, detail)
        self._effective[name] = status
        return status, detail

    # dmlint: thread(any) — takes no monitor lock (see docstring)
    def emit_event(self, event: Dict[str, Any],
                   level: int = logging.WARNING) -> Dict[str, Any]:
        """Public seam for subsystems (e.g. the device-observability compile
        ledger) to emit a structured event with this service's identity and
        the flight recorder's last trace id attached — ring + logger, the
        same fan-out health transitions get. Takes no monitor lock, so it is
        safe to call from any thread, including under other locks."""
        doc: Dict[str, Any] = {
            "component_type": self._labels.get("component_type"),
            "component_id": self._labels.get("component_id"),
            "stage": self._stage,
        }
        doc.update(event)
        recorder = self.trace_recorder
        if recorder is not None and "trace_id" not in doc:
            doc["trace_id"] = getattr(recorder, "last_trace_id", None)
        if self._events is not None:
            self._events.emit(doc)
        if self._logger is not None:
            self._logger.log(level, "event %s: %s",
                             doc.get("kind", "unknown"), doc,
                             extra={"dm_event": doc})
        return doc

    def _emit_transition(self, check: str, old: str, new: str,
                         detail: str) -> None:
        trace_id = None
        recorder = self.trace_recorder
        if recorder is not None:
            trace_id = getattr(recorder, "last_trace_id", None)
        event = {
            "kind": "health_transition",
            "component_type": self._labels.get("component_type"),
            "component_id": self._labels.get("component_id"),
            "stage": self._stage,
            "check": check,
            "from": old,
            "to": new,
            "detail": detail,
            "trace_id": trace_id,
        }
        if self._events is not None:
            self._events.emit(event)
        if self._logger is not None:
            level = logging.INFO if new in (PASS, HEALTHY) else logging.WARNING
            self._logger.log(level, "health %s: %s -> %s (%s)",
                             check, old, new, detail,
                             extra={"dm_event": event})

    # -- watchdog thread -------------------------------------------------
    # dmlint: thread(any)
    def start(self, interval_s: Optional[float] = None) -> None:
        if interval_s is not None:
            self._interval_s = interval_s
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="HealthWatchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)
        self._thread = None

    # dmlint: thread(watchdog)
    def _run(self) -> None:
        # dmlint: hot-loop
        while not self._stop.wait(self._interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 — the watchdog must outlive its checks
                if self._logger is not None:
                    self._logger.exception("health watchdog evaluation failed")


# ---------------------------------------------------------------------------
# structured (JSON) logging
# ---------------------------------------------------------------------------
class JsonLogFormatter(logging.Formatter):
    """``log_format: json`` — every log record becomes one JSON object per
    line, carrying the component identity so a fleet's stdout streams can be
    aggregated without regex parsing. Health transitions attach their full
    event under ``event`` (the ``dm_event`` record extra).

    Log↔trace correlation (dmtel): records emitted on a thread with an
    active frame context (tracing.FRAME_CONTEXT — the engine loop while a
    frame is in flight) carry ``trace_id`` and ``tenant_bucket``, so
    ``grep trace_id`` joins a stage's logs with the spans the telemetry
    collector assembled and the DLQ entry the same frame may have left."""

    def __init__(self, static: Optional[Dict[str, str]] = None,
                 tenant_buckets: int = 16) -> None:
        super().__init__()
        self._static = dict(static or {})
        self._tenant_buckets = max(1, tenant_buckets)
        # runtime import: shed → engine.metrics → this module would cycle
        # at package-import time, but formatters are built long after
        from ..shed.quota import tenant_bucket
        self._bucket_fn = tenant_bucket

    def format(self, record: logging.LogRecord) -> str:
        doc: Dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        doc.update(self._static)
        trace_id = tracing.current_trace_id()
        if trace_id is not None:
            doc["trace_id"] = f"{trace_id:016x}"
        tenant = tracing.current_tenant()
        if tenant is not None:
            # the bounded bucket, never the raw tenant id — logs feed the
            # same aggregation pipelines as metrics (shed/quota.py rationale)
            doc["tenant_bucket"] = self._bucket_fn(tenant,
                                                   self._tenant_buckets)
        event = getattr(record, "dm_event", None)
        if event is not None:
            doc["event"] = event
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, default=str)


class EventLogHandler(logging.Handler):
    """Mirrors WARNING+ records into the event ring so ``GET /admin/events``
    shows operational noise alongside health transitions (which emit their
    own richer events and are skipped here to avoid duplicates)."""

    def __init__(self, events: EventLog) -> None:
        super().__init__(level=logging.WARNING)
        self._events = events

    def emit(self, record: logging.LogRecord) -> None:
        try:
            if getattr(record, "dm_event", None) is not None:
                return
            event: Dict[str, Any] = {
                "kind": "log",
                "level": record.levelname,
                "logger": record.name,
                "message": record.getMessage(),
            }
            if record.exc_info and record.exc_info[1] is not None:
                event["error"] = repr(record.exc_info[1])
            self._events.emit(event)
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


# ---------------------------------------------------------------------------
# threading.excepthook: no daemon worker dies silently to stderr
# ---------------------------------------------------------------------------
_HOOK_LOCK = threading.Lock()
_HOOK_SINKS: List[Tuple[logging.Logger, Optional[EventLog]]] = []
_PREV_HOOK: Optional[Callable] = None


def install_thread_excepthook(logger: logging.Logger,
                              events: Optional[EventLog] = None):
    """Route uncaught exceptions in ANY thread through ``logger`` (and the
    event ring) as a structured event. Installed once per process during
    core setup; each Service registers a sink and removes it at teardown
    (``remove_excepthook_sink``). Returns the sink handle."""
    global _PREV_HOOK
    sink = (logger, events)
    with _HOOK_LOCK:
        _HOOK_SINKS.append(sink)
        if _PREV_HOOK is None:
            _PREV_HOOK = threading.excepthook
            threading.excepthook = _thread_excepthook
    return sink


def remove_excepthook_sink(sink) -> None:
    with _HOOK_LOCK:
        try:
            _HOOK_SINKS.remove(sink)
        except ValueError:
            pass


def _thread_excepthook(args) -> None:
    if args.exc_type is SystemExit:
        return
    thread_name = args.thread.name if args.thread is not None else "<unknown>"
    event = {
        "kind": "thread_exception",
        "thread": thread_name,
        "error": repr(args.exc_value),
        "traceback": "".join(traceback.format_exception(
            args.exc_type, args.exc_value, args.exc_traceback)),
    }
    with _HOOK_LOCK:
        sinks = list(_HOOK_SINKS)
        prev_hook = _PREV_HOOK
    delivered = False
    for logger, events in sinks:
        try:
            if events is not None:
                events.emit(dict(event))
            logger.error("uncaught exception in thread %s: %s",
                         thread_name, args.exc_value,
                         exc_info=(args.exc_type, args.exc_value,
                                   args.exc_traceback),
                         extra={"dm_event": event})
            delivered = True
        except Exception:  # noqa: BLE001 — the hook of last resort cannot raise
            pass
    if not delivered and prev_hook is not None:
        prev_hook(args)


# ---------------------------------------------------------------------------
# build info
# ---------------------------------------------------------------------------
_BUILD_INFO_LOCK = threading.Lock()
_BUILD_INFO_SET = False


def set_build_info() -> None:
    """Export the ``dm_build_info`` gauge (value 1; the labels ARE the data):
    package version plus the native kernels' feature versions, so dashboards
    and alerts can correlate a behavior change with the deployed build. Once
    per process; a missing/stale native library reports ``unavailable``
    rather than failing core setup."""
    global _BUILD_INFO_SET
    with _BUILD_INFO_LOCK:
        if _BUILD_INFO_SET:
            return
        from ..metadata import VERSION

        try:
            from ..utils.matchkern import DM_FEATURE_VERSION
            dm = str(DM_FEATURE_VERSION)
        except Exception:  # noqa: BLE001 — kernel not built / stale .so
            dm = "unavailable"
        try:
            from .native_transport import DMT_FEATURE_VERSION
            dmt = str(DMT_FEATURE_VERSION)
        except Exception:  # noqa: BLE001
            dmt = "unavailable"
        m.BUILD_INFO().labels(version=VERSION, dm_feature_version=dm,
                              dmt_feature_version=dmt).set(1)
        _BUILD_INFO_SET = True
