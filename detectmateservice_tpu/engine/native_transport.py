"""ctypes bindings for the native C++ transport (native/transport/dmtransport.cpp).

Role of the reference's pynng-over-libnng data plane (reference:
src/service/features/engine_socket.py:35-78; SURVEY.md §2.8): the wire is
owned by native code. Frames ride libzmq DEALER sockets, so native sockets
interoperate with the Python zmq backend (socket.py) frame-for-frame — a
pipeline can mix both.

What the native layer adds: ``recv_many`` drains a whole micro-batch in ONE
call (one GIL crossing per batch instead of per message — SURVEY.md §7 hard
part #3). The engine's batch loop uses it when the input socket provides it.

Thread-safety contract (matches the engine's usage): ``recv``/``recv_many``
are called only from the engine loop thread; ``close`` only after that thread
has been joined (engine.py stop()). The C layer serializes calls with a
mutex, but close must not race an in-flight blocking recv.
"""
from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path
from typing import List, Optional

from .socket import (
    EngineSocket,
    TransportAgain,
    TransportClosed,
    TransportError,
    TransportTimeout,
)

_PKG_DIR = Path(__file__).resolve().parent.parent
_LIB_PATH = _PKG_DIR / "_native" / "libdmtransport.so"
_SRC_PATH = _PKG_DIR.parent / "native" / "transport" / "dmtransport.cpp"

# keep in sync with dmtransport.cpp
_OK, _ETIMEOUT, _EAGAIN, _ECLOSED, _EERR, _ETOOBIG = 0, -1, -2, -3, -4, -5

# Feature version this binding expects the library to report
# (dmt_feature_version; stamped by native/build.sh, defaulted in the .cpp).
# A mismatch raises ImportError so "auto" backend selection falls back to
# the Python transport LOUDLY instead of serving an older wire surface.
# Bump in lockstep with the default in native/transport/dmtransport.cpp.
DMT_FEATURE_VERSION = 3

_INITIAL_BUF = 16 * 1024 * 1024  # starting recv buffer; grows on demand —
                                 # oversized frames are stashed native-side
                                 # (dmt_pending_size) and retried, never lost


def _stale() -> bool:
    if not _LIB_PATH.exists():
        return True
    return (_SRC_PATH.exists()
            and _SRC_PATH.stat().st_mtime > _LIB_PATH.stat().st_mtime)


def _rebuild() -> None:
    """Compile to a temp file and atomically replace (same discipline as
    utils/matchkern.py), linking against the soname directly — this image
    ships libzmq.so.5 but no dev symlink or header."""
    import os
    import tempfile

    _LIB_PATH.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(_LIB_PATH.parent))
    os.close(fd)
    try:
        subprocess.run(
            ["c++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
             str(_SRC_PATH), "-l:libzmq.so.5", "-lpthread"],
            check=True, capture_output=True, timeout=120,
        )
        os.chmod(tmp, 0o755)
        os.replace(tmp, str(_LIB_PATH))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _lib_feature_version(lib: ctypes.CDLL) -> int:
    """Version the loaded library reports; 0 for pre-versioning builds."""
    try:
        fn = lib.dmt_feature_version
    except AttributeError:
        return 0
    fn.restype = ctypes.c_int
    return int(fn())


def _load() -> ctypes.CDLL:
    if _stale():
        if not _SRC_PATH.exists() and not _LIB_PATH.exists():
            raise ImportError(f"native transport source not found at {_SRC_PATH}")
        if _SRC_PATH.exists():
            try:
                _rebuild()
            except (subprocess.SubprocessError, OSError) as exc:
                if not _LIB_PATH.exists():
                    raise ImportError(f"cannot build native transport: {exc}")
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as exc:
        # e.g. no libzmq.so.5 on this host, or a wrong-arch committed .so —
        # surface as ImportError so "auto" backend selection falls back to
        # the pure-Python transport
        raise ImportError(f"cannot load native transport: {exc}")
    if _lib_feature_version(lib) != DMT_FEATURE_VERSION:
        # stale binary: rebuild when the source is present (os.replace swaps
        # the inode, so re-dlopen maps the new object), else fail loudly
        if _SRC_PATH.exists():
            try:
                _rebuild()
                lib = ctypes.CDLL(str(_LIB_PATH))
            except (subprocess.SubprocessError, OSError):
                pass
        got = _lib_feature_version(lib)
        if got != DMT_FEATURE_VERSION:
            raise ImportError(
                f"stale native transport library {_LIB_PATH}: reports "
                f"feature version {got}, bindings expect "
                f"{DMT_FEATURE_VERSION} — rebuild with native/build.sh")
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.dmt_listen.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.dmt_listen.restype = ctypes.c_void_p
    lib.dmt_dial.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                             ctypes.c_int]
    lib.dmt_dial.restype = ctypes.c_void_p
    lib.dmt_set_recv_timeout.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dmt_recv.argtypes = [ctypes.c_void_p, u8p, ctypes.c_longlong]
    lib.dmt_recv.restype = ctypes.c_longlong
    lib.dmt_recv_many.argtypes = [ctypes.c_void_p, u8p, ctypes.c_longlong,
                                  ctypes.c_int, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_longlong)]
    lib.dmt_recv_many.restype = ctypes.c_int
    lib.dmt_pending_size.argtypes = [ctypes.c_void_p]
    lib.dmt_pending_size.restype = ctypes.c_longlong
    lib.dmt_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
                             ctypes.c_int]
    lib.dmt_send.restype = ctypes.c_int
    lib.dmt_send_many.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_longlong, ctypes.c_int,
                                  ctypes.c_int]
    lib.dmt_send_many.restype = ctypes.c_int
    lib.dmt_close.argtypes = [ctypes.c_void_p]
    return lib


_lib = _load()
_U8P = ctypes.POINTER(ctypes.c_uint8)


def _raise(code: int, what: str) -> None:
    if code == _ETIMEOUT:
        raise TransportTimeout(f"{what} timeout")
    if code == _EAGAIN:
        raise TransportAgain(f"{what} would block")
    if code == _ECLOSED:
        raise TransportClosed(f"{what} on closed socket")
    raise TransportError(f"{what} failed (code {code})")


class NativePairSocket:
    """EngineSocket over the C++ transport (surface of socket.ZmqPairSocket)."""

    def __init__(self, handle: int, addr: str):
        self._handle = handle
        self._addr = addr
        self._closed = False
        self._recv_timeout: Optional[int] = None
        self._buf = None  # allocated on first recv; output sockets never pay
        self._close_lock = threading.Lock()

    def _ensure_buf(self, cap: int):
        if self._buf is None or len(self._buf) < cap:
            self._buf = (ctypes.c_uint8 * cap)()
        return self._buf

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms
        if not self._closed:
            _lib.dmt_set_recv_timeout(self._handle, -1 if ms is None else int(ms))

    def recv(self) -> bytes:
        if self._closed:
            raise TransportClosed(f"recv on closed socket {self._addr}")
        buf = self._ensure_buf(_INITIAL_BUF)
        while True:
            n = _lib.dmt_recv(self._handle, buf, len(buf))
            if n == _ETOOBIG:
                # frame is stashed native-side; grow and retry — no data loss
                need = int(_lib.dmt_pending_size(self._handle))
                buf = self._ensure_buf(max(need, len(buf) * 2))
                continue
            if n < 0:
                _raise(int(n), "recv")
            return bytes(memoryview(buf)[: int(n)])

    def recv_many(self, max_n: int, first_timeout_ms: int) -> List[bytes]:
        """Drain up to ``max_n`` queued frames in one native call. Blocks up
        to ``first_timeout_ms`` for the first frame only; raises
        TransportTimeout when nothing arrived."""
        if self._closed:
            raise TransportClosed(f"recv on closed socket {self._addr}")
        buf = self._ensure_buf(max(_INITIAL_BUF, max_n * 4096))
        used = ctypes.c_longlong(0)
        while True:
            count = _lib.dmt_recv_many(self._handle, buf, len(buf), max_n,
                                       int(first_timeout_ms), ctypes.byref(used))
            if count == _ETOOBIG:
                # first frame alone exceeds the buffer: it is stashed
                # native-side; grow and retry — no data loss
                need = int(_lib.dmt_pending_size(self._handle))
                buf = self._ensure_buf(max(need + 4, len(buf) * 2))
                continue
            break
        if count < 0:
            _raise(int(count), "recv_many")
        frames: List[bytes] = []
        view = memoryview(buf)
        off = 0
        for _ in range(count):
            ln = int.from_bytes(view[off:off + 4], "little")
            frames.append(bytes(view[off + 4:off + 4 + ln]))
            off += 4 + ln
        return frames

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed:
            raise TransportClosed(f"send on closed socket {self._addr}")
        rc = _lib.dmt_send(self._handle, data, len(data), 1 if block else 0)
        if rc != _OK:
            _raise(int(rc), "send")

    def send_many(self, frames: List[bytes], block: bool = False) -> int:
        """Send a whole output micro-batch in ONE native call (the send-side
        twin of ``recv_many``: one GIL crossing per batch, not per frame).
        Returns how many leading frames were handed to the transport — the
        caller retries the remainder (per-frame retry/drop accounting stays
        exact). Raises the usual taxonomy only when not even the first frame
        went out."""
        if self._closed:
            raise TransportClosed(f"send on closed socket {self._addr}")
        if not frames:
            return 0
        buf = bytearray()
        for frame in frames:
            buf += len(frame).to_bytes(4, "little")
            buf += frame
        rc = _lib.dmt_send_many(self._handle, bytes(buf), len(buf),
                                len(frames), 1 if block else 0)
        if rc < 0:
            _raise(int(rc), "send_many")
        return int(rc)

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        _lib.dmt_close(self._handle)
        self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativePairSocketFactory:
    """EngineSocketFactory over the C++ transport. tls+tcp stays on the
    Python ssl transport, and ws AND inproc on the Python zmq backend — the
    factory delegates those schemes, so every address the zmq factory accepts
    works here too. inproc in particular MUST delegate: the native layer's
    private zmq context can never rendezvous with pyzmq's process-wide
    ``Context.instance()``, so a native-side inproc endpoint would silently
    never connect to a zmq-side (or auto-fallback) peer in the same process."""

    SCHEMES = ("ipc", "tcp")

    def _delegate(self, scheme: str):
        if scheme == "tls+tcp":
            from .socket import TlsTcpSocketFactory

            return TlsTcpSocketFactory()
        if scheme == "nng+tcp":
            from .socket import NngTcpSocketFactory

            return NngTcpSocketFactory()
        if scheme == "nng+tls+tcp":
            from .socket import NngTlsTcpSocketFactory

            return NngTlsTcpSocketFactory()
        if scheme in ("ws", "inproc"):
            from .socket import ZmqPairSocketFactory

            return ZmqPairSocketFactory()
        return None

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme = addr.split("://", 1)[0] if "://" in addr else ""
        delegate = self._delegate(scheme)
        if delegate is not None:
            return delegate.create(addr, logger, tls_config)
        if scheme not in self.SCHEMES:
            raise TransportError(f"unsupported scheme {scheme!r} in {addr!r}")
        if scheme == "tcp":
            host_port = addr.split("://", 1)[1].split("/", 1)[0]
            if ":" not in host_port:
                raise TransportError(f"tcp address {addr!r} requires an explicit port")
        err = ctypes.create_string_buffer(256)
        handle = _lib.dmt_listen(addr.encode(), err, len(err))
        if not handle:
            raise TransportError(
                f"cannot listen on {addr}: {err.value.decode(errors='replace')}")
        logger.debug("native transport listening on %s", addr)
        return NativePairSocket(handle, addr)

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme = addr.split("://", 1)[0] if "://" in addr else ""
        delegate = self._delegate(scheme)
        if delegate is not None:
            return delegate.create_output(
                addr, logger, tls_config, dial_timeout, buffer_size)
        if scheme not in self.SCHEMES:
            raise TransportError(f"unsupported scheme {scheme!r} in {addr!r}")
        err = ctypes.create_string_buffer(256)
        handle = _lib.dmt_dial(addr.encode(), max(1, buffer_size), err, len(err))
        if not handle:
            raise TransportError(
                f"cannot dial {addr}: {err.value.decode(errors='replace')}")
        logger.debug("native transport dialing %s (background connect)", addr)
        return NativePairSocket(handle, addr)
