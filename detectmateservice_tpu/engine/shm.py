"""Zero-copy shared-memory / inproc framing between colocated stages.

The per-hop socket cost of a colocated link (parser and detector in one pod)
is dominated by payload copies: the sender's zmq enqueue copy, the kernel
round-trip, and the receiver's bytes materialization — three-plus copies of
every frame that never leaves the host. This module moves the payload into a
shared-memory segment owned by the sending engine and puts a ~40-byte
reference frame (framing.MAGIC_SHM) on the wire instead:

* **shm mode** (ipc peers): the sender memcpys the payload into a refcounted
  segment slot once; the receiver slices it back out once. Two copies total,
  constant-size wire frames, and the socket's high-water mark stops scaling
  with payload size.
* **inproc mode** (same-process peers): the slot stores the payload *object*
  — the receiver gets the very same bytes object back. Zero copies.

Reclamation is refcounted through the C11-atomic slot protocol in
native/matchkern/dmkern.c (``dm_shm_acquire`` / ``publish`` / ``release``):
a published slot's state counts outstanding readers; the release that
reaches zero frees the slot for reuse, and a per-publish generation counter
makes stale references detectable instead of dangerous. Python never touches
the header region with plain writes — cross-process ordering (and TSan
coverage) both demand the C entry points.

Failure containment: everything degrades to copy mode, never to blocking or
loss. No free slot (a slow or dead receiver still holds them all), an
oversized payload, or a remote peer each make the sender put the plain bytes
on the wire; a receiver that cannot resolve a reference (unknown segment,
stale generation) counts a framing error and drops that frame exactly like a
corrupt batch frame. Payloads are byte-identical in either mode — pinned by
tests/test_shm.py.
"""
from __future__ import annotations

import logging
import mmap
import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

from .framing import FramingError, ShmRef, pack_shm_ref, unpack_shm_ref

try:
    from ..utils import matchkern as _mk
    _HAVE_KERNEL = _mk.has_shm_kernel()
except ImportError:  # no compiler / stale .so: zero-copy framing disabled
    _mk = None
    _HAVE_KERNEL = False


def shm_available() -> bool:
    """True when the native slot-protocol kernel is loaded (zero_copy_framing
    silently degrades to plain copy mode without it)."""
    return _HAVE_KERNEL


def _segment_dir() -> str:
    # /dev/shm keeps the segment memory-backed; any tmpdir still works (the
    # mmap is shared either way, the fallback just may touch disk)
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


# in-process writer registry for inproc (same-process) links: the reference
# names the writer, the slot stores the payload OBJECT — the reader hands the
# identical bytes object to the engine, zero copies. Guarded by a lock only
# for registry mutation; slot state itself rides the C atomics.
_INPROC_REGISTRY: Dict[str, "ShmWriter"] = {}
_INPROC_LOCK = threading.Lock()
_INPROC_SEQ = 0


class ShmWriter:
    """Sender side: a pool of refcounted payload slots in one shm segment
    (or, for ``inproc=True``, an object-slab twin that skips the copy in).

    ``publish`` is the only hot-path call: acquire a free slot, place the
    payload, publish with the reader refcount, return the wire reference —
    or None, which tells the engine to copy-downgrade this frame."""

    def __init__(self, slots: int = 32, slot_bytes: int = 262144,
                 inproc: bool = False,
                 logger: Optional[logging.Logger] = None):
        if not _HAVE_KERNEL:
            raise RuntimeError("native shm kernel not available")
        import numpy as np

        self._slots = int(slots)
        self._slot_bytes = int(slot_bytes)
        self._inproc = bool(inproc)
        self._logger = logger or logging.getLogger(__name__)
        self._closed = False
        header = _mk.shm_header_bytes(self._slots)
        self._header_bytes = header
        if inproc:
            # header atomics on process-local memory; payload objects in a
            # plain slot list (the C protocol still arbitrates ownership)
            global _INPROC_SEQ
            with _INPROC_LOCK:
                _INPROC_SEQ += 1
                self.name = f"@inproc:{os.getpid()}:{_INPROC_SEQ}"
                _INPROC_REGISTRY[self.name] = self
            self._hdr_arr = np.zeros(header, dtype=np.uint8)
            self._addr = int(self._hdr_arr.ctypes.data)
            self._mm = None
            self._path = None
            self._objs: List[Optional[bytes]] = [None] * self._slots
        else:
            size = header + self._slots * self._slot_bytes
            fd, path = tempfile.mkstemp(prefix="dmshm-", suffix=".seg",
                                        dir=_segment_dir())
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            self._path = path
            self.name = path
            self._hdr_arr = np.frombuffer(self._mm, dtype=np.uint8,
                                          count=header)
            self._addr = int(self._hdr_arr.ctypes.data)
            self._objs = []
        _mk.shm_init(self._addr, self._slots)

    def publish(self, payload: bytes, refs: int) -> Optional[bytes]:
        """Place ``payload`` into a free slot published for ``refs`` readers;
        returns the wire reference frame, or None to copy-downgrade (no free
        slot / oversized / closed). Never blocks."""
        n = len(payload)
        if (self._closed or refs <= 0
                or (not self._inproc and n > self._slot_bytes)):
            return None
        slot = _mk.shm_acquire(self._addr, self._slots)
        if slot < 0:
            return None
        if self._inproc:
            offset = 0
            self._objs[slot] = payload
        else:
            offset = self._header_bytes + slot * self._slot_bytes
            self._mm[offset:offset + n] = payload
        gen = _mk.shm_publish(self._addr, slot, refs)
        return pack_shm_ref(ShmRef(self.name, slot, gen, offset, n))

    def release_ref(self, ref_frame: bytes) -> None:
        """Sender-side release of one reference it minted but could not
        deliver (dropped/hard-failed send): the reader that will never come
        must not leak the slot."""
        try:
            ref = unpack_shm_ref(ref_frame)
        except FramingError:
            return
        self._release_slot(ref.slot, ref.gen)

    def _release_slot(self, slot: int, gen: int) -> int:
        if self._closed or not 0 <= slot < self._slots:
            return -1
        remaining = _mk.shm_release(self._addr, slot, gen)
        if remaining == 0 and self._inproc:
            self._objs[slot] = None          # let the payload object go
        return remaining

    def in_use(self) -> int:
        """Slots currently not FREE (diagnostics/tests); 0 after close —
        the header mapping is gone then, so there is nothing to read."""
        if self._closed:
            return 0
        return sum(1 for i in range(self._slots)
                   if _mk.shm_state(self._addr, i) != 0)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._inproc:
            with _INPROC_LOCK:
                _INPROC_REGISTRY.pop(self.name, None)
            self._objs = [None] * self._slots
            return
        # drop the buffer export before closing the map; readers that
        # already attached keep their own mapping (the inode lives until
        # the last map goes), new attaches fail cleanly after the unlink
        self._hdr_arr = None
        try:
            self._mm.close()
        except BufferError:  # a live export (shouldn't happen post-close)
            pass
        if self._path is not None:
            try:
                os.unlink(self._path)
            except OSError:
                pass


class _Attached:
    """One receiver-side segment attachment (mmap + header address)."""

    __slots__ = ("mm", "addr", "size", "header_bytes", "_arr")

    def __init__(self, path: str):
        import numpy as np

        with open(path, "rb+") as fh:
            self.mm = mmap.mmap(fh.fileno(), 0)
        self.size = len(self.mm)
        # the header size is implied by the writer's slot count; slots are
        # validated by range-checking offsets instead of trusting a count
        self._arr = np.frombuffer(self.mm, dtype=np.uint8)
        self.addr = int(self._arr.ctypes.data)

    def close(self) -> None:
        self._arr = None
        try:
            self.mm.close()
        except BufferError:
            pass


class ShmReader:
    """Receiver side: resolve reference frames back to payload bytes and
    release the slot. Attachments are cached per segment path; inproc
    references resolve through the process-local writer registry (returning
    the identical payload object — zero copies)."""

    def __init__(self, logger: Optional[logging.Logger] = None):
        self._logger = logger or logging.getLogger(__name__)
        self._segments: Dict[str, _Attached] = {}

    def resolve_release(self, data: bytes) -> Optional[bytes]:
        """Reference frame → payload bytes (None = unresolvable, count a
        framing error). The payload is consumed and the slot reference
        released before returning — the returned bytes are safe to hold
        indefinitely."""
        try:
            ref = unpack_shm_ref(data)
        except FramingError as exc:
            self._logger.error("garbled shm reference dropped: %s", exc)
            return None
        if ref.name.startswith("@inproc:"):
            return self._resolve_inproc(ref)
        return self._resolve_segment(ref)

    def _resolve_inproc(self, ref: ShmRef) -> Optional[bytes]:
        with _INPROC_LOCK:
            writer = _INPROC_REGISTRY.get(ref.name)
        if writer is None or not 0 <= ref.slot < writer._slots:
            self._logger.error("shm reference to unknown inproc slab %s",
                               ref.name)
            return None
        payload = writer._objs[ref.slot]
        # read the object BEFORE releasing: our outstanding ref pins the
        # slot, so the writer cannot recycle it under us
        if writer._release_slot(ref.slot, ref.gen) < 0 or payload is None:
            self._logger.error("stale inproc shm reference (slot %d gen %d)",
                               ref.slot, ref.gen)
            return None
        if len(payload) != ref.length:
            return None
        return payload

    def _resolve_segment(self, ref: ShmRef) -> Optional[bytes]:
        seg = self._segments.get(ref.name)
        if seg is None:
            try:
                seg = _Attached(ref.name)
            except (OSError, ValueError) as exc:
                self._logger.error("cannot attach shm segment %s: %s",
                                   ref.name, exc)
                return None
            self._segments[ref.name] = seg
        if not (0 <= ref.offset and ref.offset + ref.length <= seg.size
                and ref.slot >= 0
                and (ref.slot + 1) * _mk.shm_header_bytes(1) <= ref.offset):
            self._logger.error("out-of-range shm reference dropped")
            return None
        # copy out while our ref pins the slot, then release
        payload = bytes(self._segments[ref.name].mm[
            ref.offset:ref.offset + ref.length])
        if _mk.shm_release(seg.addr, ref.slot, ref.gen) < 0:
            self._logger.error("stale shm reference (slot %d gen %d)",
                               ref.slot, ref.gen)
            return None
        return payload

    def close(self) -> None:
        for seg in self._segments.values():
            seg.close()
        self._segments.clear()
