"""Prometheus metric registry helpers.

The reference keeps one module-global registry and needs idempotent metric
creation because tests build several services per process (reference:
src/service/core.py:45-52 scans ``REGISTRY._collector_to_names``). We keep a
private name → collector map instead: every series this package emits is
declared below via ``_series`` and created exactly once through
``get_or_create``, whose cache — not private prometheus_client registry
state — is the authority for "already exists".

``REGISTERED_SERIES`` maps every declared exposition name to its metric
class; tests/test_observability.py derives the dashboard-sync known-series
set from it, so a new series here is automatically held to dashboard
coverage.

Metric names and label sets are the reference's observable contract
(reference: src/service/core.py:24-61, src/service/features/engine.py:14-54,
docs/prometheus.md:29-47) and must not change.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Sequence, Type

from prometheus_client import Counter, Enum, Gauge, Histogram

_LOCK = threading.Lock()
_CACHE: Dict[str, object] = {}


def get_or_create(
    metric_cls: Type,
    name: str,
    documentation: str,
    labelnames: Sequence[str] = (),
    **kwargs,
):
    """Return the process-wide collector for ``name``, creating it once.

    All of this package's metric creation funnels through here under one
    lock, so our ``_CACHE`` is the single source of truth — a duplicate
    ``ValueError`` from prometheus_client would mean some *other* code
    registered the name first, which is a real conflict to surface, not one
    to paper over by scanning the registry's private state."""
    with _LOCK:
        found = _CACHE.get(name)
        if found is not None:
            return found
        metric = metric_cls(name, documentation, labelnames=labelnames, **kwargs)
        _CACHE[name] = metric
        return metric


# -- reference metric contract (labels: component_type, component_id) -------
LABELS = ("component_type", "component_id")

# every exposition name this package can emit → metric class; the declared
# lambda registry tests iterate (see module docstring)
REGISTERED_SERIES: Dict[str, Type] = {}


def _series(metric_cls: Type, name: str, documentation: str,
            labelnames: Sequence[str] = LABELS, **kwargs) -> Callable:
    REGISTERED_SERIES[name] = metric_cls
    return lambda: get_or_create(metric_cls, name, documentation,
                                 labelnames, **kwargs)


# engine-owned series (reference: engine.py:14-54)
DATA_READ_BYTES = _series(Counter, "data_read_bytes_total", "Bytes read from the engine socket")
DATA_READ_LINES = _series(Counter, "data_read_lines_total", "Lines read from the engine socket")
DATA_WRITTEN_BYTES = _series(Counter, "data_written_bytes_total", "Bytes written to outputs")
DATA_WRITTEN_LINES = _series(Counter, "data_written_lines_total", "Lines written to outputs")
DATA_DROPPED_BYTES = _series(Counter, "data_dropped_bytes_total", "Bytes dropped on slow/dead outputs")
DATA_DROPPED_LINES = _series(Counter, "data_dropped_lines_total", "Lines dropped on slow/dead outputs")
PROCESSING_ERRORS = _series(Counter, "processing_errors_total", "Exceptions raised by process()")

# service-owned series (reference: core.py:24-61)
ENGINE_RUNNING = _series(Enum, "engine_running", "Engine run state", states=["running", "stopped"])
ENGINE_STARTS = _series(Counter, "engine_starts_total", "Engine starts")
PROCESSING_DURATION = _series(
    Histogram,
    "processing_duration_seconds",
    "End-to-end process() duration",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)
DATA_PROCESSED_BYTES = _series(Counter, "data_processed_bytes_total", "Bytes handed to process()")
DATA_PROCESSED_LINES = _series(Counter, "data_processed_lines_total", "Lines handed to process()")

# TPU-build additions: per-chip throughput (BASELINE.json north star asks the
# /metrics endpoint to report per-chip rates; new series, new 'device' label,
# existing series untouched)
DEVICE_LABELS = ("component_type", "component_id", "device")
DEVICE_BATCHES = _series(Counter, "detector_device_batches_total", "Scored batches per device", DEVICE_LABELS)
DEVICE_LINES = _series(Counter, "detector_device_lines_total", "Scored lines per device", DEVICE_LABELS)
BATCH_SIZE_HIST = _series(
    Histogram,
    "detector_batch_size",
    "Dispatched micro-batch sizes",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
# fused native featurization (utils/matchkern dm_featurize_batch/_frames):
# native = rows the C kernel tokenized; fallback = rows it flagged for the
# exact-parity Python path (plus every row when the kernel is unavailable
# or native_featurize is off). fallback/(native+fallback) is the fraction
# of traffic NOT riding the fast path — a sustained rise means malformed
# or parity-hostile payloads are eating the featurization budget.
FEATURIZE_NATIVE_ROWS = _series(
    Counter, "featurize_native_rows_total",
    "Rows featurized by the native (C, row-parallel) kernel")
FEATURIZE_FALLBACK_ROWS = _series(
    Counter, "featurize_fallback_rows_total",
    "Rows featurized by the Python fallback path (kernel-flagged or kernel unavailable)")

# zero-copy host path (PR 7): which path decoded + serialized each parser
# row. native = the fused whole-row kernel OR the decode-span + native-emit
# hybrid; fallback = rows that crossed into pb2 objects (kernel-flagged
# strict failures, or the kernels unavailable / native_parse off). A
# sustained fallback rise means parity-hostile payloads are eating the
# parse budget — same reading as the featurize pair.
PARSE_NATIVE_ROWS = _series(
    Counter, "parse_native_rows_total",
    "Parser rows decoded and serialized by the native (C) host path")
PARSE_FALLBACK_ROWS = _series(
    Counter, "parse_fallback_rows_total",
    "Parser rows that fell back to the pb2 Python path (kernel-flagged or "
    "kernel unavailable)")
# shm zero-copy framing (engine/shm.py): frames the engine sent by
# reference into a shared-memory slot (mode=zero_copy) vs frames that
# copy-downgraded onto the wire (mode=copy — remote peer, oversized
# payload, or no free slot because a receiver is slow/dead). A copy-mode
# climb with zero_copy_framing on is the slow-receiver signal.
SHM_LABELS = ("component_type", "component_id", "mode")
SHM_FRAMES = _series(
    Counter, "shm_frames_total",
    "Frames sent through the zero-copy shm path (mode=zero_copy) or "
    "copy-downgraded (mode=copy) while zero_copy_framing is enabled",
    SHM_LABELS)

# pipeline tracing series (engine_trace: true — engine.py hop stamping).
# Stage dwell and transit are observed by every tracing stage; e2e only by
# the terminal stage (no forwarding outputs), so its count is the pipeline's
# completed-trace count, not a per-hop multiple.
_DWELL_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                  0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)
PIPELINE_STAGE_DWELL = _series(
    Histogram,
    "pipeline_stage_dwell_seconds",
    "Frame time inside this stage: ingress recv to egress send",
    buckets=_DWELL_BUCKETS,
)
PIPELINE_TRANSIT = _series(
    Histogram,
    "pipeline_transit_seconds",
    "Wire + queue time from the upstream stage's send to this stage's recv",
    buckets=_DWELL_BUCKETS,
)
PIPELINE_E2E_LATENCY = _series(
    Histogram,
    "pipeline_e2e_latency_seconds",
    "Pipeline ingest to terminal-stage completion (terminal stage only)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0),
)
INGRESS_BACKLOG = _series(
    Gauge,
    "engine_ingress_backlog",
    "Messages drained into the current dispatch burst; pinned at "
    "engine_batch_size means the ingress is saturated",
)
OUTPUT_SEND_BACKLOG = _series(
    Gauge,
    "output_send_backlog",
    "Output sockets currently waiting on a full peer queue",
)

# self-diagnosis series (engine/health.py): the watchdog rolls the
# per-subsystem checks into one Enum per process and exports every
# registered loop's heartbeat age; ops/alerts.yml alerts on both (and the
# alert rules are pinned to this registry by tests/test_observability.py,
# the same both-directions discipline as the Grafana panels).
ENGINE_HEALTH_STATE = _series(
    Enum,
    "engine_health_state",
    "Watchdog roll-up of the per-subsystem health checks",
    states=["healthy", "degraded", "unhealthy"],
)
HEARTBEAT_LABELS = ("component_type", "component_id", "loop")
HEARTBEAT_AGE = _series(
    Gauge,
    "engine_heartbeat_age_seconds",
    "Seconds since the named loop last stamped its heartbeat",
    HEARTBEAT_LABELS,
)
BUILD_INFO_LABELS = ("version", "dm_feature_version", "dmt_feature_version")
BUILD_INFO = _series(
    Gauge,
    "dm_build_info",
    "Constant 1; the labels carry the deployed package version and the "
    "native kernels' feature versions",
    BUILD_INFO_LABELS,
)

# device-side observability (engine/device_obs.py): the XLA compile ledger
# attributes every backend compile to the dispatch bucket that triggered it
# (few compiled shapes is the TPU-serving contract — SURVEY.md hard part #2),
# and flags compiles that happen on the dispatch path AFTER warm-up completed
# as unexpected recompiles, the RecompileStorm alert signal.
XLA_LABELS = ("component_type", "component_id", "bucket", "backend")
XLA_COMPILES = _series(
    Counter,
    "scorer_xla_compiles_total",
    "XLA backend compiles, attributed to the batch bucket that triggered them",
    XLA_LABELS,
)
XLA_COMPILE_SECONDS = _series(
    Counter,
    "scorer_xla_compile_seconds_total",
    "Wall seconds spent in XLA backend compiles per bucket",
    XLA_LABELS,
)
XLA_RECOMPILES_UNEXPECTED = _series(
    Counter,
    "scorer_xla_recompiles_unexpected_total",
    "Compiles on the dispatch path after warm-up completed — each one "
    "stalls the engine loop for the full compile; a nonzero rate is a "
    "recompile storm (ops/alerts.yml RecompileStorm)",
)
# warm-start serving (dmwarm, PR 17): the cold-start contract. The warm-up
# gauge splits boot→first-score into its three phases — aot (the
# lower().compile() pass over the warm bucket set), cache_load (persistent-
# cache deserialization time folded into those compiles), device_put
# (params landing in HBM / mesh shards) — set once per boot, so a replica
# whose aot phase blows past the fleet norm is visible per-phase
# (ops/alerts.yml ReplicaColdStartSlow). The cache pair only moves while
# the persistent compile cache is armed (compile_cache_enabled /
# DETECTMATE_JAX_CACHE): hits are deserialized cache entries (direct
# /jax/compilation_cache/cache_hits events, plus sub-threshold ledger
# compiles), misses are real backend compiles that had to run — a fleet
# whose replicas share a compile_cache_dir should see hits dominate from
# the second boot on.
WARMUP_PHASE_LABELS = ("component_type", "component_id", "phase")
SCORER_WARMUP_SECONDS = _series(
    Gauge,
    "scorer_warmup_seconds",
    "Wall seconds of the scorer's boot warm-up by phase: aot (warm-set "
    "lower+compile), cache_load (persistent-cache deserialization), "
    "device_put (params to HBM/mesh); set once per boot",
    WARMUP_PHASE_LABELS,
)
COMPILE_CACHE_HITS = _series(
    Counter,
    "compile_cache_hits_total",
    "Persistent compile-cache hits: compiles served by deserializing a "
    "cached executable instead of running XLA (only moves while the cache "
    "is armed)",
)
COMPILE_CACHE_MISSES = _series(
    Counter,
    "compile_cache_misses_total",
    "Persistent compile-cache misses: real XLA backend compiles that ran "
    "with the cache armed (each one then populates the shared dir)",
)
# HBM residency, refreshed AT SCRAPE TIME (Gauge.set_function bound to
# jax Device.memory_stats) — absent on backends without memory stats (CPU)
HBM_LABELS = ("component_type", "component_id", "device", "kind")
DEVICE_HBM = _series(
    Gauge,
    "device_hbm_bytes",
    "Device memory from jax Device.memory_stats(), kind=in_use|limit, "
    "read at scrape time",
    HBM_LABELS,
)

# per-dispatch batch telemetry (library/detectors/jax_scorer.py): occupancy
# is real rows / padded bucket rows (padding waste is 1 - occupancy); the
# queue-wait vs device-time split attributes each batch's latency to host
# queueing (upload workers / fit backlog) vs device compute + readback, with
# the host-CPU-twin path and the accelerator path as separate label values.
PATH_LABELS = ("component_type", "component_id", "path")
BATCH_OCCUPANCY = _series(
    Histogram,
    "detector_batch_occupancy",
    "Real rows / padded bucket size per dispatched batch (1.0 = no padding)",
    PATH_LABELS,
    buckets=(0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
)
BATCH_QUEUE_WAIT = _series(
    Histogram,
    "detector_queue_wait_seconds",
    "Dispatch-call to scoring-call-start wait per batch (worker queue / "
    "inline ~0)",
    PATH_LABELS,
    buckets=_DWELL_BUCKETS,
)
BATCH_DEVICE_SECONDS = _series(
    Histogram,
    "detector_device_seconds",
    "Scoring-call start to host-readable scores per batch (device compute "
    "+ readback on the device path; synchronous compute on the host path)",
    PATH_LABELS,
    buckets=_DWELL_BUCKETS,
)
BUCKET_LABELS = ("component_type", "component_id", "bucket", "path")
BUCKET_SELECTED = _series(
    Counter,
    "detector_bucket_selected_total",
    "Dispatches per compile bucket and scoring path (host CPU twin vs "
    "accelerator)",
    BUCKET_LABELS,
)

# open-loop load generation (loadgen/): the CLIENT-side view of the
# pipeline a load run drives. sent/received count the generator's traced
# frames and their contained lines; lost counts trace ids that never
# reached the collector after the settle window (loss, not filtering — the
# soak profiles are configured so every row flows through); the e2e
# histogram is client-observed latency measured from each frame's SCHEDULED
# arrival time (coordinated-omission guard), the external twin of
# pipeline_e2e_latency_seconds — their p99 gap is the ingress/egress blind
# spot (docs/walkthrough.md "read the client skew").
LOADGEN_SENT_FRAMES = _series(
    Counter, "loadgen_sent_frames_total",
    "Traced wire frames the open-loop load generator scheduled and sent")
LOADGEN_SENT_LINES = _series(
    Counter, "loadgen_sent_lines_total",
    "Lines (corpus rows) the open-loop load generator sent")
LOADGEN_RECEIVED_FRAMES = _series(
    Counter, "loadgen_received_frames_total",
    "Frames the load collector received at the pipeline sink")
LOADGEN_RECEIVED_LINES = _series(
    Counter, "loadgen_received_lines_total",
    "Lines the load collector received at the pipeline sink")
LOADGEN_LOST_TRACES = _series(
    Counter, "loadgen_lost_traces_total",
    "Sent trace ids never observed at the collector after the settle "
    "window — client-visible loss, the soak harness's loss==0 gate")
LOADGEN_E2E_LATENCY = _series(
    Histogram, "loadgen_e2e_latency_seconds",
    "Client-observed e2e latency: collector receive time minus the frame's "
    "scheduled (open-loop) arrival time",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0),
)
LOADGEN_OFFERED_RATE = _series(
    Gauge, "loadgen_offered_lines_per_s",
    "Configured open-loop arrival rate of the active load run (0 = idle)")
LOADGEN_SEND_LAG = _series(
    Gauge, "loadgen_send_lag_seconds",
    "How far the load sender is running behind its arrival schedule; "
    "sustained growth means the generator itself cannot source the "
    "offered rate (the scheduled stamps still keep latency honest)")

# replica-parallel serving tier (router/): one routing stage fanning frames
# across N scorer replicas. frames_total splits traffic by replica and the
# policy that picked it; replica_state is the supervisor's state machine
# (3=active, 2=recovering, 1=draining, 0=drained) — anything below 3 for
# long is the ReplicaDrainedSustained page; requeue_total counts frames
# resent to a healthy peer after a replica died holding them (at-least-once
# redelivery, the replica_kill soak's zero-loss mechanism); inflight is the
# unacked credit window per replica (pinned at router_credit_window means
# that replica is not draining its ingest).
REPLICA_LABELS = ("component_type", "component_id", "replica", "policy")
ROUTER_FRAMES = _series(
    Counter, "router_frames_total",
    "Frames the replica router dispatched, by replica and balancing policy",
    REPLICA_LABELS)
ROUTER_REPLICA_STATE = _series(
    Gauge, "router_replica_state",
    "Supervisor state per replica: 3=active, 2=recovering, 1=draining, "
    "0=drained",
    ("component_type", "component_id", "replica"))
ROUTER_REQUEUE = _series(
    Counter, "router_requeue_total",
    "Frames requeued to a healthy peer after their replica was drained "
    "while still holding them unacked (at-least-once redelivery)")
ROUTER_INFLIGHT = _series(
    Gauge, "router_inflight",
    "Unacked frames outstanding per replica (the credit window); pinned at "
    "router_credit_window means the replica is not draining its ingest",
    ("component_type", "component_id", "replica"))

# model lifecycle (rollout/): the dmroll subsystem's observable contract.
# Swaps count every cutover attempt by outcome (promoted / rolled_back /
# holdback / pinned / failed); shadow divergence is the per-row |candidate
# score - live score| while a canary shadows (the ModelCanaryDiverging
# signal — decision flips gate promotion separately, /admin/model has
# both); checkpoint age is computed at scrape time off the versioned
# store's manifest (a wedged trainer looks stale, ModelCheckpointStale);
# version info is a constant-1 gauge whose labels carry the live
# checkpoint version + model family (the fleet-skew view: one query shows
# which replica serves which version).
SWAP_LABELS = ("component_type", "component_id", "result")
MODEL_SWAPS = _series(
    Counter, "model_swaps_total",
    "Model hot-swap/cutover attempts by outcome: promoted, rolled_back, "
    "holdback (canary gate refused), pinned, failed",
    SWAP_LABELS)
MODEL_SHADOW_DIVERGENCE = _series(
    Histogram, "model_shadow_divergence",
    "Per-row |candidate - live| score delta while a candidate shadows",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 25.0))
MODEL_CHECKPOINT_AGE = _series(
    Gauge, "model_checkpoint_age_seconds",
    "Seconds since the rollout store's newest checkpoint was committed "
    "(read at scrape time; ages from manager start when none exists yet)")
MODEL_VERSION_LABELS = ("component_type", "component_id", "version", "model")
MODEL_VERSION_INFO = _series(
    Gauge, "model_version_info",
    "Constant 1; the labels carry the live model checkpoint version and "
    "model family (0 = the boot-time fit, never hot-swapped)",
    MODEL_VERSION_LABELS)

# drift & capacity observability (obs/): the dmdrift contract. Drift score
# compares the LIVE score distribution (the dmroll reservoir's paired
# rows+scores) against the baseline pinned at promote time: stat="ks" is
# the two-sample Kolmogorov–Smirnov statistic, stat="psi" the population
# stability index over baseline-quantile bins; features_over_threshold is
# how many token columns exceed the per-feature PSI ceiling — together the
# ModelDriftSustained signal. Capacity is the calibrated per-replica
# throughput model (busy-time arithmetic while traffic flows, a bounded
# idle micro-probe otherwise); headroom is offered rate ÷ modeled capacity
# — the router republishes both under its own labels as the tier-wide
# predictive scale-out signal (CapacityHeadroomLow, ops/k8s-replicas.yaml).
DRIFT_LABELS = ("component_type", "component_id", "stat")
MODEL_DRIFT_SCORE = _series(
    Gauge, "model_drift_score",
    "Live-vs-baseline score-distribution divergence, by statistic: "
    "stat=\"ks\" (two-sample Kolmogorov–Smirnov) or stat=\"psi\" "
    "(population stability index)",
    DRIFT_LABELS)
MODEL_DRIFT_FEATURES = _series(
    Gauge, "model_drift_features_over_threshold",
    "Token feature columns whose per-feature PSI against the pinned "
    "baseline exceeds drift_feature_psi_threshold")
REPLICA_CAPACITY = _series(
    Gauge, "replica_capacity_lines_per_s",
    "Modeled scoring capacity of this replica (lines/s at full device "
    "busy): rows ÷ device-seconds over the live window, or the idle "
    "micro-probe's measured rate when no traffic flows")
CAPACITY_HEADROOM = _series(
    Gauge, "capacity_headroom_ratio",
    "Offered line rate ÷ modeled capacity (0 = idle, 1 = saturated); the "
    "predictive scale-out signal beside the reactive backlog gauge")

# durable ingress spool (wal/, PR 11): the dmwal observability contract.
# Depth/bytes/age are computed AT SCRAPE TIME (Gauge.set_function bound to
# the live spool — a wedged engine thread cannot freeze them, the same
# discipline as the heartbeat ages); depth is appended-minus-acked frames,
# age is how long the OLDEST unacked record has been waiting — the two
# SpoolDepthHigh/SpoolAgeHigh alert signals (a growing age with a flat
# depth means the stage stopped draining entirely: the ingress_crash soak
# fires it during the outage). fsync seconds attribute the durability tax;
# replayed frames count recovery replays (mode="recovery", after a crash),
# operator pipeline replays (mode="pipeline") and offline canary scoring
# (mode="shadow") separately.
WAL_SPOOL_DEPTH = _series(
    Gauge, "wal_spool_depth_frames",
    "Frames appended to the durable ingress spool but not yet acked "
    "(handed downstream); read at scrape time off the live spool")
WAL_SPOOL_BYTES = _series(
    Gauge, "wal_spool_bytes",
    "On-disk bytes of the ingress spool's segment files (retention prunes "
    "sealed fully-acked segments; the unacked suffix is never pruned)")
WAL_OLDEST_UNACKED_AGE = _series(
    Gauge, "wal_oldest_unacked_age_seconds",
    "Age of the oldest unacked spool record; keeps growing while the "
    "stage is down or wedged (the SpoolAgeHigh signal)")
WAL_FSYNC_SECONDS = _series(
    Counter, "wal_fsync_seconds_total",
    "Wall seconds spent in WAL fsync batches (the durability tax of "
    "wal_fsync_interval_ms)")
WAL_REPLAY_LABELS = ("component_type", "component_id", "mode")
WAL_REPLAYED_FRAMES = _series(
    Counter, "wal_replayed_frames_total",
    "Recorded frames re-driven through the pipeline, by mode: recovery "
    "(post-crash unacked-suffix replay), pipeline (operator replay/"
    "backfill via /admin/replay), shadow (offline dmroll canary scoring)",
    WAL_REPLAY_LABELS)

# adaptive continuous batching (library/detectors/jax_scorer.py coalescer):
# rows held across process_batch calls toward the best-fitting warm bucket
# under a latency budget. Depth is the current hold; releases count why
# each coalesced batch left — full (target occupancy reached), deadline
# (oldest row's batch_deadline_ms budget spent), flush (idle/teardown
# drain). A deadline-dominated mix with low occupancy means the budget is
# too small for the arrival rate (ops/alerts.yml BatchOccupancyLow).
COALESCE_DEPTH = _series(
    Gauge,
    "detector_coalesce_depth",
    "Rows currently held by the adaptive batch coalescer, waiting for a "
    "bucket to fill or for the oldest row's deadline",
)
RELEASE_LABELS = ("component_type", "component_id", "reason")
DEADLINE_RELEASES = _series(
    Counter,
    "detector_deadline_releases_total",
    "Coalesced micro-batch releases by reason: full (target occupancy "
    "reached), deadline (latency budget spent), flush (idle/teardown)",
    RELEASE_LABELS,
)

# multi-tenant admission control (shed/, dmshed): the ingress overload
# contract. Cardinality discipline — tenant-attributed series carry the
# quota tier and a BOUNDED hashed tenant bucket (shed_tenant_buckets label
# values), never raw tenant ids; exact per-tenant counts live behind
# GET /admin/tenants. shed reasons: quota (that tenant's own token bucket
# is empty) vs ladder (the global degradation ladder gated its whole
# tier). The ladder Enum is the deterministic-overload state machine:
# normal → shed_best_effort → shed_burst → emergency, climb fast / recover
# slow like the watchdog (ops/alerts.yml DegradationLadderActive).
SHED_LABELS = ("component_type", "component_id", "tier", "tenant_bucket",
               "reason")
SHED_FRAMES = _series(
    Counter, "shed_frames_total",
    "Ingress frames refused by admission control, by quota tier, hashed "
    "tenant bucket, and reason: quota (tenant over its own token bucket) "
    "or ladder (tier gated by the degradation ladder)",
    SHED_LABELS)
ADMIT_LABELS = ("component_type", "component_id", "tier", "tenant_bucket")
ADMITTED_FRAMES = _series(
    Counter, "admitted_frames_total",
    "Ingress frames admitted past admission control, by quota tier and "
    "hashed tenant bucket",
    ADMIT_LABELS)
SHED_NACKS = _series(
    Counter, "shed_nacks_total",
    "Structured retry-after NACK replies sent for refused frames in "
    "reply mode (admission shed or drop-mode overflow) — the sender-"
    "visible twin of shed_frames_total",
)
SHED_LADDER_STATE = _series(
    Enum, "shed_ladder_state",
    "The global overload degradation ladder: which tiers ingress "
    "admission currently sheds",
    states=["normal", "shed_best_effort", "shed_burst", "emergency"],
)

# fault tolerance (faults/ + wal/deadletter.py + spool degradation, dmfault).
# faults_injected_total only moves while a FaultPlan is armed (chaos runs);
# in production it stays flat at absence. The WAL disk-error pair is the
# degradation policy's contract: errors count every append/fsync OSError
# the spool absorbed instead of letting it kill the EngineLoop thread, and
# the degraded gauge is 1 exactly while the spool is serving NON-DURABLY
# after a disk error (wal_on_disk_error: degrade) — the WalDegraded page,
# cleared when a write succeeds and durability re-arms. The DLQ series are
# the poison-frame quarantine: depth is read at scrape time off the live
# spool (same discipline as the WAL gauges), quarantined counts frames
# moved aside by reason (processing_error / replay / requeue_failed), and
# a depth that grows run-over-run is the DeadLetterGrowing ticket.
FAULT_LABELS = ("component_type", "component_id", "site", "kind")
FAULTS_INJECTED = _series(
    Counter, "faults_injected_total",
    "Faults executed by the armed FaultPlan, by instrumented site and "
    "fault kind (flat at absence unless a chaos plan is armed)",
    FAULT_LABELS)
WAL_FSYNC_ERRORS = _series(
    Counter, "wal_fsync_errors_total",
    "OSErrors (EIO/ENOSPC/...) absorbed by the ingress spool's append/"
    "fsync path instead of escaping into the EngineLoop thread")
WAL_SPOOL_DEGRADED = _series(
    Gauge, "wal_spool_degraded",
    "1 while the ingress spool is serving non-durably after a disk error "
    "(wal_on_disk_error: degrade); re-arms to 0 when writes succeed again")
DLQ_DEPTH = _series(
    Gauge, "dlq_depth_frames",
    "Frames quarantined in the dead-letter spool and not yet requeued or "
    "purged; read at scrape time off the live DLQ")
DLQ_REASON_LABELS = ("component_type", "component_id", "reason")
DLQ_QUARANTINED = _series(
    Counter, "dlq_quarantined_total",
    "Frames moved to the dead-letter quarantine after exhausting their "
    "processing attempts, by reason",
    DLQ_REASON_LABELS)
DLQ_REQUEUED = _series(
    Counter, "dlq_requeued_total",
    "Quarantined frames re-driven through the pipeline via "
    "POST /admin/dlq requeue")

# cross-stage telemetry (telemetry/, dmtel). The exporter side
# (telemetry/spans.py) runs inside every traced engine: its only hot-loop
# footprint is one bounded deque append per frame, so the single series it
# owns counts what the bounded queue/sender REFUSED (queue full, dead
# telemetry link) — spans are shed, never the pipeline. Everything else is
# collector-side (telemetry/collector.py): spans counted by their assembled
# trace's tail-sampling verdict, traces assembled vs dropped (healthy traces
# the sampler declined) vs incomplete (watermark/timeout flush without a
# terminal hop), duplicate hop spans deduped (router at-least-once requeue
# makes duplicates NORMAL, not an error), OTLP push outcomes, and the
# backlog gauge (open traces + unparsed frames) behind the
# TelemetryCollectorBacklog alert.
TELEMETRY_EXPORT_DROPPED = _series(
    Counter, "telemetry_spans_export_dropped_total",
    "Spans dropped by the engine-side exporter instead of blocking the hot "
    "loop (bounded queue full, or the telemetry link refused the frame)")
VERDICT_LABELS = ("component_type", "component_id", "verdict")
TELEMETRY_SPANS = _series(
    Counter, "telemetry_spans_total",
    "Hop spans ingested by the telemetry collector, by the tail-sampling "
    "verdict of the trace they were assembled into",
    VERDICT_LABELS)
TELEMETRY_TRACES_ASSEMBLED = _series(
    Counter, "telemetry_traces_assembled_total",
    "Pipeline traces fully assembled by the collector (terminal hop seen "
    "and the completion watermark passed)")
TELEMETRY_TRACES_DROPPED = _series(
    Counter, "telemetry_traces_dropped_total",
    "Healthy assembled traces the tail sampler declined to retain "
    "(1 - telemetry_sample_healthy_ratio of healthy traffic)")
TELEMETRY_TRACES_INCOMPLETE = _series(
    Counter, "telemetry_traces_incomplete_total",
    "Traces flushed by the collector without a terminal hop after "
    "telemetry_trace_timeout_s (a stage died, shed mid-pipeline, or its "
    "exporter dropped the span)")
TELEMETRY_SPANS_DEDUPED = _series(
    Counter, "telemetry_spans_deduped_total",
    "Duplicate (trace, stage) hop spans discarded during assembly — "
    "router at-least-once redelivery makes these normal")
OTLP_LABELS = ("component_type", "component_id", "result")
TELEMETRY_OTLP_PUSHES = _series(
    Counter, "telemetry_otlp_pushes_total",
    "OTLP/JSON export batches pushed to telemetry_otlp_url, by result "
    "(ok / error)",
    OTLP_LABELS)
TELEMETRY_COLLECTOR_BACKLOG = _series(
    Gauge, "telemetry_collector_backlog",
    "Open (not yet completed or flushed) traces held by the collector's "
    "assembler; sustained growth means the completion watermark is not "
    "advancing (a stage's exporter went quiet) or ingest outruns assembly")
