"""Prometheus metric registry helpers.

The reference keeps one module-global registry and needs idempotent metric
creation because tests build several services per process (reference:
src/service/core.py:45-52 scans ``REGISTRY._collector_to_names``). We keep a
private name → collector map instead of scanning private registry state.

Metric names and label sets are the reference's observable contract
(reference: src/service/core.py:24-61, src/service/features/engine.py:14-54,
docs/prometheus.md:29-47) and must not change.
"""
from __future__ import annotations

import threading
from typing import Dict, Sequence, Type

from prometheus_client import REGISTRY, Counter, Enum, Gauge, Histogram

_LOCK = threading.Lock()
_CACHE: Dict[str, object] = {}


def get_or_create(
    metric_cls: Type,
    name: str,
    documentation: str,
    labelnames: Sequence[str] = (),
    **kwargs,
):
    """Return the process-wide collector for ``name``, creating it once."""
    with _LOCK:
        found = _CACHE.get(name)
        if found is not None:
            return found
        try:
            metric = metric_cls(name, documentation, labelnames=labelnames, **kwargs)
        except ValueError:
            # registered by someone else (e.g. an earlier non-cached path):
            # locate it in the default registry
            for collector, names in list(REGISTRY._collector_to_names.items()):
                if name in names or any(n.startswith(name) for n in names):
                    _CACHE[name] = collector
                    return collector
            raise
        _CACHE[name] = metric
        return metric


# -- reference metric contract (labels: component_type, component_id) -------
LABELS = ("component_type", "component_id")

# engine-owned series (reference: engine.py:14-54)
DATA_READ_BYTES = lambda: get_or_create(Counter, "data_read_bytes_total", "Bytes read from the engine socket", LABELS)
DATA_READ_LINES = lambda: get_or_create(Counter, "data_read_lines_total", "Lines read from the engine socket", LABELS)
DATA_WRITTEN_BYTES = lambda: get_or_create(Counter, "data_written_bytes_total", "Bytes written to outputs", LABELS)
DATA_WRITTEN_LINES = lambda: get_or_create(Counter, "data_written_lines_total", "Lines written to outputs", LABELS)
DATA_DROPPED_BYTES = lambda: get_or_create(Counter, "data_dropped_bytes_total", "Bytes dropped on slow/dead outputs", LABELS)
DATA_DROPPED_LINES = lambda: get_or_create(Counter, "data_dropped_lines_total", "Lines dropped on slow/dead outputs", LABELS)
PROCESSING_ERRORS = lambda: get_or_create(Counter, "processing_errors_total", "Exceptions raised by process()", LABELS)

# service-owned series (reference: core.py:24-61)
ENGINE_RUNNING = lambda: get_or_create(Enum, "engine_running", "Engine run state", LABELS, states=["running", "stopped"])
ENGINE_STARTS = lambda: get_or_create(Counter, "engine_starts_total", "Engine starts", LABELS)
PROCESSING_DURATION = lambda: get_or_create(
    Histogram,
    "processing_duration_seconds",
    "End-to-end process() duration",
    LABELS,
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)
DATA_PROCESSED_BYTES = lambda: get_or_create(Counter, "data_processed_bytes_total", "Bytes handed to process()", LABELS)
DATA_PROCESSED_LINES = lambda: get_or_create(Counter, "data_processed_lines_total", "Lines handed to process()", LABELS)

# TPU-build additions: per-chip throughput (BASELINE.json north star asks the
# /metrics endpoint to report per-chip rates; new series, new 'device' label,
# existing series untouched)
DEVICE_LABELS = ("component_type", "component_id", "device")
DEVICE_BATCHES = lambda: get_or_create(Counter, "detector_device_batches_total", "Scored batches per device", DEVICE_LABELS)
DEVICE_LINES = lambda: get_or_create(Counter, "detector_device_lines_total", "Scored lines per device", DEVICE_LABELS)
BATCH_SIZE_HIST = lambda: get_or_create(
    Histogram,
    "detector_batch_size",
    "Dispatched micro-batch sizes",
    LABELS,
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
