"""Device-side observability: the XLA compile ledger and batch span log.

The accelerator side of the pipeline — ``jax.jit`` scoring, bucket warm-up,
host/device routing — was a black box: a recompile storm or a
padding-wasteful bucket mix was invisible until it surfaced as e2e latency.
This module closes that gap with the same contract machinery the host
pipeline already has (declared series, Grafana row, alert rules, structured
events):

* :class:`CompileLedger` — every XLA backend compile in the process is
  recorded (jax.monitoring's ``backend_compile_duration`` event) and
  attributed to the dispatch bucket / code path that triggered it via a
  thread-local :meth:`CompileLedger.context` the scorer wraps around its jit
  call sites. Counters: ``scorer_xla_compiles_total{bucket,backend}`` and
  ``scorer_xla_compile_seconds_total{bucket,backend}``. A bounded ring of
  compile events is served at ``GET /admin/xla``.
* **unexpected-recompile detection** — after the scorer marks warm-up
  complete, any compile inside a *dispatch* context (``expected=False``) is
  a recompile the bucket design promised would never happen. Each one
  increments ``scorer_xla_recompiles_unexpected_total`` (the
  ``RecompileStorm`` alert signal), emits a structured
  ``unexpected_recompile`` event through the bound
  :class:`~detectmateservice_tpu.engine.health.HealthMonitor` (ring +
  logger, with the flight recorder's last trace id), and arms the
  ``xla_recompile_storm`` watchdog check.
* **batch span log** — each drained device batch records a span (bucket,
  real rows, path, queue-wait vs device-time split, the PR-1 trace id
  current at dispatch) into a bounded ring, also on ``GET /admin/xla``.
* :func:`export_hbm_gauges` — ``device_hbm_bytes{device,kind}`` computed at
  scrape time from ``jax.Device.memory_stats()`` (absent on CPU backends,
  which return ``None`` — then nothing is exported).

Attribution contract: only compiles that fire inside *some* ledger context
participate in unexpected-recompile detection. Compiles with no active
context (another library jit-compiling in the same process) are still
recorded in the ring — ``where: external`` — but never flagged, so the
detector cannot false-alarm on co-tenant compilation.

The module imports no jax at import time: non-jax stages (parsers, readers)
construct Services without paying jax's import cost; the monitoring listener
installs lazily from the scorer.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, Optional, Tuple

from . import metrics as m
from .health import DEGRADED, PASS, UNHEALTHY

# the jax.monitoring event name that marks one XLA backend compile
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# the jax.monitoring event recorded when the persistent compilation cache
# serves a compile by deserializing a stored executable (the backend compile
# — and therefore COMPILE_EVENT — is skipped entirely on that path)
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

# duration event covering the deserialization itself — the ground truth for
# the warm-up's cache_load phase split
CACHE_RETRIEVAL_EVENT = "/jax/compilation_cache/cache_retrieval_time_sec"

# how long after the last unexpected recompile the watchdog check stays
# degraded (long enough to survive a scrape/evaluation gap, short enough
# that a one-off mis-sized batch does not page for an hour)
RECOMPILE_STORM_WINDOW_S = 120.0


class CompileLedger:
    """Bounded record of XLA compiles + device-batch spans for one process.

    Thread-safe; the hot cost is zero when no compile happens (the listener
    only fires on actual backend compiles, and span recording is one lock +
    deque append per *drained batch*, never per message)."""

    def __init__(self, max_events: int = 256, max_spans: int = 256,
                 storm_window_s: float = RECOMPILE_STORM_WINDOW_S) -> None:
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, max_events))
        self._spans: deque = deque(maxlen=max(1, max_spans))
        self._seq = 0
        self._span_seq = 0
        self._warmed = False
        self._storm_window_s = storm_window_s
        self._labels = {"component_type": "core", "component_id": "unknown"}
        self.monitor = None               # HealthMonitor, set via bind()
        self._emit_events = True
        self._tls = threading.local()
        # label-children cache: a compile is rare but the .labels() dict
        # hash on every record would still be waste (dmlint DM-H001 idiom)
        self._compile_children: Dict[Tuple[str, str], tuple] = {}
        self._unexpected_child = None
        self._totals = {"compiles": 0, "seconds": 0.0, "unexpected": 0}
        self._last_unexpected_mono: Optional[float] = None
        self._recent_unexpected: deque = deque(maxlen=64)  # monotonic stamps
        # persistent compile-cache classifier (dmwarm): None = cache not
        # armed, counters stay silent; a threshold = a recorded "compile"
        # faster than it is a deserialized cache entry, not a real compile
        self._cache_threshold_s: Optional[float] = None
        self._cache_totals = {"hits": 0, "misses": 0}
        self._cache_children: Optional[tuple] = None
        self._cache_load_seconds = 0.0
        # boot warm-up phase timings (scorer_warmup_seconds{phase}); the
        # scorer records aot / cache_load / device_put once per boot
        self._warmup_phases: Dict[str, float] = {}
        self._warmup_children: Dict[str, Any] = {}
        # bucket-state provider (the scorer's adaptive batcher): lets
        # GET /admin/xla report the LIVE warm/retired compile-bucket sets
        # next to the compile history they explain
        self._bucket_state_fn = None

    # -- wiring ----------------------------------------------------------
    def bind(self, labels: Optional[Dict[str, str]] = None, monitor=None,
             emit_events: bool = True, register_check: bool = True) -> None:
        """Attach component identity + the health plane (called by the
        Service at construction; last bind wins — the ledger is per-process,
        like the metric registry)."""
        with self._lock:
            if labels:
                self._labels = dict(labels)
                self._compile_children.clear()
                self._unexpected_child = None
                self._cache_children = None
                self._warmup_children.clear()
            if monitor is not self.monitor:
                # a storm that predates this binding belongs to the previous
                # service — a freshly-bound monitor starts with a clean
                # storm window (the ring and counters keep the history)
                self._recent_unexpected.clear()
                self._last_unexpected_mono = None
            self.monitor = monitor
            self._emit_events = emit_events
        if monitor is not None and register_check:
            monitor.remove_check(RecompileStormCheck.name)
            monitor.add_check(RecompileStormCheck(self, monitor,
                                                  self._storm_window_s))

    def set_bucket_state_provider(self, fn) -> None:
        """Attach a callable returning the scorer's live compile-bucket
        state (warm / retired sets); surfaced under ``buckets`` in
        :meth:`snapshot`. Last registration wins — the ledger is
        per-process, like the metric registry."""
        with self._lock:
            self._bucket_state_fn = fn

    # -- attribution contexts -------------------------------------------
    @contextlib.contextmanager
    def context(self, bucket: Optional[int] = None,
                backend: Optional[str] = None, where: Optional[str] = None,
                expected: Optional[bool] = None) -> Iterator[None]:
        """Attribute compiles fired by the enclosed code to (bucket, where).

        ``expected`` is inherited from the enclosing context when ``None``
        (outermost default: True) — so a sharded-scorer context nested
        inside the dispatch path keeps the dispatch path's ``False``."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append({"bucket": bucket, "backend": backend, "where": where,
                      "expected": expected})
        try:
            yield
        finally:
            stack.pop()

    def _effective_context(self) -> Optional[Dict[str, Any]]:
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return None
        eff: Dict[str, Any] = {"bucket": None, "backend": None,
                               "where": None, "expected": True}
        for frame in stack:
            for key, value in frame.items():
                if value is not None:
                    eff[key] = value
        return eff

    # -- persistent compile-cache classification (dmwarm) ----------------
    def arm_cache_classifier(self, threshold_s: float) -> None:
        """Arm hit/miss counting: the persistent compilation cache is on,
        and a recorded compile returning in under ``threshold_s`` is a
        deserialized cache entry (utils/profiling.enable_compilation_cache
        calls this after configuring jax)."""
        with self._lock:
            self._cache_threshold_s = float(threshold_s)

    @property
    def cache_armed(self) -> bool:
        with self._lock:
            return self._cache_threshold_s is not None

    def _cache_counters(self) -> tuple:
        pair = self._cache_children
        if pair is None:
            pair = (m.COMPILE_CACHE_HITS().labels(**self._labels),
                    m.COMPILE_CACHE_MISSES().labels(**self._labels))
            self._cache_children = pair
        return pair

    def record_cache_hit(self) -> None:
        """One persistent-cache hit observed DIRECTLY (the jax
        ``cache_hits`` monitoring event — on that path the backend compile
        is skipped entirely, so :meth:`record_compile` never sees it)."""
        with self._lock:
            self._cache_totals["hits"] += 1
            hits_c, _ = self._cache_counters()
        hits_c.inc()

    def record_cache_retrieval(self, duration_s: float) -> None:
        """Accumulate persistent-cache deserialization wall time (the jax
        ``cache_retrieval_time_sec`` duration event) — the warm-up's
        cache_load phase reads the running total."""
        with self._lock:
            self._cache_load_seconds += max(0.0, float(duration_s))

    def cache_load_seconds(self) -> float:
        with self._lock:
            return self._cache_load_seconds

    # -- boot warm-up phase timings (dmwarm) -----------------------------
    def record_warmup_phase(self, phase: str, seconds: float) -> None:
        """Record one boot warm-up phase's wall time
        (``scorer_warmup_seconds{phase=aot|cache_load|device_put}``)."""
        seconds = max(0.0, float(seconds))
        with self._lock:
            self._warmup_phases[phase] = round(seconds, 6)
            child = self._warmup_children.get(phase)
            if child is None:
                child = m.SCORER_WARMUP_SECONDS().labels(
                    phase=phase, **self._labels)
                self._warmup_children[phase] = child
        child.set(seconds)

    def warmup_phases(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._warmup_phases)

    # -- warm-up lifecycle ----------------------------------------------
    def mark_warmup_complete(self) -> None:
        with self._lock:
            self._warmed = True

    @property
    def warmup_complete(self) -> bool:
        with self._lock:
            return self._warmed

    def reset(self) -> None:
        """Back to the un-warmed state with empty rings and zeroed totals
        (tests; a rebuilt scorer re-runs its warm-up and re-marks). The
        Prometheus counters are cumulative by contract and stay untouched."""
        with self._lock:
            self._warmed = False
            self._events.clear()
            self._spans.clear()
            self._totals = {"compiles": 0, "seconds": 0.0, "unexpected": 0}
            self._cache_totals = {"hits": 0, "misses": 0}
            self._cache_load_seconds = 0.0
            self._warmup_phases.clear()
            self._last_unexpected_mono = None
            self._recent_unexpected.clear()
            self._bucket_state_fn = None  # bound to a dead scorer otherwise

    # -- recording -------------------------------------------------------
    def _compile_counters(self, bucket: str, backend: str) -> tuple:
        pair = self._compile_children.get((bucket, backend))
        if pair is None:
            labels = dict(self._labels, bucket=bucket, backend=backend)
            pair = (m.XLA_COMPILES().labels(**labels),
                    m.XLA_COMPILE_SECONDS().labels(**labels))
            self._compile_children[(bucket, backend)] = pair
        return pair

    def record_compile(self, duration_s: float,
                       bucket: Optional[int] = None,
                       backend: Optional[str] = None,
                       where: Optional[str] = None,
                       expected: Optional[bool] = None) -> Dict[str, Any]:
        """Record one backend compile. Normally driven by the monitoring
        listener (attribution from the thread-local context); the explicit
        keyword arguments are the injection seam for tests."""
        eff = self._effective_context()
        attributed = eff is not None or bucket is not None
        if eff is None:
            eff = {"bucket": None, "backend": None, "where": None,
                   "expected": True}
        if bucket is not None:
            eff["bucket"] = bucket
        if backend is not None:
            eff["backend"] = backend
        if where is not None:
            eff["where"] = where
        if expected is not None:
            eff["expected"] = expected
        bucket_s = "?" if eff["bucket"] is None else str(eff["bucket"])
        backend_s = eff["backend"] or _default_backend()
        where_s = eff["where"] or ("unattributed" if attributed else "external")
        event: Dict[str, Any]
        with self._lock:
            self._seq += 1
            phase = "runtime" if self._warmed else "warmup"
            unexpected = bool(self._warmed and attributed
                              and not eff["expected"])
            self._totals["compiles"] += 1
            self._totals["seconds"] += float(duration_s)
            compiles_c, seconds_c = self._compile_counters(bucket_s, backend_s)
            event = {
                "seq": self._seq,
                "ts": round(time.time(), 6),
                "bucket": bucket_s,
                "backend": backend_s,
                "seconds": round(float(duration_s), 6),
                "where": where_s,
                "phase": phase,
                "unexpected": unexpected,
            }
            cache_c = None
            if self._cache_threshold_s is not None:
                # cache armed: a sub-threshold "compile" is a deserialized
                # cache entry (the ISSUE's hit heuristic — most hits skip
                # backend compile entirely and arrive via record_cache_hit
                # instead); anything slower is a real compile that now
                # populates the shared dir
                hit = float(duration_s) < self._cache_threshold_s
                event["cache"] = "hit" if hit else "miss"
                self._cache_totals["hits" if hit else "misses"] += 1
                hits_c, misses_c = self._cache_counters()
                cache_c = hits_c if hit else misses_c
            unexpected_c = None
            if unexpected:
                self._totals["unexpected"] += 1
                now = time.monotonic()
                self._last_unexpected_mono = now
                self._recent_unexpected.append(now)
                if self._unexpected_child is None:
                    self._unexpected_child = (
                        m.XLA_RECOMPILES_UNEXPECTED().labels(**self._labels))
                unexpected_c = self._unexpected_child
            monitor = self.monitor
            emit = unexpected and self._emit_events and monitor is not None
            self._events.append(event)
        compiles_c.inc()
        seconds_c.inc(float(duration_s))
        if cache_c is not None:
            cache_c.inc()
        if unexpected_c is not None:
            unexpected_c.inc()
        if emit:
            # outside the ledger lock: the monitor fans out to the event
            # ring and the logger, neither of which may nest under it
            monitor.emit_event(dict(event, kind="unexpected_recompile"))
        return event

    def record_span(self, bucket: int, real: int, path: str,
                    queue_wait_s: float, device_s: float,
                    trace_id: Optional[str] = None,
                    release: Optional[str] = None) -> None:
        """One drained device batch: the span the flight recorder's trace id
        links back to (PR-1 `/admin/trace` ↔ this batch). ``release`` names
        why the coalescer let the batch go (full/deadline/flush); None for
        uncoalesced dispatches."""
        with self._lock:
            self._span_seq += 1
            self._spans.append({
                "seq": self._span_seq,
                "ts": round(time.time(), 6),
                "bucket": int(bucket),
                "real": int(real),
                "occupancy": round(int(real) / max(1, int(bucket)), 4),
                "path": path,
                "queue_wait_s": round(float(queue_wait_s), 6),
                "device_s": round(float(device_s), 6),
                "trace_id": trace_id,
                "release": release,
            })

    # -- reads -----------------------------------------------------------
    def unexpected_in_window(self, window_s: Optional[float] = None,
                             now: Optional[float] = None) -> int:
        window = self._storm_window_s if window_s is None else window_s
        now = time.monotonic() if now is None else now
        with self._lock:
            return sum(1 for t in self._recent_unexpected
                       if now - t <= window)

    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """The ``GET /admin/xla`` document."""
        with self._lock:
            events = list(self._events)
            spans = list(self._spans)
            totals = dict(self._totals)
            totals["seconds"] = round(totals["seconds"], 6)
            warmed = self._warmed
            bucket_fn = self._bucket_state_fn
            cache_armed = self._cache_threshold_s is not None
            cache_totals = dict(self._cache_totals)
            warmup_phases = dict(self._warmup_phases)
        if limit is not None and limit >= 0:
            events = events[-limit:]
            spans = spans[-limit:]
        doc = {
            "warmup_complete": warmed,
            "totals": totals,
            "compiles": events,
            "batches": spans,
            "compile_cache": {"armed": cache_armed, **cache_totals},
            "warmup_phases": warmup_phases,
        }
        if bucket_fn is not None:
            try:
                doc["buckets"] = bucket_fn()
            except Exception:  # noqa: BLE001 — a racing scorer must not kill the read
                pass
        return doc


class WarmupPendingCheck:
    """Watchdog check: UNHEALTHY while the scorer's boot warm-up is in
    flight. The replica supervisor dispatches to healthy AND degraded
    replicas (router/router.py ``dispatchable``), so a booting replica that
    has not finished AOT-compiling its warm set must probe UNHEALTHY — not
    merely degraded — or scale-out would route traffic onto a replica whose
    first dispatch pays a synchronous XLA compile (exactly the cold-start
    this check makes impossible to hide). PASS once the ledger's
    ``mark_warmup_complete`` lands; the scorer registers this check at the
    top of ``setup_io`` so deep-health evaluated mid-warm-up refuses
    ACTIVE."""

    name = "scorer_warmup_pending"

    def __init__(self, ledger: CompileLedger, monitor) -> None:
        self._ledger = ledger
        self._monitor = monitor

    def evaluate(self, now: float) -> Tuple[str, str]:
        if self._ledger.monitor is not self._monitor:
            return PASS, "ledger bound to another service"
        if not self._ledger.warmup_complete:
            return UNHEALTHY, ("scorer warm-up in flight — refusing ACTIVE "
                               "until the warm set is AOT-compiled")
        phases = self._ledger.warmup_phases()
        if phases:
            total = sum(phases.values())
            return PASS, f"warm-up complete in {total:.3f}s ({phases})"
        return PASS, "warm-up complete"


class RecompileStormCheck:
    """Watchdog check: degraded while unexpected recompiles are recent.

    Only reports for the monitor the ledger is currently bound to — a
    monitor from an earlier Service in the same process (tests build many)
    keeps the check object but it evaluates to PASS, so a storm can never be
    blamed on a component that did not dispatch the batch."""

    name = "xla_recompile_storm"

    def __init__(self, ledger: CompileLedger, monitor,
                 window_s: float = RECOMPILE_STORM_WINDOW_S) -> None:
        self._ledger = ledger
        self._monitor = monitor
        self._window_s = window_s

    def evaluate(self, now: float) -> Tuple[str, str]:
        if self._ledger.monitor is not self._monitor:
            return PASS, "ledger bound to another service"
        recent = self._ledger.unexpected_in_window(self._window_s)
        if recent:
            return DEGRADED, (
                f"{recent} unexpected XLA recompile(s) in the last "
                f"{self._window_s:.0f}s — see GET /admin/xla")
        return PASS, "no unexpected recompiles"


# ---------------------------------------------------------------------------
# process-wide ledger + the (single) jax.monitoring listener
# ---------------------------------------------------------------------------
_ACTIVE = CompileLedger()
_INSTALL_LOCK = threading.Lock()
_LISTENER_INSTALLED = False


def get_ledger() -> CompileLedger:
    return _ACTIVE


def activate(ledger: CompileLedger) -> CompileLedger:
    """Swap the ledger the process-wide listener feeds (tests); returns the
    previous one so callers can restore it."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, ledger
    return prev


def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    try:
        if event == COMPILE_EVENT:
            _ACTIVE.record_compile(duration)
        elif event == CACHE_RETRIEVAL_EVENT:
            _ACTIVE.record_cache_retrieval(duration)
    except Exception:  # noqa: BLE001 — telemetry must never break a compile
        pass


def install_listener() -> bool:
    """Register the compile listener with jax.monitoring (idempotent; once
    per process). Returns False when jax is unavailable."""
    global _LISTENER_INSTALLED
    with _INSTALL_LOCK:
        if _LISTENER_INSTALLED:
            return True
        try:
            from jax import monitoring
        except ImportError:
            return False
        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _LISTENER_INSTALLED = True
        return True


_CACHE_LISTENER_INSTALLED = False


def _on_cache_event(event: str, **kwargs) -> None:
    if event != CACHE_HIT_EVENT:
        return
    try:
        _ACTIVE.record_cache_hit()
    # dmlint: ignore[DM-R001] hit counting is telemetry riding a compile —
    except Exception:  # noqa: BLE001 — it must never break the compile
        pass


def install_cache_listener() -> bool:
    """Register the persistent-cache hit listener (idempotent; once per
    process). A cache hit deserializes the stored executable and skips the
    backend compile — so COMPILE_EVENT never fires and only this event
    carries the hit. Called by ``enable_compilation_cache`` when the cache
    arms; returns False when jax is unavailable."""
    global _CACHE_LISTENER_INSTALLED
    with _INSTALL_LOCK:
        if _CACHE_LISTENER_INSTALLED:
            return True
        try:
            from jax import monitoring
        except ImportError:
            return False
        monitoring.register_event_listener(_on_cache_event)
        _CACHE_LISTENER_INSTALLED = True
        return True


def _default_backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — jax absent or not yet initialized
        return "unknown"


# ---------------------------------------------------------------------------
# HBM gauges
# ---------------------------------------------------------------------------
_HBM_LOCK = threading.Lock()
_HBM_EXPORTED: set = set()

# jax Device.memory_stats() key → exported `kind` label value
_HBM_KINDS = (("in_use", "bytes_in_use"), ("limit", "bytes_limit"))


def _hbm_reader(device, stat_key: str):
    def read() -> float:
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — a dead device must not kill the scrape
            return 0.0
        return float((stats or {}).get(stat_key, 0.0))

    return read


def export_hbm_gauges(labels: Dict[str, str]) -> int:
    """Export ``device_hbm_bytes{device,kind}`` for every local device whose
    backend reports memory stats, computed at scrape time. Returns how many
    devices export (0 on CPU, whose ``memory_stats()`` is ``None``)."""
    try:
        import jax
    except ImportError:
        return 0
    exported = 0
    for device in jax.local_devices():
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — probe failure == no stats
            stats = None
        if not stats:
            continue
        exported += 1
        key = (tuple(sorted(labels.items())), str(device))
        with _HBM_LOCK:
            if key in _HBM_EXPORTED:
                continue
            _HBM_EXPORTED.add(key)
        for kind, stat_key in _HBM_KINDS:
            m.DEVICE_HBM().labels(device=str(device), kind=kind,
                                  **labels).set_function(
                _hbm_reader(device, stat_key))
    return exported
