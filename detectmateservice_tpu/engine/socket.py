"""Data-plane transport: pair-socket surface over multiple backends.

The reference's data plane is NNG Pair0 via pynng (reference:
src/service/features/engine_socket.py:35-78, engine.py:133-179). This build
has no libnng; the same observable surface — ``listen/dial/send/recv`` with
receive timeouts, non-blocking sends, background reconnect, drop-don't-block —
is provided over:

* **zmq DEALER** pairs for ``ipc:// tcp:// inproc://`` (libzmq does background
  reconnect and bounded buffering natively; DEALER-DEALER is bidirectional 1:1
  like Pair0),
* a pure-Python **length-prefixed TLS/TCP** transport for ``tls+tcp://``
  (real ssl: server cert/key, client CA + server-name verification — parity
  with the reference's mbedTLS modes, engine_socket.py:60-71, engine.py:165-170),
* an in-process queue transport for tests,
* an optional in-tree **C++ transport** (native/transport) loaded when built,
  with the same surface.

Exception taxonomy maps 1:1 onto pynng's (Timeout / TryAgain / NNGException →
TransportTimeout / TransportAgain / TransportError), because the engine's
retry/drop logic is written against it (reference: engine.py:216-218,290-299).

The factory protocol is the seam tests use to inject fakes — kept verbatim
(reference: engine_socket.py:23-32).
"""
from __future__ import annotations

import errno
import logging
import os
import queue
import socket as _stdsocket
import ssl
import struct
import threading
import time
from typing import Dict, List, Optional, Protocol, runtime_checkable

import zmq


class TransportError(Exception):
    """Base transport failure (maps to pynng.NNGException)."""


class TransportTimeout(TransportError):
    """recv timed out (maps to pynng.Timeout)."""


class TransportAgain(TransportError):
    """Non-blocking send would block (maps to pynng.TryAgain)."""


class TransportClosed(TransportError):
    """Operation on a closed socket."""


@runtime_checkable
class EngineSocket(Protocol):
    """Minimal socket surface the engine loop uses (reference: engine_socket.py:12-20)."""

    def recv(self) -> bytes: ...
    def send(self, data: bytes, block: bool = True) -> None: ...
    def close(self) -> None: ...
    @property
    def recv_timeout(self) -> Optional[int]: ...
    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None: ...


@runtime_checkable
class EngineSocketFactory(Protocol):
    """Factory seam (reference: engine_socket.py:23-32). ``create`` returns a
    listening socket bound to ``addr``; ``create_output`` returns a dialing
    socket connected (possibly in the background) to ``addr``."""

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket: ...

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket: ...


def _split_scheme(addr: str) -> tuple:
    if "://" not in addr:
        raise TransportError(f"address {addr!r} has no scheme")
    scheme, rest = addr.split("://", 1)
    return scheme, rest


# ---------------------------------------------------------------------------
# zmq backend
# ---------------------------------------------------------------------------

_shared_ctx: Optional[zmq.Context] = None
_ctx_lock = threading.Lock()


def _context() -> zmq.Context:
    # one process-wide context so inproc:// endpoints are visible everywhere
    global _shared_ctx
    with _ctx_lock:
        if _shared_ctx is None or _shared_ctx.closed:
            _shared_ctx = zmq.Context.instance()
        return _shared_ctx


class ZmqPairSocket:
    """DEALER socket with the pair surface. 1:1 bidirectional, background
    reconnect, bounded HWM buffering; ``send(block=False)`` raises
    TransportAgain when buffers are full (drop handling is the engine's job,
    reference: engine.py:286-296)."""

    def __init__(self, sock: zmq.Socket, addr: str, unlink_on_close: Optional[str] = None):
        self._sock = sock
        self._addr = addr
        self._closed = False
        self._recv_timeout: Optional[int] = None
        self._unlink_on_close = unlink_on_close
        self._lock = threading.Lock()

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms
        self._sock.setsockopt(zmq.RCVTIMEO, -1 if ms is None else int(ms))

    def recv(self) -> bytes:
        if self._closed:
            raise TransportClosed(f"recv on closed socket {self._addr}")
        try:
            return self._sock.recv()
        except zmq.Again as exc:
            raise TransportTimeout(str(exc) or "recv timeout") from exc
        except zmq.ZMQError as exc:
            if self._closed:
                raise TransportClosed(str(exc)) from exc
            raise TransportError(str(exc)) from exc

    def recv_many(self, max_n: int, first_timeout_ms: int) -> List[bytes]:
        """Drain up to ``max_n`` frames in one call: a timed recv for the
        first, then non-blocking drains — the engine's burst collector pays
        one call per BURST instead of one per frame (the native transport's
        recv_many contract, minus its single-buffer copy). Raises
        TransportTimeout when nothing arrives within ``first_timeout_ms``."""
        if self._closed:
            raise TransportClosed(f"recv on closed socket {self._addr}")
        if max_n <= 0:
            return []  # native contract: never over-deliver past the cap
        frames: List[bytes] = []
        try:
            self._sock.setsockopt(zmq.RCVTIMEO, max(1, int(first_timeout_ms)))
            try:
                frames.append(self._sock.recv())
            finally:
                try:
                    self._sock.setsockopt(
                        zmq.RCVTIMEO,
                        -1 if self._recv_timeout is None
                        else int(self._recv_timeout))
                except zmq.ZMQError:
                    pass  # closing mid-call: frames already read still count
            while len(frames) < max_n:
                try:
                    frames.append(self._sock.recv(flags=zmq.DONTWAIT))
                except zmq.Again:
                    break
            return frames
        except zmq.Again as exc:
            raise TransportTimeout(str(exc) or "recv timeout") from exc
        except zmq.ZMQError as exc:
            if frames:
                # frames already consumed from the queue must reach the
                # caller, not vanish — the native backend returns partial
                # batches in the same situation (delivered-or-counted
                # accounting depends on it)
                return frames
            if self._closed:
                raise TransportClosed(str(exc)) from exc
            raise TransportError(str(exc)) from exc

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed:
            raise TransportClosed(f"send on closed socket {self._addr}")
        try:
            self._sock.send(data, flags=0 if block else zmq.DONTWAIT)
        except zmq.Again as exc:
            raise TransportAgain(str(exc) or "send would block") from exc
        except zmq.ZMQError as exc:
            if self._closed:
                raise TransportClosed(str(exc)) from exc
            raise TransportError(str(exc)) from exc

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._sock.close(linger=0)
        finally:
            if self._unlink_on_close:
                try:
                    os.unlink(self._unlink_on_close)
                except OSError:
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ZmqPairSocketFactory:
    """Default factory (role of the reference's NngPairSocketFactory,
    engine_socket.py:35-78)."""

    SCHEMES = ("ipc", "tcp", "inproc")

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme == "tls+tcp":
            factory = TlsTcpSocketFactory()
            return factory.create(addr, logger, tls_config)
        if scheme == "nng+tcp":
            return NngTcpSocketFactory().create(addr, logger, tls_config)
        if scheme == "nng+tls+tcp":
            return NngTlsTcpSocketFactory().create(addr, logger, tls_config)
        if scheme == "ws":
            # the Python RFC6455 transport, NOT libzmq's ws (a compile-time
            # option this image's libzmq lacks) — and wire-compatible with
            # NNG ws peers, which zmq's ws would not be
            return WsSocketFactory().create(addr, logger, tls_config)
        if scheme not in self.SCHEMES:
            raise TransportError(f"unsupported scheme {scheme!r} in {addr!r}")
        unlink = None
        if scheme == "ipc":
            # unlink a stale ipc file before bind (reference: engine_socket.py:46-54)
            path = rest
            if os.path.exists(path):
                try:
                    os.unlink(path)
                    logger.debug("unlinked stale ipc file %s", path)
                except OSError as exc:
                    raise TransportError(f"cannot unlink stale ipc file {path}: {exc}") from exc
            unlink = path
        if scheme == "tcp":
            host_port = rest.split("/", 1)[0]
            if ":" not in host_port:
                raise TransportError(f"tcp address {addr!r} requires an explicit port")
        sock = _context().socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        try:
            sock.bind(addr)
        except zmq.ZMQError as exc:
            sock.close(linger=0)  # close on bind failure (reference: engine_socket.py:72-78)
            raise TransportError(f"cannot listen on {addr}: {exc}") from exc
        logger.debug("listening on %s", addr)
        return ZmqPairSocket(sock, addr, unlink_on_close=unlink)

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, _ = _split_scheme(addr)
        if scheme == "tls+tcp":
            factory = TlsTcpSocketFactory()
            return factory.create_output(addr, logger, tls_config, dial_timeout, buffer_size)
        if scheme == "nng+tcp":
            return NngTcpSocketFactory().create_output(addr, logger, tls_config,
                                                       dial_timeout, buffer_size)
        if scheme == "nng+tls+tcp":
            return NngTlsTcpSocketFactory().create_output(addr, logger, tls_config,
                                                          dial_timeout, buffer_size)
        if scheme == "ws":
            return WsSocketFactory().create_output(addr, logger, tls_config,
                                                   dial_timeout, buffer_size)
        if scheme not in self.SCHEMES:
            raise TransportError(f"unsupported scheme {scheme!r} in {addr!r}")
        sock = _context().socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        sock.setsockopt(zmq.SNDHWM, max(1, buffer_size))
        sock.setsockopt(zmq.RCVHWM, max(1, buffer_size))
        sock.setsockopt(zmq.RECONNECT_IVL, 100)
        # ZMQ_IMMEDIATE: queue only to live connections so non-blocking sends
        # to a dead peer raise Again instead of buffering forever — matches
        # the reference's drop accounting (engine.py:286-296)
        sock.setsockopt(zmq.IMMEDIATE, 1)
        try:
            sock.connect(addr)  # async connect, like nng dial(block=False)
        except zmq.ZMQError as exc:
            sock.close(linger=0)
            raise TransportError(f"cannot dial {addr}: {exc}") from exc
        logger.debug("dialing %s (background connect)", addr)
        return ZmqPairSocket(sock, addr)


# ---------------------------------------------------------------------------
# framed-TCP core: length-prefixed frames over a (possibly wrapped) stream.
# Two users: the tls+tcp backend (ssl wrap, 4-byte frames) and the NNG
# SP-wire backend (plain TCP, SP handshake, 8-byte frames).
# ---------------------------------------------------------------------------

_FRAME_HDR = struct.Struct("!I")
_MAX_FRAME = 64 * 1024 * 1024
# Steady-state socket timeout on ESTABLISHED framed/ws connections. Serves
# two contracts at once (advisor r3 high+medium): (a) it REPLACES the dial/
# handshake timeout, which must not govern steady-state reads — a ~1 s
# connect timeout left on the socket made the reader tear down and redial
# every second of inbound idle on one-way output pipes; recv treats a tick
# as "no data yet", not an error; (b) it bounds each SEND ATTEMPT, so a
# stalled peer cannot wedge the engine thread indefinitely. Plain-TCP sends
# retry in chunks as long as the peer keeps draining (a slow reader — e.g.
# one paused in an XLA compile — is backpressure, not failure) and tear the
# connection down only after _SEND_STALL_WINDOWS consecutive zero-progress
# windows; ssl sends cannot resume a partially-written record, so a single
# timeout there tears down immediately.
_STEADY_TIMEOUT = 2.0
_SEND_STALL_WINDOWS = 5   # ~10 s of ZERO progress before giving up


def _send_with_progress(sock: _stdsocket.socket, data: bytes) -> None:
    """sendall with per-chunk timeouts and progress-based retry (plain TCP).

    ``socket.sendall`` gives no way to know how much was written when it
    times out, so a timeout there corrupts the frame stream. ``send`` does:
    loop it, retry zero-progress windows up to the stall limit, and raise
    ``socket.timeout`` only for a genuinely wedged peer."""
    view = memoryview(data)
    stalls = 0
    while view:
        try:
            sent = sock.send(view)
        except _stdsocket.timeout:
            stalls += 1
            if stalls >= _SEND_STALL_WINDOWS:
                raise
            continue
        if sent:
            stalls = 0
            view = view[sent:]


class _FramedConn:
    """One established stream connection with length-prefix framing."""

    def __init__(self, sock: _stdsocket.socket, hdr: struct.Struct = _FRAME_HDR):
        self.sock = sock
        self.send_lock = threading.Lock()
        self._hdr = hdr
        self._is_ssl = isinstance(sock, ssl.SSLSocket)

    def send_frame(self, data: bytes) -> None:
        with self.send_lock:
            try:
                payload = self._hdr.pack(len(data)) + data
                if self._is_ssl:
                    # an SSL record interrupted mid-write cannot be resumed
                    # byte-wise; rely on sendall and treat timeout as fatal
                    self.sock.sendall(payload)
                else:
                    _send_with_progress(self.sock, payload)
            except _stdsocket.timeout as exc:
                # partial frame may have hit the wire → framing is corrupt;
                # close so the reader thread runs the normal teardown path
                self.close()
                raise TransportError(
                    "send stalled (no progress for "
                    f"{_SEND_STALL_WINDOWS * _STEADY_TIMEOUT:.0f}s); "
                    "connection dropped") from exc

    def recv_frame(self) -> bytes:
        hdr = self._recv_exact(self._hdr.size)
        (length,) = self._hdr.unpack(hdr)
        if length > _MAX_FRAME:
            raise TransportError(f"oversized frame: {length} bytes")
        return self._recv_exact(length)

    def _recv_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except (_stdsocket.timeout, ssl.SSLWantReadError):
                continue  # idle tick, not an error: keep accumulating
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
        return bytes(buf)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class FramedTcpListener:
    """Server side of a framed-TCP transport. Accepts any number of dialers
    (fan-in, like many NNG dialers to one listener) and merges their frames
    into one recv queue. Replies route exactly via ``last_origin``/``send_to``
    (the engine's reply mode uses them); plain ``send`` falls back to the
    connection the last message arrived on — correct for Pair0 1:1, a
    heuristic under multi-dialer interleaving. ``prepare(raw_sock,
    server_side)`` turns an accepted TCP connection into a ``_FramedConn``
    (ssl wrap for tls+tcp, SP handshake for nng+tcp) or raises to reject
    the peer."""

    def __init__(self, host: str, port: int, prepare,
                 logger: logging.Logger, buffer_size: int = 100,
                 label: str = "framed+tcp"):
        self._logger = logger
        self._prepare = prepare
        self._label = label
        self._rq: "queue.Queue" = queue.Queue(maxsize=max(1, buffer_size))
        self._conns: List[_FramedConn] = []
        self._conns_lock = threading.Lock()
        self._last_conn: Optional[_FramedConn] = None
        self._closed = threading.Event()
        self._recv_timeout: Optional[int] = None
        self._listener = _stdsocket.socket(_stdsocket.AF_INET, _stdsocket.SOCK_STREAM)
        self._listener.setsockopt(_stdsocket.SOL_SOCKET, _stdsocket.SO_REUSEADDR, 1)
        try:
            self._listener.bind((host, port))
            self._listener.listen(16)
        except OSError as exc:
            self._listener.close()
            raise TransportError(f"cannot listen on {label}://{host}:{port}: {exc}") from exc
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True,
                                               name=f"{label}-accept")
        self._accept_thread.start()

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                raw_conn, peer = self._listener.accept()
            except OSError:
                return
            try:
                conn = self._prepare(raw_conn, True)
            except (ssl.SSLError, OSError, TransportError) as exc:
                self._logger.warning("%s handshake failed from %s: %s",
                                     self._label, peer, exc)
                raw_conn.close()
                continue
            conn.sock.settimeout(_STEADY_TIMEOUT)
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._reader_loop, args=(conn,), daemon=True,
                             name=f"{self._label}-reader").start()

    def _reader_loop(self, conn: _FramedConn) -> None:
        try:
            while not self._closed.is_set():
                frame = conn.recv_frame()
                self._rq.put((conn, frame))
        except (ConnectionError, OSError, TransportError):
            pass
        finally:
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            conn.close()

    def recv(self) -> bytes:
        if self._closed.is_set():
            raise TransportClosed(f"recv on closed {self._label} listener")
        timeout = None if self._recv_timeout is None else self._recv_timeout / 1000.0
        try:
            conn, frame = self._rq.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout("recv timeout")
        self._last_conn = conn
        return frame

    @property
    def peer_count(self) -> int:
        """Live fan-in connections. The engine uses this to skip per-frame
        origin bookkeeping when only one peer exists (misrouting needs two).
        Taken under the conns lock: the probe runs once per burst, and a
        torn read during an accept would misclassify the whole burst."""
        with self._conns_lock:
            return len(self._conns)

    @property
    def last_origin(self):
        """Opaque token identifying the connection the most recent ``recv``'d
        frame arrived on. Capture it right after ``recv`` and pass it to
        ``send_to`` to route a reply to the requester — with multiple dialers
        fanned in, plain ``send`` can only guess (last-recv heuristic)."""
        return self._last_conn

    def send_to(self, origin, data: bytes, block: bool = True) -> None:
        """Send to the exact connection ``origin`` (a ``last_origin`` token).
        Raises TransportAgain if that peer has disconnected — a reply to a
        gone requester is undeliverable, not misroutable to someone else."""
        if self._closed.is_set():
            raise TransportClosed(f"send on closed {self._label} listener")
        with self._conns_lock:
            alive = origin in self._conns
        if not alive:
            raise TransportAgain("reply peer disconnected")
        try:
            origin.send_frame(data)
        except (ConnectionError, OSError) as exc:
            raise TransportError(str(exc)) from exc

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed.is_set():
            raise TransportClosed(f"send on closed {self._label} listener")
        conn = self._last_conn
        if conn is None:
            with self._conns_lock:
                conn = self._conns[0] if self._conns else None
        if conn is None:
            raise TransportAgain("no connected peer")
        try:
            conn.send_frame(data)
        except (ConnectionError, OSError) as exc:
            raise TransportError(str(exc)) from exc

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            for conn in self._conns:
                conn.close()
            self._conns.clear()


class FramedTcpDialer:
    """Client side of a framed-TCP transport with background redial (parity
    with nng dial(block=False) + reconnect, reference: engine.py:148,172-175).
    ``prepare(raw_sock, server_side)`` performs the ssl wrap / SP handshake
    and returns the framed connection."""

    def __init__(self, host: str, port: int, prepare,
                 logger: logging.Logger,
                 dial_timeout_ms: Optional[int], buffer_size: int = 100,
                 label: str = "framed+tcp"):
        self._host, self._port = host, port
        self._prepare = prepare
        self._label = label
        self._logger = logger
        self._dial_timeout = (dial_timeout_ms or 1000) / 1000.0
        self._conn: Optional[_FramedConn] = None
        self._conn_lock = threading.Lock()
        self._rq: "queue.Queue" = queue.Queue(maxsize=max(1, buffer_size))
        self._closed = threading.Event()
        self._recv_timeout: Optional[int] = None
        self._dial_thread = threading.Thread(target=self._dial_loop, daemon=True,
                                             name=f"{label}-dialer")
        self._dial_thread.start()

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms

    def _dial_loop(self) -> None:
        backoff = 0.05
        while not self._closed.is_set():
            with self._conn_lock:
                have = self._conn is not None
            if have:
                time.sleep(0.1)
                continue
            try:
                raw = _stdsocket.create_connection((self._host, self._port),
                                                   timeout=self._dial_timeout)
                # TCP self-connect guard: redialing a DOWN localhost listener,
                # the kernel can pick the target port as this socket's
                # ephemeral source port and the simultaneous-open handshake
                # connects the socket TO ITSELF. The SP/ws handshake then
                # "succeeds" against our own bytes, the dialer believes the
                # peer is back (black-holing traffic into an echo loop), and
                # the port stays captured so the real listener can never
                # rebind (EADDRINUSE). Found by tests/test_chaos.py.
                if raw.getsockname() == raw.getpeername():
                    raw.close()
                    raise TransportError("self-connect (peer is down)")
                conn = self._prepare(raw, False)
                # the connect timeout must NOT govern steady-state reads
                # (it made the reader tear down + redial on every ~1 s of
                # inbound idle); switch to the steady-state timeout, under
                # which recv treats a tick as idle and send stays bounded
                conn.sock.settimeout(_STEADY_TIMEOUT)
                with self._conn_lock:
                    self._conn = conn
                threading.Thread(target=self._reader_loop, args=(conn,), daemon=True,
                                 name=f"{self._label}-dial-reader").start()
                backoff = 0.05
            except (OSError, ssl.SSLError, TransportError):
                time.sleep(backoff)
                backoff = min(backoff * 2, 1.0)

    def _reader_loop(self, conn: _FramedConn) -> None:
        try:
            while not self._closed.is_set():
                self._rq.put(conn.recv_frame())
        except (ConnectionError, OSError, TransportError):
            pass
        finally:
            with self._conn_lock:
                if self._conn is conn:
                    self._conn = None
            conn.close()

    def recv(self) -> bytes:
        if self._closed.is_set():
            raise TransportClosed(f"recv on closed {self._label} dialer")
        timeout = None if self._recv_timeout is None else self._recv_timeout / 1000.0
        try:
            return self._rq.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout("recv timeout")

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed.is_set():
            raise TransportClosed(f"send on closed {self._label} dialer")
        with self._conn_lock:
            conn = self._conn
        if conn is None:
            raise TransportAgain("not connected")
        try:
            conn.send_frame(data)
        except (ConnectionError, OSError) as exc:
            with self._conn_lock:
                if self._conn is conn:
                    self._conn = None
            if self._closed.is_set():
                # close() raced this send and pulled the fd out from under
                # us (observed as a spurious "[Errno 9] Bad file descriptor"
                # under full-suite load) — that is a clean shutdown, not a
                # transport failure
                raise TransportClosed(
                    f"send on closed {self._label} dialer") from exc
            if getattr(exc, "errno", None) == errno.EBADF:
                # conn torn down concurrently (redial in flight): retryable
                raise TransportAgain("connection lost during send") from exc
            raise TransportError(str(exc)) from exc

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        with self._conn_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


def _host_port(rest: str, addr: str) -> tuple:
    host_port = rest.split("/", 1)[0]
    if ":" not in host_port:
        raise TransportError(f"address {addr!r} requires an explicit port")
    host, port_s = host_port.rsplit(":", 1)
    try:
        return host, int(port_s)
    except ValueError as exc:
        raise TransportError(f"bad port in {addr!r}") from exc


# Shared TLS plumbing for the two TLS-bearing schemes (tls+tcp and
# nng+tls+tcp). Contexts are fully configured — and their material errors
# raised — BEFORE the listener binds / the dialer connects, the ordering the
# reference pins (reference: tests/test_tls_transport.py:156-188). One home
# for TLS policy, so hardening (min version, ciphers, client certs) cannot
# drift between the schemes.

def _server_ssl_ctx(tls_config: Optional[object], addr: str,
                    scheme: str) -> ssl.SSLContext:
    if tls_config is None or not getattr(tls_config, "cert_key_file", None):
        raise TransportError(
            f"{scheme} listener {addr!r} requires tls_input.cert_key_file")
    ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    try:
        ssl_ctx.load_cert_chain(tls_config.cert_key_file)
    except (OSError, ssl.SSLError) as exc:
        raise TransportError(
            f"cannot load TLS cert/key {tls_config.cert_key_file}: {exc}") from exc
    return ssl_ctx


def _client_ssl_ctx(tls_config: Optional[object], addr: str, scheme: str,
                    host: str) -> tuple:
    if tls_config is None or not getattr(tls_config, "ca_file", None):
        raise TransportError(f"{scheme} output {addr!r} requires tls_output.ca_file")
    ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    try:
        ssl_ctx.load_verify_locations(tls_config.ca_file)
    except (OSError, ssl.SSLError) as exc:
        raise TransportError(f"cannot load TLS CA {tls_config.ca_file}: {exc}") from exc
    return ssl_ctx, getattr(tls_config, "server_name", None) or host


def _tls_server_wrap(ssl_ctx: ssl.SSLContext,
                     raw: _stdsocket.socket) -> ssl.SSLSocket:
    """Server-side TLS handshake with a bounded deadline. The accepted socket
    arrives blocking with NO timeout, and ``wrap_socket`` blocks in
    ``do_handshake`` waiting for a ClientHello — a peer that connects and
    sends nothing (port scanner, half-open connection) would wedge the single
    accept loop forever, a silent DoS on every later dialer. Same guard
    ``_sp_prepare`` applies to the SP header read; the accept loop sets the
    steady-state timeout right after ``prepare`` returns."""
    raw.settimeout(5.0)
    return ssl_ctx.wrap_socket(raw, server_side=True)


class TlsTcpSocketFactory:
    """tls+tcp:// factory: real ssl around the framework's 4-byte
    length-prefixed framing (for NNG-wire TLS interop see
    NngTlsTcpSocketFactory)."""

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "tls+tcp":
            raise TransportError(f"TlsTcpSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        ssl_ctx = _server_ssl_ctx(tls_config, addr, "tls+tcp")

        def prepare(raw: _stdsocket.socket, server_side: bool) -> _FramedConn:
            return _FramedConn(_tls_server_wrap(ssl_ctx, raw))

        return FramedTcpListener(host, port, prepare, logger, label="tls+tcp")

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "tls+tcp":
            raise TransportError(f"TlsTcpSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        ssl_ctx, server_name = _client_ssl_ctx(tls_config, addr, "tls+tcp", host)

        def prepare(raw: _stdsocket.socket, server_side: bool) -> _FramedConn:
            return _FramedConn(ssl_ctx.wrap_socket(raw, server_hostname=server_name))

        return FramedTcpDialer(host, port, prepare, logger, dial_timeout,
                               buffer_size, label="tls+tcp")


# ---------------------------------------------------------------------------
# nng+tcp backend: NNG/nanomsg SP wire protocol (Pair0 over TCP), so real
# NNG peers — e.g. a reference-style fluentd with fluent-plugin-nng
# (reference: container/Dockerfile_fluentd:5-9) — can dial this data plane
# without libnng on either linking path here.
#
# Wire format (nanomsg TCP mapping, which NNG's tcp transport speaks):
#   on connect, both peers send 8 bytes:  0x00 'S' 'P' 0x00  proto_be16  0x0000
#   (Pair0's protocol number is 16); a peer whose header disagrees is
#   rejected. After the handshake every message is
#   uint64_be length | payload.
# ---------------------------------------------------------------------------

SP_PAIR0_PROTO = 16
_SP_HDR = struct.Struct("!Q")  # u64 BE message length


def sp_handshake_bytes(proto: int = SP_PAIR0_PROTO) -> bytes:
    return b"\x00SP\x00" + struct.pack("!HH", proto, 0)


def _sp_prepare(raw: _stdsocket.socket, server_side: bool) -> _FramedConn:
    """Exchange and validate the SP protocol header (both directions —
    TCP is full duplex and NNG sends immediately on connect)."""
    raw.sendall(sp_handshake_bytes())
    saved = raw.gettimeout()
    raw.settimeout(5.0)  # a silent non-SP peer must not wedge the accept loop
    try:
        got = bytearray()
        while len(got) < 8:
            chunk = raw.recv(8 - len(got))
            if not chunk:
                raise TransportError("peer closed during SP handshake")
            got.extend(chunk)
    except OSError as exc:
        raise TransportError(f"SP handshake read failed: {exc}") from exc
    finally:
        raw.settimeout(saved)
    if bytes(got[:4]) != b"\x00SP\x00":
        raise TransportError(f"not an SP peer (header {bytes(got[:4])!r})")
    (proto, _reserved) = struct.unpack("!HH", bytes(got[4:]))
    if proto != SP_PAIR0_PROTO:
        raise TransportError(
            f"SP protocol mismatch: peer speaks {proto}, want Pair0 ({SP_PAIR0_PROTO})")
    return _FramedConn(raw, hdr=_SP_HDR)


# ---------------------------------------------------------------------------
# ws backend: RFC 6455 WebSocket, NNG dialect — one pipeline message per
# binary ws message, subprotocol "pair.sp.nanomsg.org" (what NNG's ws://
# transport speaks, reference: settings.py:31-37 lists ws among the NNG
# schemes). Implemented over the framed-TCP listener/dialer machinery with
# a ws "conn" in place of the length-prefix codec, so this build needs
# neither libzmq's compile-time ws option nor libnng.
# ---------------------------------------------------------------------------

_WS_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
_WS_SUBPROTO = "pair.sp.nanomsg.org"


def _ws_accept_key(key: str) -> str:
    import base64
    import hashlib

    return base64.b64encode(
        hashlib.sha1(key.encode() + _WS_GUID).digest()).decode()


def _ws_xor(data: bytes, mask: bytes) -> bytes:
    """Apply the RFC 6455 masking XOR. Data-plane hot path: every client→
    server byte passes through this, so it must NOT be a per-byte Python
    loop (1 interpreter op/byte ≈ seconds on a 64 MB frame). int.xor runs
    in C over the whole buffer."""
    n = len(data)
    if n == 0:
        return data
    full = mask * (n // 4) + mask[: n % 4]
    return (int.from_bytes(data, "little")
            ^ int.from_bytes(full, "little")).to_bytes(n, "little")


class _WsConn:
    """One established WebSocket connection: binary messages in/out, control
    frames handled inline (pong for ping, clean close). Duck-typed to the
    ``_FramedConn`` surface the framed listener/dialer use."""

    def __init__(self, sock: _stdsocket.socket, mask_outgoing: bool,
                 initial: bytes = b""):
        self.sock = sock
        self.send_lock = threading.Lock()
        self._mask = mask_outgoing            # RFC 6455: clients MUST mask
        # bytes the handshake read past the HTTP terminator (TCP may
        # coalesce the peer's first frame with its handshake): consumed
        # before any socket read, or the stream desyncs permanently
        self._buf = bytearray(initial)

    def send_frame(self, data: bytes) -> None:
        n = len(data)
        head = bytearray([0x82])              # FIN + binary opcode
        mask_bit = 0x80 if self._mask else 0
        if n < 126:
            head.append(mask_bit | n)
        elif n < 1 << 16:
            head.append(mask_bit | 126)
            head += struct.pack("!H", n)
        else:
            head.append(mask_bit | 127)
            head += struct.pack("!Q", n)
        if self._mask:
            mask = os.urandom(4)
            head += mask
            data = _ws_xor(data, mask)
        with self.send_lock:
            try:
                if isinstance(self.sock, ssl.SSLSocket):
                    self.sock.sendall(bytes(head) + data)
                else:
                    _send_with_progress(self.sock, bytes(head) + data)
            except _stdsocket.timeout as exc:
                self.close()  # partial frame on the wire → stream corrupt
                raise TransportError(
                    "ws send stalled (no progress for "
                    f"{_SEND_STALL_WINDOWS * _STEADY_TIMEOUT:.0f}s); "
                    "connection dropped") from exc

    def recv_frame(self) -> bytes:
        message = bytearray()
        while True:
            b0, b1 = self._recv_exact(2)
            fin, opcode = b0 & 0x80, b0 & 0x0F
            masked, length = b1 & 0x80, b1 & 0x7F
            if length == 126:
                (length,) = struct.unpack("!H", self._recv_exact(2))
            elif length == 127:
                (length,) = struct.unpack("!Q", self._recv_exact(8))
            if length > _MAX_FRAME:
                raise TransportError(f"oversized ws frame: {length} bytes")
            mask = self._recv_exact(4) if masked else None
            payload = self._recv_exact(length) if length else b""
            if mask:
                payload = _ws_xor(payload, mask)
            if opcode == 0x9:                 # ping → pong, keep reading
                self._send_control(0xA, payload)
                continue
            if opcode == 0xA:                 # unsolicited pong: ignore
                continue
            if opcode == 0x8:                 # close
                try:
                    self._send_control(0x8, payload[:2])
                except OSError:
                    pass
                raise ConnectionError("ws peer closed")
            if opcode in (0x1, 0x2, 0x0):     # text/binary/continuation
                # per-frame _MAX_FRAME alone does not bound the ASSEMBLED
                # message: a peer streaming FIN-less fragments could grow
                # it without limit (advisor r3 low — memory exhaustion)
                if len(message) + len(payload) > _MAX_FRAME:
                    raise TransportError(
                        f"oversized ws message: fragmented past {_MAX_FRAME} bytes")
                message += payload
                if fin:
                    return bytes(message)
                continue
            raise TransportError(f"unexpected ws opcode {opcode:#x}")

    def _send_control(self, opcode: int, payload: bytes) -> None:
        head = bytearray([0x80 | opcode])
        mask_bit = 0x80 if self._mask else 0
        head.append(mask_bit | len(payload))
        if self._mask:
            mask = os.urandom(4)
            head += mask
            payload = _ws_xor(payload, mask)
        with self.send_lock:
            try:
                self.sock.sendall(bytes(head) + payload)
            except _stdsocket.timeout as exc:
                self.close()
                raise TransportError("ws control send timed out") from exc

    def _recv_exact(self, n: int) -> bytes:
        buf = bytearray()
        if self._buf:
            take = self._buf[:n]
            del self._buf[:len(take)]
            buf.extend(take)
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except (_stdsocket.timeout, ssl.SSLWantReadError):
                continue  # idle tick, not an error: keep accumulating
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
        return bytes(buf)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def _ws_server_prepare(raw: _stdsocket.socket, path: str) -> _WsConn:
    """Accept an HTTP Upgrade request and complete the ws handshake."""
    saved = raw.gettimeout()
    raw.settimeout(5.0)
    try:
        request = b""
        while b"\r\n\r\n" not in request:
            chunk = raw.recv(4096)
            if not chunk:
                raise TransportError("peer closed during ws handshake")
            request += chunk
            if len(request) > 64 * 1024:
                raise TransportError("oversized ws handshake request")
        # split at the terminator FIRST: TCP may coalesce the client's first
        # frame with the request, and those bytes are frame data, not header
        head, _, rest = request.partition(b"\r\n\r\n")
        headers = {}
        for line in head.split(b"\r\n")[1:]:
            if b":" in line:
                k, v = line.split(b":", 1)
                # latin-1 never raises; a peer sending garbage header bytes
                # must be rejected below, not kill the accept thread
                headers[k.strip().lower().decode("latin-1")] = (
                    v.strip().decode("latin-1"))
        key = headers.get("sec-websocket-key")
        if not key or "websocket" not in headers.get("upgrade", "").lower():
            raise TransportError("not a websocket upgrade request")
        offered = [p.strip() for p in
                   headers.get("sec-websocket-protocol", "").split(",") if p.strip()]
        try:
            accept = _ws_accept_key(key)
        except (ValueError, UnicodeEncodeError) as exc:
            raise TransportError(f"bad Sec-WebSocket-Key: {exc}") from exc
        lines = [
            "HTTP/1.1 101 Switching Protocols",
            "Upgrade: websocket",
            "Connection: Upgrade",
            f"Sec-WebSocket-Accept: {accept}",
        ]
        if _WS_SUBPROTO in offered:           # echo NNG's pair0 subprotocol
            lines.append(f"Sec-WebSocket-Protocol: {_WS_SUBPROTO}")
        raw.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
    finally:
        raw.settimeout(saved)
    return _WsConn(raw, mask_outgoing=False, initial=rest)


def _ws_client_prepare(raw: _stdsocket.socket, host: str, port: int,
                       path: str) -> _WsConn:
    """Send the HTTP Upgrade request and validate the 101 response."""
    import base64

    key = base64.b64encode(os.urandom(16)).decode()
    request = (
        f"GET {path or '/'} HTTP/1.1\r\n"
        f"Host: {host}:{port}\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\n"
        "Sec-WebSocket-Version: 13\r\n"
        f"Sec-WebSocket-Protocol: {_WS_SUBPROTO}\r\n"
        "\r\n")
    saved = raw.gettimeout()
    raw.settimeout(5.0)
    try:
        raw.sendall(request.encode())
        response = b""
        while b"\r\n\r\n" not in response:
            chunk = raw.recv(4096)
            if not chunk:
                raise TransportError("peer closed during ws handshake")
            response += chunk
            if len(response) > 64 * 1024:
                raise TransportError("oversized ws handshake response")
    finally:
        raw.settimeout(saved)
    # bytes past the terminator are the server's first frame(s) — keep them
    head, _, rest = response.partition(b"\r\n\r\n")
    status = head.split(b"\r\n", 1)[0]
    if b"101" not in status:
        raise TransportError(f"ws upgrade refused: {status.decode(errors='replace')}")
    want = _ws_accept_key(key).encode()
    if want not in head:
        raise TransportError("ws handshake: bad Sec-WebSocket-Accept")
    return _WsConn(raw, mask_outgoing=True, initial=rest)


class WsSocketFactory:
    """ws:// factory: RFC 6455 over the framed listener/dialer machinery,
    independent of libzmq's compile-time ws option."""

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "ws":
            raise TransportError(f"WsSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        path = "/" + rest.split("/", 1)[1] if "/" in rest else "/"

        def prepare(raw: _stdsocket.socket, server_side: bool) -> _WsConn:
            return _ws_server_prepare(raw, path)

        return FramedTcpListener(host, port, prepare, logger, label="ws")

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "ws":
            raise TransportError(f"WsSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        path = "/" + rest.split("/", 1)[1] if "/" in rest else "/"

        def prepare(raw: _stdsocket.socket, server_side: bool) -> _WsConn:
            return _ws_client_prepare(raw, host, port, path)

        return FramedTcpDialer(host, port, prepare, logger, dial_timeout,
                               buffer_size, label="ws")


class NngTlsTcpSocketFactory:
    """nng+tls+tcp:// factory: SP Pair0 wire protocol INSIDE a real TLS
    stream — byte-compatible with NNG's ``tls+tcp`` transport (mbedTLS under
    libnng), which is how the reference's encrypted deployments speak on the
    wire (reference: src/service/features/engine_socket.py:60-71 server-side
    TLSConfig applied before listen; engine.py:165-170 client CA config).
    NNG's TLS transport completes the TLS handshake first and then runs the
    same 8-byte SP header exchange and u64-be length framing inside the
    session, so composing the ssl wrap with ``_sp_prepare`` reproduces the
    wire exactly. The plain-``tls+tcp://`` scheme here remains the
    framework-private 4-byte framing; THIS scheme is the one a genuine
    NNG/fluentd peer can dial encrypted.

    Ordering contract preserved: the TLS context is fully configured before
    the listener binds / the dialer connects (reference:
    tests/test_tls_transport.py:156-188)."""

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "nng+tls+tcp":
            raise TransportError(f"NngTlsTcpSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        ssl_ctx = _server_ssl_ctx(tls_config, addr, "nng+tls+tcp")

        def prepare(raw: _stdsocket.socket, server_side: bool) -> _FramedConn:
            # TLS first, then the SP header exchange inside the session —
            # NNG's layering (its tls+tcp transport wraps the SP stream)
            return _sp_prepare(_tls_server_wrap(ssl_ctx, raw), True)

        return FramedTcpListener(host, port, prepare, logger, label="nng+tls+tcp")

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "nng+tls+tcp":
            raise TransportError(f"NngTlsTcpSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        ssl_ctx, server_name = _client_ssl_ctx(tls_config, addr, "nng+tls+tcp", host)

        def prepare(raw: _stdsocket.socket, server_side: bool) -> _FramedConn:
            return _sp_prepare(
                ssl_ctx.wrap_socket(raw, server_hostname=server_name), False)

        return FramedTcpDialer(host, port, prepare, logger, dial_timeout,
                               buffer_size, label="nng+tls+tcp")


class NngTcpSocketFactory:
    """nng+tcp:// factory: SP Pair0 wire compatibility over plain TCP."""

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "nng+tcp":
            raise TransportError(f"NngTcpSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        return FramedTcpListener(host, port, _sp_prepare, logger, label="nng+tcp")

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "nng+tcp":
            raise TransportError(f"NngTcpSocketFactory cannot handle scheme {scheme!r}")
        host, port = _host_port(rest, addr)
        return FramedTcpDialer(host, port, _sp_prepare, logger, dial_timeout,
                               buffer_size, label="nng+tcp")


# ---------------------------------------------------------------------------
# in-process queue backend (test seam; also used by the process-free demo)
# ---------------------------------------------------------------------------

class _QueuePair:
    def __init__(self, maxsize: int = 1024):
        self.a_to_b: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self.b_to_a: "queue.Queue" = queue.Queue(maxsize=maxsize)


_inproc_registry: Dict[str, _QueuePair] = {}
_inproc_lock = threading.Lock()


class InprocQueueSocket:
    def __init__(self, addr: str, rq: "queue.Queue", sq: "queue.Queue"):
        self._addr = addr
        self._rq, self._sq = rq, sq
        self._closed = False
        self._recv_timeout: Optional[int] = None

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms

    def recv(self) -> bytes:
        if self._closed:
            raise TransportClosed(f"recv on closed {self._addr}")
        timeout = None if self._recv_timeout is None else self._recv_timeout / 1000.0
        try:
            return self._rq.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout("recv timeout")

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed:
            raise TransportClosed(f"send on closed {self._addr}")
        try:
            self._sq.put(data, block=block)
        except queue.Full:
            raise TransportAgain("send queue full")

    def close(self) -> None:
        self._closed = True


class MergedIngressSocket:
    """N listener shards draining into ONE engine loop (the multi-ingress
    regime of docs/benchmarks.md): each shard is an independent listening
    socket — its own fd, its own kernel buffer, its own sender — and the
    merge happens here at recv time, so a single dispatch loop (and a
    single device pipeline behind it) aggregates what N single-ingress
    pipes deliver.

    Fairness: recv rotates the starting shard; recv_many (exposed only when
    every shard supports it, i.e. the native transport) takes the first
    burst from whichever shard produces one, then drains the OTHER shards
    non-blockingly into the same batch — one GIL crossing per shard per
    call, bursts stay aggregated. Replies (send) go to the shard the last
    message arrived on; reply mode across shards keeps per-shard 1:1
    semantics."""

    def __init__(self, socks: List[EngineSocket]):
        if not socks:
            raise TransportError("MergedIngressSocket needs >= 1 shard")
        self._socks = list(socks)
        self._idx = 0
        self._last: EngineSocket = self._socks[0]
        self._recv_timeout: Optional[int] = None
        if all(callable(getattr(s, "recv_many", None)) for s in self._socks):
            self.recv_many = self._recv_many  # engine capability probe

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms
        # per-shard slice of the poll budget (recv walks all shards); an
        # unbounded merged recv still polls shards on a finite slice — a
        # blocking recv on shard 0 would starve the others
        share = 100 if ms is None else max(1, ms // len(self._socks))
        for s in self._socks:
            s.recv_timeout = share

    def recv(self) -> bytes:
        k = len(self._socks)
        # one full rotation covers the whole configured timeout (each shard
        # holds a 1/k slice); an infinite timeout loops rotations forever
        while True:
            for i in range(k):
                sock = self._socks[(self._idx + i) % k]
                try:
                    data = sock.recv()
                except TransportTimeout:
                    continue
                self._idx = (self._idx + i + 1) % k
                self._last = sock
                return data
            if self._recv_timeout is not None:
                raise TransportTimeout("recv timeout (all shards idle)")

    def _recv_many(self, max_n: int, first_timeout_ms: int) -> List[bytes]:
        k = len(self._socks)
        frames: List[bytes] = []
        share = max(1, first_timeout_ms // k)
        for i in range(k):
            sock = self._socks[(self._idx + i) % k]
            try:
                got = sock.recv_many(max_n - len(frames),
                                     share if not frames else 1)
            except TransportTimeout:
                # an idle shard must not discard what other shards already
                # delivered — empty is a per-shard non-event here
                continue
            if got:
                self._last = sock
                frames.extend(got)
            if len(frames) >= max_n:
                break
        self._idx = (self._idx + 1) % k
        return frames

    @property
    def peer_count(self) -> int:
        """Reply destinations across all shards: shards with their own
        peer accounting report it; a plain pair shard counts as one."""
        return sum(getattr(s, "peer_count", 1) for s in self._socks)

    @property
    def last_origin(self):
        """Reply token: (shard, shard-level origin). Exact per-message reply
        routing composes across the merge — the engine captures this per
        recv'd frame and ``send_to`` unwraps it, so micro-batches that mix
        shards still reply to the right shard (and, on fan-in listeners,
        the right connection)."""
        return (self._last, getattr(self._last, "last_origin", None))

    def send_to(self, origin, data: bytes, block: bool = True) -> None:
        sock, inner = origin
        if inner is not None and callable(getattr(sock, "send_to", None)):
            sock.send_to(inner, data, block=block)
        else:
            sock.send(data, block=block)

    def send(self, data: bytes, block: bool = True) -> None:
        self._last.send(data, block=block)

    def close(self) -> None:
        for s in self._socks:
            try:
                s.close()
            except TransportError:
                pass


def make_socket_factory(backend: str = "auto",
                        logger: Optional[logging.Logger] = None) -> EngineSocketFactory:
    """Resolve a transport backend name to a factory.

    ``native`` = the in-tree C++ transport (raises if it cannot be built),
    ``zmq`` = the Python backend, ``auto`` = native when available else zmq.
    Native and zmq frames are wire-compatible, so a pipeline can mix them.
    """
    if backend in ("auto", "native"):
        try:
            from .native_transport import NativePairSocketFactory

            return NativePairSocketFactory()
        except (ImportError, OSError) as exc:
            if backend == "native":
                raise TransportError(f"native transport unavailable: {exc}")
            if logger:
                logger.debug("native transport unavailable (%s); using zmq", exc)
    return ZmqPairSocketFactory()


class InprocQueueSocketFactory:
    """Queue-based factory for tests and single-process demos."""

    def __init__(self, maxsize: int = 1024):
        self._maxsize = maxsize

    def _pair(self, addr: str) -> _QueuePair:
        with _inproc_lock:
            pair = _inproc_registry.get(addr)
            if pair is None:
                pair = _QueuePair(self._maxsize)
                _inproc_registry[addr] = pair
            return pair

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        pair = self._pair(addr)
        return InprocQueueSocket(addr, rq=pair.a_to_b, sq=pair.b_to_a)

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        pair = self._pair(addr)
        return InprocQueueSocket(addr, rq=pair.b_to_a, sq=pair.a_to_b)
