"""Data-plane transport: pair-socket surface over multiple backends.

The reference's data plane is NNG Pair0 via pynng (reference:
src/service/features/engine_socket.py:35-78, engine.py:133-179). This build
has no libnng; the same observable surface — ``listen/dial/send/recv`` with
receive timeouts, non-blocking sends, background reconnect, drop-don't-block —
is provided over:

* **zmq DEALER** pairs for ``ipc:// tcp:// inproc://`` (libzmq does background
  reconnect and bounded buffering natively; DEALER-DEALER is bidirectional 1:1
  like Pair0),
* a pure-Python **length-prefixed TLS/TCP** transport for ``tls+tcp://``
  (real ssl: server cert/key, client CA + server-name verification — parity
  with the reference's mbedTLS modes, engine_socket.py:60-71, engine.py:165-170),
* an in-process queue transport for tests,
* an optional in-tree **C++ transport** (native/transport) loaded when built,
  with the same surface.

Exception taxonomy maps 1:1 onto pynng's (Timeout / TryAgain / NNGException →
TransportTimeout / TransportAgain / TransportError), because the engine's
retry/drop logic is written against it (reference: engine.py:216-218,290-299).

The factory protocol is the seam tests use to inject fakes — kept verbatim
(reference: engine_socket.py:23-32).
"""
from __future__ import annotations

import logging
import os
import queue
import socket as _stdsocket
import ssl
import struct
import threading
import time
from typing import Dict, List, Optional, Protocol, runtime_checkable

import zmq


class TransportError(Exception):
    """Base transport failure (maps to pynng.NNGException)."""


class TransportTimeout(TransportError):
    """recv timed out (maps to pynng.Timeout)."""


class TransportAgain(TransportError):
    """Non-blocking send would block (maps to pynng.TryAgain)."""


class TransportClosed(TransportError):
    """Operation on a closed socket."""


@runtime_checkable
class EngineSocket(Protocol):
    """Minimal socket surface the engine loop uses (reference: engine_socket.py:12-20)."""

    def recv(self) -> bytes: ...
    def send(self, data: bytes, block: bool = True) -> None: ...
    def close(self) -> None: ...
    @property
    def recv_timeout(self) -> Optional[int]: ...
    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None: ...


@runtime_checkable
class EngineSocketFactory(Protocol):
    """Factory seam (reference: engine_socket.py:23-32). ``create`` returns a
    listening socket bound to ``addr``; ``create_output`` returns a dialing
    socket connected (possibly in the background) to ``addr``."""

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket: ...

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket: ...


def _split_scheme(addr: str) -> tuple:
    if "://" not in addr:
        raise TransportError(f"address {addr!r} has no scheme")
    scheme, rest = addr.split("://", 1)
    return scheme, rest


# ---------------------------------------------------------------------------
# zmq backend
# ---------------------------------------------------------------------------

_shared_ctx: Optional[zmq.Context] = None
_ctx_lock = threading.Lock()


def _context() -> zmq.Context:
    # one process-wide context so inproc:// endpoints are visible everywhere
    global _shared_ctx
    with _ctx_lock:
        if _shared_ctx is None or _shared_ctx.closed:
            _shared_ctx = zmq.Context.instance()
        return _shared_ctx


class ZmqPairSocket:
    """DEALER socket with the pair surface. 1:1 bidirectional, background
    reconnect, bounded HWM buffering; ``send(block=False)`` raises
    TransportAgain when buffers are full (drop handling is the engine's job,
    reference: engine.py:286-296)."""

    def __init__(self, sock: zmq.Socket, addr: str, unlink_on_close: Optional[str] = None):
        self._sock = sock
        self._addr = addr
        self._closed = False
        self._recv_timeout: Optional[int] = None
        self._unlink_on_close = unlink_on_close
        self._lock = threading.Lock()

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms
        self._sock.setsockopt(zmq.RCVTIMEO, -1 if ms is None else int(ms))

    def recv(self) -> bytes:
        if self._closed:
            raise TransportClosed(f"recv on closed socket {self._addr}")
        try:
            return self._sock.recv()
        except zmq.Again as exc:
            raise TransportTimeout(str(exc) or "recv timeout") from exc
        except zmq.ZMQError as exc:
            if self._closed:
                raise TransportClosed(str(exc)) from exc
            raise TransportError(str(exc)) from exc

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed:
            raise TransportClosed(f"send on closed socket {self._addr}")
        try:
            self._sock.send(data, flags=0 if block else zmq.DONTWAIT)
        except zmq.Again as exc:
            raise TransportAgain(str(exc) or "send would block") from exc
        except zmq.ZMQError as exc:
            if self._closed:
                raise TransportClosed(str(exc)) from exc
            raise TransportError(str(exc)) from exc

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._sock.close(linger=0)
        finally:
            if self._unlink_on_close:
                try:
                    os.unlink(self._unlink_on_close)
                except OSError:
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ZmqPairSocketFactory:
    """Default factory (role of the reference's NngPairSocketFactory,
    engine_socket.py:35-78)."""

    SCHEMES = ("ipc", "tcp", "inproc", "ws")

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme == "tls+tcp":
            factory = TlsTcpSocketFactory()
            return factory.create(addr, logger, tls_config)
        if scheme not in self.SCHEMES:
            raise TransportError(f"unsupported scheme {scheme!r} in {addr!r}")
        unlink = None
        if scheme == "ipc":
            # unlink a stale ipc file before bind (reference: engine_socket.py:46-54)
            path = rest
            if os.path.exists(path):
                try:
                    os.unlink(path)
                    logger.debug("unlinked stale ipc file %s", path)
                except OSError as exc:
                    raise TransportError(f"cannot unlink stale ipc file {path}: {exc}") from exc
            unlink = path
        if scheme == "tcp":
            host_port = rest.split("/", 1)[0]
            if ":" not in host_port:
                raise TransportError(f"tcp address {addr!r} requires an explicit port")
        sock = _context().socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        try:
            sock.bind(addr)
        except zmq.ZMQError as exc:
            sock.close(linger=0)  # close on bind failure (reference: engine_socket.py:72-78)
            raise TransportError(f"cannot listen on {addr}: {exc}") from exc
        logger.debug("listening on %s", addr)
        return ZmqPairSocket(sock, addr, unlink_on_close=unlink)

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, _ = _split_scheme(addr)
        if scheme == "tls+tcp":
            factory = TlsTcpSocketFactory()
            return factory.create_output(addr, logger, tls_config, dial_timeout, buffer_size)
        if scheme not in self.SCHEMES:
            raise TransportError(f"unsupported scheme {scheme!r} in {addr!r}")
        sock = _context().socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        sock.setsockopt(zmq.SNDHWM, max(1, buffer_size))
        sock.setsockopt(zmq.RCVHWM, max(1, buffer_size))
        sock.setsockopt(zmq.RECONNECT_IVL, 100)
        # ZMQ_IMMEDIATE: queue only to live connections so non-blocking sends
        # to a dead peer raise Again instead of buffering forever — matches
        # the reference's drop accounting (engine.py:286-296)
        sock.setsockopt(zmq.IMMEDIATE, 1)
        try:
            sock.connect(addr)  # async connect, like nng dial(block=False)
        except zmq.ZMQError as exc:
            sock.close(linger=0)
            raise TransportError(f"cannot dial {addr}: {exc}") from exc
        logger.debug("dialing %s (background connect)", addr)
        return ZmqPairSocket(sock, addr)


# ---------------------------------------------------------------------------
# tls+tcp backend: length-prefixed frames over ssl-wrapped TCP
# ---------------------------------------------------------------------------

_FRAME_HDR = struct.Struct("!I")
_MAX_FRAME = 64 * 1024 * 1024


class _FramedConn:
    """One established TLS connection with 4-byte length framing."""

    def __init__(self, sock: _stdsocket.socket):
        self.sock = sock
        self.send_lock = threading.Lock()

    def send_frame(self, data: bytes) -> None:
        with self.send_lock:
            self.sock.sendall(_FRAME_HDR.pack(len(data)) + data)

    def recv_frame(self) -> bytes:
        hdr = self._recv_exact(_FRAME_HDR.size)
        (length,) = _FRAME_HDR.unpack(hdr)
        if length > _MAX_FRAME:
            raise TransportError(f"oversized frame: {length} bytes")
        return self._recv_exact(length)

    def _recv_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
        return bytes(buf)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class TlsTcpListener:
    """Server side of tls+tcp://. Accepts any number of dialers (fan-in, like
    many NNG dialers to one listener) and merges their frames into one recv
    queue. Replies go to the connection the last message arrived on."""

    def __init__(self, host: str, port: int, ssl_ctx: ssl.SSLContext,
                 logger: logging.Logger, buffer_size: int = 100):
        self._logger = logger
        self._ssl_ctx = ssl_ctx
        self._rq: "queue.Queue" = queue.Queue(maxsize=max(1, buffer_size))
        self._conns: List[_FramedConn] = []
        self._conns_lock = threading.Lock()
        self._last_conn: Optional[_FramedConn] = None
        self._closed = threading.Event()
        self._recv_timeout: Optional[int] = None
        self._listener = _stdsocket.socket(_stdsocket.AF_INET, _stdsocket.SOCK_STREAM)
        self._listener.setsockopt(_stdsocket.SOL_SOCKET, _stdsocket.SO_REUSEADDR, 1)
        try:
            self._listener.bind((host, port))
            self._listener.listen(16)
        except OSError as exc:
            self._listener.close()
            raise TransportError(f"cannot listen on tls+tcp://{host}:{port}: {exc}") from exc
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True,
                                               name="TlsAccept")
        self._accept_thread.start()

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                raw_conn, peer = self._listener.accept()
            except OSError:
                return
            try:
                tls_conn = self._ssl_ctx.wrap_socket(raw_conn, server_side=True)
            except (ssl.SSLError, OSError) as exc:
                self._logger.warning("TLS handshake failed from %s: %s", peer, exc)
                raw_conn.close()
                continue
            conn = _FramedConn(tls_conn)
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._reader_loop, args=(conn,), daemon=True,
                             name="TlsReader").start()

    def _reader_loop(self, conn: _FramedConn) -> None:
        try:
            while not self._closed.is_set():
                frame = conn.recv_frame()
                self._rq.put((conn, frame))
        except (ConnectionError, OSError, TransportError):
            pass
        finally:
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            conn.close()

    def recv(self) -> bytes:
        if self._closed.is_set():
            raise TransportClosed("recv on closed tls listener")
        timeout = None if self._recv_timeout is None else self._recv_timeout / 1000.0
        try:
            conn, frame = self._rq.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout("recv timeout")
        self._last_conn = conn
        return frame

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed.is_set():
            raise TransportClosed("send on closed tls listener")
        conn = self._last_conn
        if conn is None:
            with self._conns_lock:
                conn = self._conns[0] if self._conns else None
        if conn is None:
            raise TransportAgain("no connected peer")
        try:
            conn.send_frame(data)
        except (ConnectionError, OSError) as exc:
            raise TransportError(str(exc)) from exc

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            for conn in self._conns:
                conn.close()
            self._conns.clear()


class TlsTcpDialer:
    """Client side of tls+tcp:// with background redial (parity with nng
    dial(block=False) + reconnect, reference: engine.py:148,172-175)."""

    def __init__(self, host: str, port: int, ssl_ctx: ssl.SSLContext,
                 server_name: Optional[str], logger: logging.Logger,
                 dial_timeout_ms: Optional[int], buffer_size: int = 100):
        self._host, self._port = host, port
        self._ssl_ctx = ssl_ctx
        self._server_name = server_name or host
        self._logger = logger
        self._dial_timeout = (dial_timeout_ms or 1000) / 1000.0
        self._conn: Optional[_FramedConn] = None
        self._conn_lock = threading.Lock()
        self._rq: "queue.Queue" = queue.Queue(maxsize=max(1, buffer_size))
        self._closed = threading.Event()
        self._recv_timeout: Optional[int] = None
        self._dial_thread = threading.Thread(target=self._dial_loop, daemon=True,
                                             name="TlsDialer")
        self._dial_thread.start()

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms

    def _dial_loop(self) -> None:
        backoff = 0.05
        while not self._closed.is_set():
            with self._conn_lock:
                have = self._conn is not None
            if have:
                time.sleep(0.1)
                continue
            try:
                raw = _stdsocket.create_connection((self._host, self._port),
                                                   timeout=self._dial_timeout)
                tls = self._ssl_ctx.wrap_socket(raw, server_hostname=self._server_name)
                conn = _FramedConn(tls)
                with self._conn_lock:
                    self._conn = conn
                threading.Thread(target=self._reader_loop, args=(conn,), daemon=True,
                                 name="TlsDialReader").start()
                backoff = 0.05
            except (OSError, ssl.SSLError):
                time.sleep(backoff)
                backoff = min(backoff * 2, 1.0)

    def _reader_loop(self, conn: _FramedConn) -> None:
        try:
            while not self._closed.is_set():
                self._rq.put(conn.recv_frame())
        except (ConnectionError, OSError, TransportError):
            pass
        finally:
            with self._conn_lock:
                if self._conn is conn:
                    self._conn = None
            conn.close()

    def recv(self) -> bytes:
        if self._closed.is_set():
            raise TransportClosed("recv on closed tls dialer")
        timeout = None if self._recv_timeout is None else self._recv_timeout / 1000.0
        try:
            return self._rq.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout("recv timeout")

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed.is_set():
            raise TransportClosed("send on closed tls dialer")
        with self._conn_lock:
            conn = self._conn
        if conn is None:
            raise TransportAgain("not connected")
        try:
            conn.send_frame(data)
        except (ConnectionError, OSError) as exc:
            with self._conn_lock:
                if self._conn is conn:
                    self._conn = None
            raise TransportError(str(exc)) from exc

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        with self._conn_lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


def _host_port(rest: str, addr: str) -> tuple:
    host_port = rest.split("/", 1)[0]
    if ":" not in host_port:
        raise TransportError(f"address {addr!r} requires an explicit port")
    host, port_s = host_port.rsplit(":", 1)
    try:
        return host, int(port_s)
    except ValueError as exc:
        raise TransportError(f"bad port in {addr!r}") from exc


class TlsTcpSocketFactory:
    """tls+tcp:// factory. The TLS context is fully configured *before* the
    listener binds / the dialer connects — the ordering the reference pins
    (reference: tests/test_tls_transport.py:156-188)."""

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "tls+tcp":
            raise TransportError(f"TlsTcpSocketFactory cannot handle scheme {scheme!r}")
        if tls_config is None or not getattr(tls_config, "cert_key_file", None):
            raise TransportError(f"tls+tcp listener {addr!r} requires tls_input.cert_key_file")
        host, port = _host_port(rest, addr)
        ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        try:
            ssl_ctx.load_cert_chain(tls_config.cert_key_file)
        except (OSError, ssl.SSLError) as exc:
            raise TransportError(f"cannot load TLS cert/key {tls_config.cert_key_file}: {exc}") from exc
        return TlsTcpListener(host, port, ssl_ctx, logger)

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        logger = logger or logging.getLogger(__name__)
        scheme, rest = _split_scheme(addr)
        if scheme != "tls+tcp":
            raise TransportError(f"TlsTcpSocketFactory cannot handle scheme {scheme!r}")
        if tls_config is None or not getattr(tls_config, "ca_file", None):
            raise TransportError(f"tls+tcp output {addr!r} requires tls_output.ca_file")
        host, port = _host_port(rest, addr)
        ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        try:
            ssl_ctx.load_verify_locations(tls_config.ca_file)
        except (OSError, ssl.SSLError) as exc:
            raise TransportError(f"cannot load TLS CA {tls_config.ca_file}: {exc}") from exc
        server_name = getattr(tls_config, "server_name", None)
        return TlsTcpDialer(host, port, ssl_ctx, server_name, logger, dial_timeout,
                            buffer_size)


# ---------------------------------------------------------------------------
# in-process queue backend (test seam; also used by the process-free demo)
# ---------------------------------------------------------------------------

class _QueuePair:
    def __init__(self, maxsize: int = 1024):
        self.a_to_b: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self.b_to_a: "queue.Queue" = queue.Queue(maxsize=maxsize)


_inproc_registry: Dict[str, _QueuePair] = {}
_inproc_lock = threading.Lock()


class InprocQueueSocket:
    def __init__(self, addr: str, rq: "queue.Queue", sq: "queue.Queue"):
        self._addr = addr
        self._rq, self._sq = rq, sq
        self._closed = False
        self._recv_timeout: Optional[int] = None

    @property
    def recv_timeout(self) -> Optional[int]:
        return self._recv_timeout

    @recv_timeout.setter
    def recv_timeout(self, ms: Optional[int]) -> None:
        self._recv_timeout = ms

    def recv(self) -> bytes:
        if self._closed:
            raise TransportClosed(f"recv on closed {self._addr}")
        timeout = None if self._recv_timeout is None else self._recv_timeout / 1000.0
        try:
            return self._rq.get(timeout=timeout)
        except queue.Empty:
            raise TransportTimeout("recv timeout")

    def send(self, data: bytes, block: bool = True) -> None:
        if self._closed:
            raise TransportClosed(f"send on closed {self._addr}")
        try:
            self._sq.put(data, block=block)
        except queue.Full:
            raise TransportAgain("send queue full")

    def close(self) -> None:
        self._closed = True


def make_socket_factory(backend: str = "auto",
                        logger: Optional[logging.Logger] = None) -> EngineSocketFactory:
    """Resolve a transport backend name to a factory.

    ``native`` = the in-tree C++ transport (raises if it cannot be built),
    ``zmq`` = the Python backend, ``auto`` = native when available else zmq.
    Native and zmq frames are wire-compatible, so a pipeline can mix them.
    """
    if backend in ("auto", "native"):
        try:
            from .native_transport import NativePairSocketFactory

            return NativePairSocketFactory()
        except (ImportError, OSError) as exc:
            if backend == "native":
                raise TransportError(f"native transport unavailable: {exc}")
            if logger:
                logger.debug("native transport unavailable (%s); using zmq", exc)
    return ZmqPairSocketFactory()


class InprocQueueSocketFactory:
    """Queue-based factory for tests and single-process demos."""

    def __init__(self, maxsize: int = 1024):
        self._maxsize = maxsize

    def _pair(self, addr: str) -> _QueuePair:
        with _inproc_lock:
            pair = _inproc_registry.get(addr)
            if pair is None:
                pair = _QueuePair(self._maxsize)
                _inproc_registry[addr] = pair
            return pair

    def create(self, addr: str, logger: Optional[logging.Logger] = None,
               tls_config: Optional[object] = None) -> EngineSocket:
        pair = self._pair(addr)
        return InprocQueueSocket(addr, rq=pair.a_to_b, sq=pair.b_to_a)

    def create_output(self, addr: str, logger: Optional[logging.Logger] = None,
                      tls_config: Optional[object] = None,
                      dial_timeout: Optional[int] = None,
                      buffer_size: int = 100) -> EngineSocket:
        pair = self._pair(addr)
        return InprocQueueSocket(addr, rq=pair.b_to_a, sq=pair.a_to_b)
