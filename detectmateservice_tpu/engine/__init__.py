from .engine import Engine, EngineException, Processor, BatchProcessor
from .socket import (
    EngineSocket,
    EngineSocketFactory,
    TransportAgain,
    TransportClosed,
    TransportError,
    TransportTimeout,
    ZmqPairSocketFactory,
    NngTcpSocketFactory,
    NngTlsTcpSocketFactory,
    InprocQueueSocketFactory,
    make_socket_factory,
)

__all__ = [
    "Engine",
    "EngineException",
    "Processor",
    "BatchProcessor",
    "EngineSocket",
    "EngineSocketFactory",
    "TransportAgain",
    "TransportClosed",
    "TransportError",
    "TransportTimeout",
    "ZmqPairSocketFactory",
    "NngTcpSocketFactory",
    "NngTlsTcpSocketFactory",
    "InprocQueueSocketFactory",
    "make_socket_factory",
]
