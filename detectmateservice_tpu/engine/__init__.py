from .engine import Engine, EngineException, Processor, BatchProcessor
from .framing import Hop, TraceContext
from .tracing import FlightRecorder
from .socket import (
    EngineSocket,
    EngineSocketFactory,
    TransportAgain,
    TransportClosed,
    TransportError,
    TransportTimeout,
    ZmqPairSocketFactory,
    NngTcpSocketFactory,
    NngTlsTcpSocketFactory,
    InprocQueueSocketFactory,
    make_socket_factory,
)

__all__ = [
    "Engine",
    "EngineException",
    "Processor",
    "BatchProcessor",
    "Hop",
    "TraceContext",
    "FlightRecorder",
    "EngineSocket",
    "EngineSocketFactory",
    "TransportAgain",
    "TransportClosed",
    "TransportError",
    "TransportTimeout",
    "ZmqPairSocketFactory",
    "NngTcpSocketFactory",
    "NngTlsTcpSocketFactory",
    "InprocQueueSocketFactory",
    "make_socket_factory",
]
