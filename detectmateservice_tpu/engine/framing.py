"""Batch frames: many serialized messages in one wire frame.

The per-message socket cost (zmq enqueue + GIL crossing + syscall amortization)
caps a single Python sender at ~80k sends/s (measured via
scripts/bench_service.py) — far below what the TPU detector sustains
(445k+ lines/s). Packing K messages per frame amortizes that cost K-fold on
both ends; this is SURVEY.md §7 hard part #3 ("batch *frames* before
crossing into Python") applied to the whole service mesh, not just ingest.

Wire format (version 1):

    0xD7 'D' 'M' 0x01 | varint n | n × (varint len | len bytes)

The first byte 0xD7 decodes as protobuf field 26 / wire type 7 — wire type 7
does not exist, so no valid protobuf message (all pipeline schemas are
protobuf) can begin with it: receivers can safely auto-detect batch frames
and stay wire-compatible with single-message peers. Senders only emit batch
frames when ``engine_frame_batch > 1`` is configured, so interop with
reference-style peers is the default.

Wire format (version 2, traced frames — opt-in via ``engine_trace``):

    0xD7 'D' 'M' 0x02 | varint trace_len | trace block | payload

``payload`` is a complete v1 wire unit — either a v1 batch frame or a plain
single message — so downgrading a v2 frame for a v1-only peer is a slice:
everything after the trace block, byte-identical to what an untraced sender
would have emitted. The trace block:

    trace_id (8 bytes) | varint ingest_ns | varint n_hops
    | n_hops × (varint name_len | name utf-8 | varint recv_ns | varint send_ns)

Timestamps are ``time.time_ns()`` epoch nanoseconds — comparable across the
processes of one pipeline host (and across NTP-synced hosts to clock-sync
precision). The length prefix exists for damage containment: a garbled trace
block is skipped by its declared length and the payload messages survive
(the error is counted); only a declared length running past the frame end
loses the frame.
"""
from __future__ import annotations

import itertools
import json
import os
from typing import List, NamedTuple, Optional, Tuple

MAGIC = b"\xd7DM\x01"
MAGIC_V2 = b"\xd7DM\x02"
# Zero-copy shm reference frame (v2 format family, PR 7): instead of payload
# bytes, the frame carries a (segment name, slot, gen, offset, length)
# reference into a shared-memory segment owned by the SENDING engine
# (engine/shm.py). The referenced payload is a complete v1/v2 wire unit —
# byte-identical to what a copy-mode sender would have put on the wire — so
# resolving a shm frame and receiving a plain frame are indistinguishable
# downstream. Senders only emit these on colocated links (ipc/inproc peers
# with ``zero_copy_framing`` enabled) and copy-downgrade everywhere else.
MAGIC_SHM = b"\xd7DM\x03"
# Tenant-attributed frame (v2 format family, dmshed): the OUTERMOST wrapper —
# a tenant id rides in front of whatever the sender emits (a v2 traced frame,
# a v1 batch frame, or a plain single message), so ingress admission control
# can attribute and shed a frame from its first bytes without touching the
# trace block or payload. Stripping it for a tenant-unaware peer is a slice
# (everything after the block), the same clean-downgrade contract v2 has:
#
#     0xD7 'D' 'M' 0x04 | varint id_len | tenant id utf-8 | payload
MAGIC_TEN = b"\xd7DM\x04"
# Span frame (dmtel): a batch of completed hop spans shipped from an engine's
# telemetry sender thread to the collector (telemetry/collector.py). Spans are
# operator-facing telemetry, not pipeline payload — they never mix with data
# frames on a data link and the collector is their only receiver — so the body
# is JSON (a list of span dicts, docs/transport.md "span wire format") rather
# than a packed binary block: the encode cost is paid on the sender THREAD,
# off the hot loop, and debuggability of the telemetry channel itself wins:
#
#     0xD7 'D' 'M' 0x05 | varint body_len | span JSON utf-8
MAGIC_SPAN = b"\xd7DM\x05"


class FramingError(ValueError):
    """A frame carried the batch magic but its body was malformed."""


def _put_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _get_varint(data: bytes, pos: int) -> tuple:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise FramingError("truncated varint in batch frame")
        byte = data[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise FramingError("varint overflow in batch frame")


def pack_batch(messages: List[bytes]) -> bytes:
    """Pack serialized messages into one batch frame."""
    out = bytearray(MAGIC)
    _put_varint(out, len(messages))
    for msg in messages:
        _put_varint(out, len(msg))
        out += msg
    return bytes(out)


def frame_msg_count(data: bytes) -> int:
    """Cheap message-count estimate for burst sizing: the header varint of a
    batch frame, 1 for a single message, 0 for an empty/garbled header.
    v2 (traced) frames are counted by their payload. Does NOT validate the
    body — use ``unpack_batch`` (or the native kernel's count pass) for
    that."""
    if not data:
        return 0
    if data.startswith(MAGIC_TEN):
        try:
            id_len, pos = _get_varint(data, len(MAGIC_TEN))
        except FramingError:
            return 0
        start = pos + id_len
        if start > len(data):
            return 0
        return frame_msg_count(data[start:])
    if data.startswith(MAGIC_V2):
        try:
            trace_len, pos = _get_varint(data, len(MAGIC_V2))
        except FramingError:
            return 0
        start = pos + trace_len
        if start > len(data):
            return 0
        return frame_msg_count(data[start:])
    if not data.startswith(MAGIC):
        return 1
    try:
        count, _ = _get_varint(data, len(MAGIC))
    except FramingError:
        return 0
    return count


# -- shm reference frames (zero-copy framing) --------------------------------


class ShmRef(NamedTuple):
    """A shared-memory payload reference: which segment, which slot (and its
    publish generation, so a stale ref is detected instead of reading a
    recycled slot), and the payload's byte range within the segment."""

    name: str        # segment path, or "@inproc:<pid>:<id>" for the
                     # in-process object registry (true zero-copy)
    slot: int
    gen: int
    offset: int
    length: int


def pack_shm_ref(ref: ShmRef) -> bytes:
    """ShmRef → wire frame:
    ``MAGIC_SHM | varint name_len | name | varint slot | varint gen
    | varint offset | varint length``."""
    out = bytearray(MAGIC_SHM)
    name = ref.name.encode("utf-8")
    _put_varint(out, len(name))
    out += name
    _put_varint(out, ref.slot)
    _put_varint(out, ref.gen)
    _put_varint(out, ref.offset)
    _put_varint(out, ref.length)
    return bytes(out)


def unpack_shm_ref(data: bytes) -> ShmRef:
    """Wire frame → ShmRef; raises FramingError on a garbled reference (the
    payload itself is unreachable then — unlike a garbled v2 trace block,
    there is nothing to salvage)."""
    if not data.startswith(MAGIC_SHM):
        raise FramingError("not a shm reference frame")
    name_len, pos = _get_varint(data, len(MAGIC_SHM))
    end = pos + name_len
    if end > len(data):
        raise FramingError("truncated segment name in shm reference")
    try:
        name = data[pos:end].decode("utf-8")
    except UnicodeDecodeError as exc:
        raise FramingError(f"non-UTF-8 segment name in shm reference: {exc}")
    slot, pos = _get_varint(data, end)
    gen, pos = _get_varint(data, pos)
    offset, pos = _get_varint(data, pos)
    length, pos = _get_varint(data, pos)
    if pos != len(data):
        raise FramingError("trailing bytes after shm reference")
    return ShmRef(name, slot, gen, offset, length)


# -- trace context (v2 frames) ----------------------------------------------

# trace-id stream: one getrandom() at import, then a counter (GIL-atomic
# ``next``) — collision-safe within a process by construction, across
# processes by the 64-bit random base
_TRACE_ID_BASE = int.from_bytes(os.urandom(8), "big")
_TRACE_ID_SEQ = itertools.count()


class Hop(NamedTuple):
    """One stage transit record: when the frame entered and left the stage."""

    stage: str
    recv_ns: int
    send_ns: int


class TraceContext:
    """Per-frame trace state threaded through the wire (v2 trace block)."""

    __slots__ = ("trace_id", "ingest_ns", "hops")

    def __init__(self, trace_id: int, ingest_ns: int,
                 hops: Optional[List[Hop]] = None) -> None:
        self.trace_id = trace_id
        self.ingest_ns = ingest_ns
        self.hops: List[Hop] = hops if hops is not None else []

    @classmethod
    def new(cls, ingest_ns: int) -> "TraceContext":
        # random 64-bit base + per-process counter, not os.urandom per
        # trace: id generation sits on the per-frame ingest path and a
        # getrandom(2) syscall there costs more than the whole hop stamp
        return cls((_TRACE_ID_BASE + next(_TRACE_ID_SEQ))
                   & 0xFFFFFFFFFFFFFFFF, ingest_ns)

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.ingest_ns == other.ingest_ns
                and self.hops == other.hops)

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id:#018x}, ingest={self.ingest_ns},"
                f" hops={self.hops!r})")


def pack_trace_block(ctx: TraceContext) -> bytes:
    out = bytearray(ctx.trace_id.to_bytes(8, "big"))
    _put_varint(out, ctx.ingest_ns)
    _put_varint(out, len(ctx.hops))
    for hop in ctx.hops:
        name = hop.stage.encode("utf-8")
        _put_varint(out, len(name))
        out += name
        _put_varint(out, hop.recv_ns)
        _put_varint(out, hop.send_ns)
    return bytes(out)


def parse_trace_block(block: bytes) -> TraceContext:
    """Trace block bytes → TraceContext; raises FramingError on damage."""
    if len(block) < 8:
        raise FramingError("trace block shorter than the 8-byte trace id")
    trace_id = int.from_bytes(block[:8], "big")
    ingest_ns, pos = _get_varint(block, 8)
    n_hops, pos = _get_varint(block, pos)
    hops: List[Hop] = []
    for _ in range(n_hops):
        name_len, pos = _get_varint(block, pos)
        end = pos + name_len
        if end > len(block):
            raise FramingError("truncated hop name in trace block")
        try:
            stage = block[pos:end].decode("utf-8")
        except UnicodeDecodeError as exc:
            raise FramingError(f"non-UTF-8 hop name in trace block: {exc}")
        pos = end
        recv_ns, pos = _get_varint(block, pos)
        send_ns, pos = _get_varint(block, pos)
        hops.append(Hop(stage, recv_ns, send_ns))
    if pos != len(block):
        raise FramingError("trailing bytes after trace block hops")
    return TraceContext(trace_id, ingest_ns, hops)


def wrap_trace(payload: bytes, ctx: TraceContext) -> bytes:
    """Payload (a v1 batch frame or a plain single message) → v2 frame."""
    block = pack_trace_block(ctx)
    out = bytearray(MAGIC_V2)
    _put_varint(out, len(block))
    out += block
    out += payload
    return bytes(out)


def unwrap_trace(data: bytes) -> Tuple[bytes, Optional[TraceContext], bool]:
    """v2 frame → ``(payload, trace, trace_damaged)``.

    Non-v2 input passes through as ``(data, None, False)``. A v2 frame whose
    trace block is internally garbled still yields its payload — the block is
    skipped by its declared length and ``trace_damaged`` is True so the
    caller can count a framing error without dropping the payload messages.
    Only a declared trace length running past the frame end (no payload can
    exist) raises FramingError."""
    if not data.startswith(MAGIC_V2):
        return data, None, False
    trace_len, pos = _get_varint(data, len(MAGIC_V2))
    start = pos + trace_len
    if start > len(data):
        raise FramingError("trace block length exceeds frame size")
    try:
        ctx = parse_trace_block(data[pos:start])
    except FramingError:
        return data[start:], None, True
    return data[start:], ctx, False


def peek_trace_id(data: bytes) -> Optional[int]:
    """The trace id of a v2 frame WITHOUT parsing the hop records — the
    router's sticky_trace policy runs this per dispatched frame, so it reads
    exactly one varint and eight bytes (plus one varint skip when a tenant
    block rides in front). None for non-v2 frames and for frames whose
    declared trace block cannot hold an id."""
    if data.startswith(MAGIC_TEN):
        try:
            id_len, pos = _get_varint(data, len(MAGIC_TEN))
        except FramingError:
            return None
        start = pos + id_len
        if start > len(data):
            return None
        data = data[start:]
    if not data.startswith(MAGIC_V2):
        return None
    try:
        trace_len, pos = _get_varint(data, len(MAGIC_V2))
    except FramingError:
        return None
    if trace_len < 8 or pos + 8 > len(data):
        return None
    return int.from_bytes(data[pos:pos + 8], "big")


# -- tenant attribution (dmshed frames) --------------------------------------


def wrap_tenant(payload: bytes, tenant: str) -> bytes:
    """Payload (any complete wire unit: v2 traced frame, v1 batch frame, or
    a plain single message) → tenant-attributed frame. The tenant block is
    always the OUTERMOST wrapper; senders stamp it last."""
    out = bytearray(MAGIC_TEN)
    name = tenant.encode("utf-8")
    _put_varint(out, len(name))
    out += name
    out += payload
    return bytes(out)


def unwrap_tenant(data: bytes) -> Tuple[bytes, Optional[str], bool]:
    """Tenant frame → ``(payload, tenant, tenant_damaged)``.

    Non-tenant input passes through as ``(data, None, False)``. A tenant
    block whose id bytes are not valid UTF-8 still yields its payload — the
    block is skipped by its declared length and ``tenant_damaged`` is True
    so the caller can count the damage (and admit under the default quota)
    without dropping the payload messages. Only a declared id length
    running past the frame end (no payload can exist) raises
    FramingError."""
    if not data.startswith(MAGIC_TEN):
        return data, None, False
    id_len, pos = _get_varint(data, len(MAGIC_TEN))
    start = pos + id_len
    if start > len(data):
        raise FramingError("tenant id length exceeds frame size")
    try:
        tenant = data[pos:start].decode("utf-8")
    except UnicodeDecodeError:
        return data[start:], None, True
    return data[start:], tenant, False


def peek_tenant_id(data: bytes) -> Optional[str]:
    """The tenant id of a tenant-attributed frame WITHOUT touching the
    payload — admission control runs this per ingress frame, so it reads
    exactly one varint and the id bytes. None for frames with no tenant
    block or an undecodable id."""
    if not data.startswith(MAGIC_TEN):
        return None
    try:
        id_len, pos = _get_varint(data, len(MAGIC_TEN))
    except FramingError:
        return None
    start = pos + id_len
    if start > len(data):
        return None
    try:
        return data[pos:start].decode("utf-8")
    except UnicodeDecodeError:
        return None


# -- span frames (dmtel telemetry channel) -----------------------------------


def pack_spans(spans: List[dict]) -> bytes:
    """Span dicts → one span frame for the telemetry channel. Runs on the
    exporter's sender thread (telemetry/spans.py), never the hot loop."""
    body = json.dumps(spans, separators=(",", ":")).encode("utf-8")
    out = bytearray(MAGIC_SPAN)
    _put_varint(out, len(body))
    out += body
    return bytes(out)


def unpack_spans(data: bytes) -> Optional[List[dict]]:
    """Span frame → span dicts; None when ``data`` is not a span frame.
    Raises FramingError on a garbled body — unlike a damaged v2 trace block
    there is no payload to salvage behind it, the frame IS the telemetry."""
    if not data.startswith(MAGIC_SPAN):
        return None
    body_len, pos = _get_varint(data, len(MAGIC_SPAN))
    end = pos + body_len
    if end > len(data):
        raise FramingError("span body length exceeds frame size")
    if end != len(data):
        raise FramingError("trailing bytes after span frame body")
    try:
        spans = json.loads(data[pos:end].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise FramingError(f"undecodable span frame body: {exc}")
    if not isinstance(spans, list):
        raise FramingError("span frame body is not a JSON list")
    return spans


def unpack_batch(data: bytes) -> Optional[List[bytes]]:
    """Batch frame → messages; None when ``data`` is a plain single message
    (no magic). Raises FramingError on a corrupt batch body."""
    if not data.startswith(MAGIC):
        return None
    count, pos = _get_varint(data, len(MAGIC))
    messages: List[bytes] = []
    for _ in range(count):
        length, pos = _get_varint(data, pos)
        end = pos + length
        if end > len(data):
            raise FramingError("truncated message in batch frame")
        messages.append(data[pos:end])
        pos = end
    if pos != len(data):
        raise FramingError("trailing bytes after batch frame body")
    return messages
