"""Batch frames: many serialized messages in one wire frame.

The per-message socket cost (zmq enqueue + GIL crossing + syscall amortization)
caps a single Python sender at ~80k sends/s (measured via
scripts/bench_service.py) — far below what the TPU detector sustains
(445k+ lines/s). Packing K messages per frame amortizes that cost K-fold on
both ends; this is SURVEY.md §7 hard part #3 ("batch *frames* before
crossing into Python") applied to the whole service mesh, not just ingest.

Wire format (version 1):

    0xD7 'D' 'M' 0x01 | varint n | n × (varint len | len bytes)

The first byte 0xD7 decodes as protobuf field 26 / wire type 7 — wire type 7
does not exist, so no valid protobuf message (all pipeline schemas are
protobuf) can begin with it: receivers can safely auto-detect batch frames
and stay wire-compatible with single-message peers. Senders only emit batch
frames when ``engine_frame_batch > 1`` is configured, so interop with
reference-style peers is the default.
"""
from __future__ import annotations

from typing import List, Optional

MAGIC = b"\xd7DM\x01"


class FramingError(ValueError):
    """A frame carried the batch magic but its body was malformed."""


def _put_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _get_varint(data: bytes, pos: int) -> tuple:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise FramingError("truncated varint in batch frame")
        byte = data[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise FramingError("varint overflow in batch frame")


def pack_batch(messages: List[bytes]) -> bytes:
    """Pack serialized messages into one batch frame."""
    out = bytearray(MAGIC)
    _put_varint(out, len(messages))
    for msg in messages:
        _put_varint(out, len(msg))
        out += msg
    return bytes(out)


def frame_msg_count(data: bytes) -> int:
    """Cheap message-count estimate for burst sizing: the header varint of a
    batch frame, 1 for a single message, 0 for an empty/garbled header. Does
    NOT validate the body — use ``unpack_batch`` (or the native kernel's
    count pass) for that."""
    if not data:
        return 0
    if not data.startswith(MAGIC):
        return 1
    try:
        count, _ = _get_varint(data, len(MAGIC))
    except FramingError:
        return 0
    return count


def unpack_batch(data: bytes) -> Optional[List[bytes]]:
    """Batch frame → messages; None when ``data`` is a plain single message
    (no magic). Raises FramingError on a corrupt batch body."""
    if not data.startswith(MAGIC):
        return None
    count, pos = _get_varint(data, len(MAGIC))
    messages: List[bytes] = []
    for _ in range(count):
        length, pos = _get_varint(data, pos)
        end = pos + length
        if end > len(data):
            raise FramingError("truncated message in batch frame")
        messages.append(data[pos:end])
        pos = end
    if pos != len(data):
        raise FramingError("trailing bytes after batch frame body")
    return messages
