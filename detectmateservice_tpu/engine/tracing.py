"""Pipeline flight recorder: bounded in-memory store of completed traces.

The terminal stage of a traced pipeline (the engine with no forwarding
outputs — reply mode or an output component) finalizes each frame's
TraceContext and hands it here with its end-to-end latency. The recorder
keeps two bounded views:

* the N **slowest** traces seen since start/reset (a min-heap on e2e), so
  the tail that matters for debugging is never evicted by volume, and
* a **sampled** ring of every Kth completed trace, so the recorder also
  shows what *normal* looks like.

``GET /admin/trace`` (web/server.py) serves ``snapshot()`` as JSON and
``chrome_events()`` as a Chrome trace-event document loadable in Perfetto /
chrome://tracing — each hop becomes a complete ("X") slice on its stage's
track, and inter-stage wire+queue time becomes a "transit" slice, so the
pipeline bottleneck is visible as the widest box.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from .framing import TraceContext

# Per-THREAD frame context (dmtel log↔trace correlation): the engine loop
# stores the trace id (an int, hex-formatted only at log time) and tenant of
# the frame it is currently expanding/dispatching, and clears both at burst
# finalize. JsonLogFormatter (health.py) reads it, so every log record — a
# quarantine, a processor exception, a shed decision — emitted while a frame
# is in flight carries ``trace_id``/``tenant_bucket`` and joins the spans the
# collector assembled for the same frame. A threading.local, not a global:
# records logged from admin/sender threads must never inherit another
# thread's frame. Plain attribute stores, GIL-atomic — no lock on the hot
# path.
FRAME_CONTEXT = threading.local()


def current_trace_id() -> Optional[int]:
    """The engine-loop trace id active on THIS thread, or None. Observe
    sites (exemplars) and the log formatter read through this instead of
    touching the thread-local's unguaranteed attributes."""
    return getattr(FRAME_CONTEXT, "trace_id", None)


def current_tenant() -> Optional[str]:
    """The tenant of the frame active on this thread, or None."""
    return getattr(FRAME_CONTEXT, "tenant", None)


def trace_to_dict(ctx: TraceContext, e2e_s: float) -> Dict[str, Any]:
    return {
        "trace_id": f"{ctx.trace_id:016x}",
        "ingest_ns": ctx.ingest_ns,
        "e2e_seconds": e2e_s,
        "hops": [
            {"stage": h.stage, "recv_ns": h.recv_ns, "send_ns": h.send_ns}
            for h in ctx.hops
        ],
    }


class FlightRecorder:
    def __init__(self, max_slowest: int = 32, max_sampled: int = 128,
                 sample_every: int = 64) -> None:
        self._lock = threading.Lock()
        self._max_slowest = max(1, max_slowest)
        self._sample_every = max(1, sample_every)
        # heap entries carry a tiebreak counter: equal-e2e dicts must never
        # be compared by heapq
        self._tiebreak = itertools.count()
        self._slowest: List[tuple] = []  # min-heap of (e2e_s, n, trace_dict)
        self._sampled: deque = deque(maxlen=max(1, max_sampled))
        self._completed = 0
        self._last_trace_id: Optional[str] = None

    def record(self, ctx: TraceContext, e2e_s: float) -> None:
        entry = trace_to_dict(ctx, e2e_s)
        with self._lock:
            self._completed += 1
            self._last_trace_id = entry["trace_id"]
            if len(self._slowest) < self._max_slowest:
                heapq.heappush(self._slowest,
                               (e2e_s, next(self._tiebreak), entry))
            elif e2e_s > self._slowest[0][0]:
                heapq.heapreplace(self._slowest,
                                  (e2e_s, next(self._tiebreak), entry))
            if self._completed % self._sample_every == 1 or self._sample_every == 1:
                self._sampled.append(entry)

    @property
    def completed(self) -> int:
        with self._lock:
            return self._completed

    @property
    def last_trace_id(self) -> Optional[str]:
        """Most recently completed trace id (health events attach it so an
        operator can jump from a transition straight to a trace)."""
        with self._lock:
            return self._last_trace_id

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            slowest = [e[2] for e in sorted(self._slowest,
                                            key=lambda e: -e[0])]
            sampled = list(self._sampled)
            completed = self._completed
        return {"completed": completed, "slowest": slowest,
                "sampled": sampled}

    def chrome_events(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto / chrome://tracing loadable)."""
        snap = self.snapshot()
        seen = set()
        events: List[Dict[str, Any]] = []
        for trace in snap["slowest"] + snap["sampled"]:
            if trace["trace_id"] in seen:
                continue
            seen.add(trace["trace_id"])
            pid = int(trace["trace_id"], 16) % (1 << 31)
            events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"trace {trace['trace_id']}"},
            })
            prev_send = trace["ingest_ns"]
            for hop in trace["hops"]:
                if hop["recv_ns"] > prev_send:
                    events.append({
                        "name": "transit", "cat": "pipeline", "ph": "X",
                        "pid": pid, "tid": 0,
                        "ts": prev_send / 1000.0,
                        "dur": (hop["recv_ns"] - prev_send) / 1000.0,
                    })
                events.append({
                    "name": hop["stage"], "cat": "pipeline", "ph": "X",
                    "pid": pid, "tid": 0,
                    "ts": hop["recv_ns"] / 1000.0,
                    "dur": max(0, hop["send_ns"] - hop["recv_ns"]) / 1000.0,
                    "args": {"trace_id": trace["trace_id"]},
                })
                prev_send = hop["send_ns"]
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> None:
        with self._lock:
            self._slowest.clear()
            self._sampled.clear()
            self._completed = 0
            self._last_trace_id = None
